"""TPUJob reconciler: CR -> gang admission -> pods -> lifecycle.

First-party heir of the external tf-operator binary the reference only
*deployed* (kubeflow/core/tf-job-operator.libsonnet:61-125): watches
TPUJob CRs, gang-admits them onto slice inventory, creates the headless
Service + one pod per slice host with the rendezvous env injected
(the TF_CONFIG analogue, see runtime/bootstrap.py), and drives the
status state machine:

    Queued -> Starting -> Running -> Succeeded | Failed

Failure semantics fix the reference's two warts (SURVEY.md §5):
  - any worker failure or disappearance (preemption) restarts the WHOLE
    gang from checkpoint, bounded by restartPolicy.maxRestarts — replacing
    per-pod `restartPolicy: OnFailure` and the launcher's sleep-forever
    hack (tf-controller-examples/tf-cnn/launcher.py:86-90);
  - success is "all workers succeeded", not a chief heuristic
    (kubeflow/tf-job/tf-job.libsonnet:39-44) — SPMD workers exit together.

Level-triggered: ``reconcile_once`` is idempotent and polls, like
controller-runtime; no watch plumbing to mock in tests.

Multi-tenant mode: when a
:class:`~kubeflow_tpu.scheduler.queue.ClusterScheduler` is attached,
``reconcile_all`` consults it for an admission :class:`Plan` instead
of offering CRs to the gang in listing order — quotas, weighted-fair
ordering, priority classes, backfill, and preemption all live in that
policy layer (kubeflow_tpu/scheduler/).  A ``preempt`` verdict drives
the ``Preempting`` phase here: the victim keeps its pods and claim
for a checkpoint grace window (policy clock, skewable), then the gang
is torn down through the same machinery a worker failure uses and the
job re-queues flagged ``resumable`` — on re-admission the trainer's
``CheckpointManager.restore_or_init`` continues from the latest saved
step instead of step 0.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Dict, List, Optional

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.gang import GangScheduler, NodeQuarantine
from kubeflow_tpu.operator.kube import (
    FAILED,
    PENDING,
    RUNNING,
    SUCCEEDED,
    Conflict,
    FakeKube,
    NotFound,
)
from kubeflow_tpu.runtime import bootstrap, tracing
from kubeflow_tpu.scheduler import colocate, fuse
from kubeflow_tpu.testing import faults

log = logging.getLogger(__name__)

COORDINATOR_PORT = 8476
LABEL_JOB = "kubeflow-tpu.org/job-name"
LABEL_INDEX = "kubeflow-tpu.org/worker-index"

QUEUED = "Queued"
STARTING = "Starting"
JOB_RUNNING = "Running"
JOB_PREEMPTING = "Preempting"
JOB_SUCCEEDED = "Succeeded"
JOB_FAILED = "Failed"
TERMINAL = (JOB_SUCCEEDED, JOB_FAILED)


def worker_name(job: str, index: int) -> str:
    return f"{job}-worker-{index}"


def coordinator_address(job: crd.TPUJobSpec) -> str:
    """Stable DNS via the headless Service — the openmpi hostfile trick
    (kubeflow/openmpi/assets.libsonnet:30-35) minus the hostfile."""
    return (f"{worker_name(job.name, 0)}.{job.name}.{job.namespace}"
            f":{COORDINATOR_PORT}")


def build_headless_service(job: crd.TPUJobSpec) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {
            "name": job.name,
            "namespace": job.namespace,
            "labels": {LABEL_JOB: job.name},
        },
        "spec": {
            "clusterIP": "None",  # headless: per-pod DNS records
            "selector": {LABEL_JOB: job.name},
            "ports": [{"name": "coordinator", "port": COORDINATOR_PORT}],
        },
    }


def build_worker_pod(job: crd.TPUJobSpec, index: int,
                     avoid_nodes: Optional[List[str]] = None) -> dict:
    topo = job.topology
    hosts_per_slice = topo.hosts
    slice_id = index // hosts_per_slice
    env = {
        bootstrap.ENV_COORDINATOR: coordinator_address(job),
        bootstrap.ENV_NUM_PROCESSES: str(job.num_workers),
        bootstrap.ENV_PROCESS_ID: str(index),
        bootstrap.ENV_JOB_NAME: job.name,
        bootstrap.ENV_SLICE_TYPE: job.slice_type,
        **job.worker.env,
    }
    if job.num_slices > 1:
        env[bootstrap.ENV_MEGASCALE_SLICES] = str(job.num_slices)
        env["MEGASCALE_SLICE_ID"] = str(slice_id)
    if topo.is_cpu:
        # CPU gang (cpu-N slice): schedulable anywhere, no TPU resource —
        # the reference's minikube CPU TFJob shape.
        resources = {"requests": {"cpu": "1", "memory": "1Gi"}}
    else:
        resources = {
            "limits": {"google.com/tpu": str(topo.chips_per_host)},
            "requests": {"google.com/tpu": str(topo.chips_per_host)},
        }
    container = {
        "name": "worker",
        "image": job.worker.image,
        "env": [{"name": k, "value": v} for k, v in sorted(env.items())],
        "resources": resources,
        "ports": [{"containerPort": COORDINATOR_PORT}],
    }
    if job.worker.command:
        container["command"] = list(job.worker.command)
    if job.worker.args:
        container["args"] = list(job.worker.args)
    if job.worker.working_dir:
        container["workingDir"] = job.worker.working_dir
    spec: dict = {
        "restartPolicy": "Never",  # gang restart is the operator's job
        "hostname": worker_name(job.name, index),
        "subdomain": job.name,  # -> {pod}.{job}.{ns} DNS
        "nodeSelector": topo.k8s_node_selector(),
        "containers": [container],
    }
    if avoid_nodes:
        # Quarantined (flapping) nodes: hard anti-affinity, so the
        # k8s scheduler cannot land a fresh gang on the host that just
        # ate the previous one's restart budget.
        spec["affinity"] = {"nodeAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": {
                "nodeSelectorTerms": [{"matchExpressions": [{
                    "key": "kubernetes.io/hostname",
                    "operator": "NotIn",
                    "values": sorted(avoid_nodes),
                }]}],
            },
        }}
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": worker_name(job.name, index),
            "namespace": job.namespace,
            "labels": {
                LABEL_JOB: job.name,
                LABEL_INDEX: str(index),
            },
        },
        "spec": spec,
    }


class TPUJobController:
    def __init__(self, kube: FakeKube, scheduler: GangScheduler,
                 cluster=None,
                 quarantine: Optional[NodeQuarantine] = None):
        self.kube = kube
        self.scheduler = scheduler
        # Optional policy layer (scheduler.ClusterScheduler): when set,
        # admission order/quotas/priorities/preemption come from its
        # per-pass Plan instead of gang FIFO.
        self.cluster = cluster
        # Bad-node attribution: repeated WorkerFailed pods on one node
        # quarantine it (excluded from placement for a cooldown) so a
        # flapping host stops eating gangs' restart budgets.
        self.quarantine = quarantine or NodeQuarantine()
        # (job, pod, restart-generation) triples already attributed: a
        # real apiserver keeps listing a Failed pod (deletion grace)
        # for sweeps after the restart, and re-counting the SAME
        # failure each sweep would quarantine a node off one incident.
        self._attributed: Dict[str, set] = {}
        # Transient per-job bookkeeping (admission timestamps for the
        # gang-schedule-to-running metric; restart counts live in status).
        self._admitted_at: Dict[str, float] = {}
        # Preemption grace deadlines on the policy clock, keyed by job.
        self._preempt_deadline: Dict[str, float] = {}
        # Serving claims seen (scheduler/colocate.py): their gang
        # claims are released when the CR vanishes — scale-to-zero
        # deletes the claim CR outright instead of resizing to 0.
        self._serving_claims: set = set()
        # Live speculative-placement pods by claim key ->
        # {(namespace, pod name)}; retired once the claim is fully
        # granted or gone.
        self._prepull: Dict[str, set] = {}
        # Job-lifecycle traces (runtime/tracing.py): one trace per job,
        # a span per phase dwelled in, the root stamped at the terminal
        # transition (tail sampling then always keeps Failed jobs).
        self._job_traces: Dict[str, dict] = {}
        self.metrics: List[dict] = []

    # -- main loop --------------------------------------------------------

    def run(self, poll_interval_s: float = 2.0, max_iterations: int = 0):
        i = 0
        while True:
            self.reconcile_all()
            i += 1
            if max_iterations and i >= max_iterations:
                return
            time.sleep(poll_interval_s)

    def reconcile_all(self) -> None:
        from kubeflow_tpu.runtime.prom import REGISTRY

        crs = [cr for cr in self.kube.list_custom()
               if cr.get("kind") == crd.KIND]
        decisions: dict = {}
        order: Dict[str, int] = {}
        plan_obj = None
        if self.cluster is not None:
            try:
                plan = self.cluster.plan(crs)
                plan_obj = plan
                decisions = plan.decisions
                order = {key: i for i, key in enumerate(plan.order)}
            except Exception:
                # A wedged policy pass (scheduler.admit fault, config
                # bug) must not stop already-admitted gangs from being
                # reconciled: fall back to no-decision, which keeps
                # running jobs running and pending jobs Queued.
                log.exception("scheduler plan failed; holding queue")
                REGISTRY.counter(
                    "kft_scheduler_plan_errors_total",
                    "admission-plan passes that raised",
                ).inc()

        def cr_key(cr_obj: dict) -> str:
            meta = cr_obj.get("metadata", {})
            return (f"{meta.get('namespace', 'kubeflow')}/"
                    f"{meta.get('name', '')}")

        # Plan order first (admissions land exactly as simulated),
        # then everything the plan didn't rank, in listing order.
        if order:
            crs.sort(key=lambda cr: order.get(cr_key(cr), len(order)))

        if plan_obj is not None:
            # Speculative placement: pin prepull pods to the nodes the
            # plan just decided will free, in the SAME sweep that
            # starts the victims' drains.
            try:
                self._sync_prepull(plan_obj, crs, cr_key)
            except Exception:
                log.exception("prepull sync failed")

        phases: dict = {}
        for cr_obj in crs:
            try:
                phase = self.reconcile_once(
                    cr_obj, decision=decisions.get(cr_key(cr_obj)))
                phases[phase] = phases.get(phase, 0) + 1
            except ValueError as e:  # SpecError + topology parse errors
                self._set_phase(cr_obj, JOB_FAILED, reason="InvalidSpec",
                                message=str(e))
                phases[JOB_FAILED] = phases.get(JOB_FAILED, 0) + 1
            except Exception:
                log.exception(
                    "reconcile of %s failed", cr_obj["metadata"]["name"]
                )
                REGISTRY.counter(
                    "kft_operator_reconcile_errors_total",
                    "reconcile passes that raised",
                ).inc()
        REGISTRY.counter(
            "kft_operator_reconcile_passes_total",
            "full reconcile sweeps over all TPUJobs",
        ).inc()
        # Serving claims whose CR vanished (scale-to-zero, kubectl
        # delete): release their chips so pending training backfills
        # this sweep, not next.
        live_claim_keys = {cr_key(cr) for cr in crs}
        for skey in [k for k in self._serving_claims
                     if k not in live_claim_keys]:
            self.scheduler.release(skey)
            self._serving_claims.discard(skey)
            if self.cluster is not None:
                self.cluster.forget(skey)
        # Trace state of jobs whose CR vanished pre-terminal (kubectl
        # delete mid-run) would otherwise accumulate forever — no
        # terminal transition will ever prune them.  Keys come from
        # the SAME helper _set_phase stamps with (namespace default
        # 'default', NOT cr_key's 'kubeflow') or a defaulted-namespace
        # job's live trace would be wiped every sweep.
        live_keys = {
            self._trace_key(cr.get("metadata", {})) for cr in crs}
        for key in [k for k in self._job_traces
                    if k not in live_keys]:
            del self._job_traces[key]
        gauge = REGISTRY.gauge(
            "kft_operator_jobs", "TPUJobs by phase at last sweep")
        for phase in (QUEUED, STARTING, JOB_RUNNING, JOB_PREEMPTING,
                      JOB_SUCCEEDED, JOB_FAILED):
            gauge.set(phases.get(phase, 0), phase=phase)
        REGISTRY.gauge(
            "kft_operator_quarantined_nodes",
            "nodes excluded from gang placement for flapping workers",
        ).set(len(self.quarantine.quarantined()))

    # -- single-job reconcile --------------------------------------------

    def reconcile_once(self, cr_obj: dict, decision=None) -> str:
        """Reconcile one CR dict; returns the resulting phase.

        ``decision`` is this job's verdict from the cluster
        scheduler's plan (None when no policy layer is attached, or
        for CRs the plan could not parse): ``admit`` gates the gang
        offer, ``wait``/``unsatisfiable`` replace the FIFO queue
        semantics, ``preempt`` drives the grace-window eviction.
        """
        job = crd.TPUJobSpec.from_custom_resource(cr_obj)
        status = cr_obj.get("status", {}) or {}
        phase = status.get("phase", "")
        key = f"{job.namespace}/{job.name}"

        if phase in TERMINAL:
            self.scheduler.release(key)
            self._preempt_deadline.pop(key, None)
            self._attributed.pop(key, None)
            if self.cluster is not None:
                self.cluster.forget(key)
            return phase

        # Serving claims (scheduler/colocate.py): the fleet
        # autoscaler's desired-replica count riding the TPUJob shape.
        # No pods or service — the grant is a gang claim plus a
        # Deployment scale patch; the serving replicas themselves live
        # under the Deployment.
        if colocate.is_serving_claim_cr(cr_obj):
            return self._reconcile_serving_claim(
                cr_obj, job, status, phase, key, decision)

        # Fused members (scheduler/fuse.py): the plan mirrored the
        # gang's verdict onto this member key; one shared pod gang is
        # driven under the fused claim while each member CR keeps its
        # own phase, events, restart budget and resumable flag.
        if decision is not None and decision.fused_gang:
            return self._reconcile_fused_member(
                cr_obj, job, status, phase, key, decision)

        # 0. Preemption: a higher-priority job needs this gang's
        # slices.  Grace window first (checkpoint-on-SIGTERM
        # contract), teardown + resumable re-queue after.  A gang
        # that FINISHES during the grace is a completion, not an
        # eviction — without this check the preempt branch would
        # return before pod observation every pass, then tear down
        # and pointlessly re-run an already-succeeded job.
        if decision is not None and decision.action == "preempt" \
                and self.scheduler.admitted(key):
            pods = self.kube.list_pods(job.namespace,
                                       labels={LABEL_JOB: job.name})
            done = [(p.get("status") or {}).get("phase", PENDING)
                    for p in pods]
            if len(pods) == job.num_workers and all(
                    ph == SUCCEEDED for ph in done):
                self._set_phase(cr_obj, JOB_SUCCEEDED,
                                reason="AllWorkersDone",
                                message="gang completed during "
                                        "preemption grace")
                self.scheduler.release(key)
                self._admitted_at.pop(key, None)
                self._preempt_deadline.pop(key, None)
                if self.cluster is not None:
                    self.cluster.forget(key)
                return JOB_SUCCEEDED
            if any(ph == FAILED for ph in done):
                # The gang DIED during the grace: nothing is
                # checkpointing, so the window buys nobody anything —
                # cut it short, count the failure against the restart
                # budget exactly like a WorkerFailed restart would,
                # and hand the slices over now.
                restarts = int(status.get("restarts", 0))
                self._preempt_deadline.pop(key, None)
                self._note_worker_failures(job, pods, restarts)
                self._teardown_pods(job)
                self.scheduler.release(key)
                self._admitted_at.pop(key, None)
                if restarts + 1 > job.restart.max_restarts:
                    self._set_phase(
                        cr_obj, JOB_FAILED,
                        reason="MaxRestartsExceeded",
                        message=(f"{done.count(FAILED)} worker(s) "
                                 f"failed during preemption grace; "
                                 f"restarts={restarts}"),
                        extra={"restarts": restarts})
                    if self.cluster is not None:
                        self.cluster.forget(key)
                    return JOB_FAILED
                if self.cluster is not None:
                    self.cluster.note_preempted(key)
                self.kube.record_event(
                    job.namespace, f"TPUJob/{job.name}",
                    "WorkerFailed",
                    f"{done.count(FAILED)} worker(s) failed during "
                    f"preemption grace; grace cut short, gang restart "
                    f"{restarts + 1}/{job.restart.max_restarts} on "
                    f"re-admission", type_="Warning")
                self._set_phase(
                    cr_obj, QUEUED, reason="PreemptedRequeued",
                    message="gang failed during preemption grace; "
                            "resumes from latest checkpoint",
                    extra={"resumable": True,
                           "restarts": restarts + 1})
                return QUEUED
            return self._preempt(cr_obj, job, status, decision)

        # 1. Gang admission (all slices or nothing).
        if decision is None and self.cluster is not None:
            # Policy mode, but the plan had no verdict for this job
            # (plan pass failed, or the CR appeared mid-pass).  Never
            # fall through to the gang FIFO — that would bypass every
            # quota/priority rule.  Admitted jobs keep running; the
            # rest hold for the next plan.
            if not self.scheduler.admitted(key):
                if phase != QUEUED:
                    self._set_phase(
                        cr_obj, QUEUED, reason="WaitingForScheduler",
                        message="no admission verdict this pass")
                return QUEUED
            if phase == JOB_PREEMPTING:
                # Mid-grace victim with no verdict this pass: hold the
                # eviction state; the next healthy plan re-issues the
                # preempt decision and the grace deadline persists.
                return JOB_PREEMPTING
            admitted = True
        elif decision is None:
            admitted = self.scheduler.offer(
                key, job.slice_type, job.num_slices,
                queue=job.queue or "default"
            )
        elif self.scheduler.admitted(key):
            if phase == JOB_PREEMPTING and \
                    self._preempt_deadline.pop(key, None) is not None:
                # The plan withdrew the eviction (shortage resolved
                # mid-grace): the gang was never torn down, so it just
                # keeps running; a future eviction starts a new grace.
                # Revert the eviction stamps — the job was never
                # actually preempted, so neither the resumable flag
                # nor the preemption count may survive (the next
                # _set_phase below persists the corrected status).
                status = dict(status)
                status["resumable"] = False
                status["preemptions"] = max(
                    0, int(status.get("preemptions", 1)) - 1)
                cr_obj["status"] = status
                self.kube.record_event(
                    job.namespace, f"TPUJob/{job.name}",
                    "PreemptionCancelled", decision.message)
            admitted = True
        elif decision.action == "admit":
            # The plan validated capacity against the same gang
            # snapshot in this reconcile pass, so the offer admits
            # immediately — the gang's own FIFO queue stays empty in
            # policy mode.
            admitted = self.scheduler.offer(
                key, job.slice_type, job.num_slices,
                queue=job.queue or "default")
            if admitted and self.cluster is not None:
                self.cluster.note_admitted(
                    key, backfilled=decision.backfilled,
                    resumed=bool(status.get("resumable")))
                if status.get("resumable"):
                    # The flag is CONSUMED by this resume admission:
                    # a later ordinary gang restart must not count as
                    # another resume.  `preemptions` stays — that one
                    # is history.  Persisted by the _set_phase the
                    # materialize step below is guaranteed to make
                    # (phase was Queued).
                    status = dict(status)
                    status["resumable"] = False
                    cr_obj["status"] = status
        elif decision.action == "unsatisfiable":
            self._set_phase(cr_obj, JOB_FAILED,
                            reason=decision.reason or
                            "UnsatisfiableResources",
                            message=decision.message)
            self.scheduler.release(key)
            if self.cluster is not None:
                self.cluster.forget(key)
            return JOB_FAILED
        else:
            admitted = False
        if not admitted:
            if decision is not None:
                reason = decision.reason or "WaitingForSlices"
                if phase != QUEUED or status.get("reason") != reason:
                    self._set_phase(cr_obj, QUEUED, reason=reason,
                                    message=decision.message)
                return QUEUED
            if self.scheduler.unsatisfiable(key):
                # Demand exceeds total inventory: it can NEVER run.  Fail
                # fast with a clear message and release the queue slot so
                # jobs behind it in the FIFO are not wedged forever.
                self._set_phase(
                    cr_obj, JOB_FAILED, reason="UnsatisfiableResources",
                    message=(
                        f"requires {job.num_slices} x {job.slice_type} but "
                        f"cluster capacity is "
                        f"{self.scheduler.capacity.get(job.slice_type, 0)}"
                    ),
                )
                self.scheduler.release(key)
                return JOB_FAILED
            if phase != QUEUED:
                self._set_phase(cr_obj, QUEUED, reason="WaitingForSlices",
                                message=f"queue position "
                                        f"{self.scheduler.position(key)}")
            return QUEUED
        self._admitted_at.setdefault(key, faults.monotonic())

        # 2. Materialize service + pods (idempotent).
        try:
            self.kube.create_service(build_headless_service(job))
        except Conflict:
            pass
        existing = {
            p["metadata"]["name"]: p
            for p in self.kube.list_pods(job.namespace,
                                         labels={LABEL_JOB: job.name})
        }
        restarts = int(status.get("restarts", 0))
        avoid_nodes = self.quarantine.quarantined()
        for i in range(job.num_workers):
            name = worker_name(job.name, i)
            if name not in existing:
                if phase == JOB_RUNNING:
                    # A pod vanished mid-run (preemption/node loss):
                    # that's a gang failure, not a hole to patch.
                    return self._gang_restart(
                        cr_obj, job, restarts,
                        reason="WorkerLost",
                        message=f"{name} disappeared while Running",
                    )
                try:
                    self.kube.create_pod(
                        build_worker_pod(job, i, avoid_nodes))
                except Conflict:
                    pass

        # 3. Observe the gang.
        pods = self.kube.list_pods(job.namespace, labels={LABEL_JOB: job.name})
        phases = [(p.get("status") or {}).get("phase", PENDING)
                  for p in pods]
        if any(ph == FAILED for ph in phases):
            return self._gang_restart(
                cr_obj, job, restarts, reason="WorkerFailed",
                message=f"{phases.count(FAILED)} worker(s) failed",
            )
        if len(pods) == job.num_workers and all(
                ph == SUCCEEDED for ph in phases):
            self._set_phase(cr_obj, JOB_SUCCEEDED, reason="AllWorkersDone",
                            message="gang completed")
            self.scheduler.release(key)
            self._admitted_at.pop(key, None)
            return JOB_SUCCEEDED
        if len(pods) == job.num_workers and all(
                ph in (RUNNING, SUCCEEDED) for ph in phases):
            if phase != JOB_RUNNING:
                latency = faults.monotonic() - self._admitted_at.get(
                    key, faults.monotonic())
                self.metrics.append({
                    "event": "gang_running", "job": key,
                    "schedule_to_running_s": latency,
                })
                from kubeflow_tpu.runtime.prom import REGISTRY

                # The BASELINE north-star, scrapeable: p50 comes from
                # the histogram on the operator's --metrics-port.
                # Buckets sized for gang startup (image pull + TPU node
                # provisioning: seconds to minutes), not request
                # latency — the registry caches the first registration,
                # so defaults here could never be widened later.
                REGISTRY.histogram(
                    "kft_gang_schedule_to_running_seconds",
                    "gang admission to all-workers-running latency",
                    buckets=(1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0,
                             300.0, 600.0),
                ).observe(latency)
                self._set_phase(cr_obj, JOB_RUNNING, reason="GangRunning",
                                message="all workers running",
                                extra={"restarts": restarts})
            return JOB_RUNNING
        if phase != STARTING or status.get("restarts") != restarts:
            self._set_phase(cr_obj, STARTING, reason="CreatingWorkers",
                            message=f"{phases.count(RUNNING)}/"
                                    f"{job.num_workers} running",
                            extra={"restarts": restarts})
        return STARTING

    # -- serving claims (train/serve colocation) ---------------------------

    def _reconcile_serving_claim(self, cr_obj: dict,
                                 job: crd.TPUJobSpec, status: dict,
                                 phase: str, key: str,
                                 decision) -> str:
        """Drive one ServingClaim CR through the shared-pool arbiter.

        Grants and grows go through the plan verdict (which may have
        preempted training to make room); shrinks release in place
        with no arbitration — freed slices backfill pending training
        the same sweep (``GangScheduler.resize`` re-drains the FIFO).
        The granted count is patched onto the Deployment's
        ``spec.replicas`` HERE, keeping every chip movement inside the
        reconcile loop; the autoscaler only ever writes desire into
        the claim CR.
        """
        desired = job.num_slices
        labels = (cr_obj.get("metadata") or {}).get("labels") or {}
        deployment = labels.get(colocate.LABEL_DEPLOYMENT, "")
        self._serving_claims.add(key)
        admitted = self.scheduler.admitted(key)
        held = self.scheduler.claim_count(key)
        denied = False
        reason = message = ""

        if admitted and desired < held:
            self.scheduler.resize(key, desired)
            held = desired
            self.kube.record_event(
                job.namespace, f"TPUJob/{job.name}", "ClaimShrunk",
                f"serving claim released {held} -> {desired} slices; "
                f"training backfills")
        elif admitted and desired > held:
            if decision is not None and decision.action == "admit":
                if self.scheduler.resize(key, desired):
                    if self.cluster is not None:
                        # Clear the grow-delta queue entry and record
                        # its wait in the CLI window.
                        self.cluster.queue.note_admitted(
                            key + colocate.GROW_SUFFIX)
                    held = desired
            elif decision is not None:
                reason = decision.reason or ""
                message = decision.message
                denied = (decision.action == "unsatisfiable"
                          or reason == "PreemptionRateLimited")
        elif not admitted:
            if decision is not None and decision.action == "admit":
                admitted = self.scheduler.offer(
                    key, job.slice_type, desired, queue="serving")
                if admitted:
                    held = desired
                    if self.cluster is not None:
                        self.cluster.note_admitted(
                            key, backfilled=decision.backfilled)
            elif decision is not None:
                reason = decision.reason or ""
                message = decision.message
                denied = (decision.action == "unsatisfiable"
                          or reason == "PreemptionRateLimited")
            elif self.cluster is None:
                # No policy layer: claims fall back to gang FIFO like
                # any job (--no-scheduler operators still colocate).
                admitted = self.scheduler.offer(
                    key, job.slice_type, desired, queue="serving")
                if admitted:
                    held = desired

        granted = self.scheduler.claim_count(key)
        if deployment and granted > 0:
            # Patch only on grant/resize; a claim pending its FIRST
            # grant must not scale the deployment down to zero.
            try:
                dep = self.kube.get_deployment(job.namespace,
                                               deployment)
                current = int(
                    (dep.get("spec") or {}).get("replicas", 0) or 0)
                if current != granted:
                    self.kube.patch_deployment_scale(
                        job.namespace, deployment, granted)
                    self.kube.record_event(
                        job.namespace, f"Deployment/{deployment}",
                        "ServingScaled",
                        f"claim {key}: {current} -> {granted} "
                        f"replicas")
            except NotFound:
                pass

        pool = (self.cluster.pool_status()
                if self.cluster is not None else None)
        if granted >= desired and granted > 0:
            new_phase, new_reason = JOB_RUNNING, "ClaimGranted"
            message = f"{granted}/{desired} replicas granted"
        elif admitted:
            new_phase = STARTING
            new_reason = reason or "ClaimGrowing"
            message = message or (f"{granted}/{desired} replicas "
                                  f"granted")
        else:
            new_phase = QUEUED
            new_reason = reason or "WaitingForSlices"
        extra: dict = {"grantedReplicas": granted, "denied": denied}
        if pool is not None:
            extra["pool"] = pool
        if (phase != new_phase or status.get("reason") != new_reason
                or int(status.get("grantedReplicas", -1) or 0)
                != granted
                or bool(status.get("denied")) != denied):
            self._set_phase(cr_obj, new_phase, reason=new_reason,
                            message=message, extra=extra)
        elif pool is not None and status.get("pool") != pool:
            # Pool accounting moved but the verdict didn't: refresh
            # the stamp without minting an event per sweep.
            new_status = dict(status)
            new_status["pool"] = pool
            cr_obj["status"] = new_status
            self.kube.update_custom_status(
                job.namespace, job.name, new_status)
        return new_phase

    def _sync_prepull(self, plan, crs: List[dict], cr_key) -> None:
        """Speculative placement (arXiv 2010.11307): pin prepull pods
        to the nodes of victims evicted FOR a serving claim, so the
        replica image pull overlaps the victim's drain; retired once
        the claim is fully granted (or its CR vanished).  Fused-gang
        victims are skipped — their key is not a CR key, and their
        members' pods ride the gang name."""
        by_key = {cr_key(cr): cr for cr in crs}
        for victim, preemptor in plan.preemptions:
            claim_cr = by_key.get(preemptor)
            if claim_cr is None or \
                    not colocate.is_serving_claim_cr(claim_cr):
                continue
            victim_cr = by_key.get(victim)
            if victim_cr is None:
                continue
            vmeta = victim_cr.get("metadata", {})
            vns = vmeta.get("namespace", "kubeflow")
            cmeta = claim_cr.get("metadata", {})
            cns = cmeta.get("namespace", "kubeflow")
            cname = cmeta.get("name", "")
            image = (((claim_cr.get("spec") or {}).get("worker")
                      or {}).get("image")
                     or colocate.DEFAULT_SERVING_IMAGE)
            nodes = set()
            for pod in self.kube.list_pods(
                    vns, labels={LABEL_JOB: vmeta.get("name", "")}):
                node = (pod.get("spec") or {}).get("nodeName")
                if node:
                    nodes.add(node)
            for node in sorted(nodes):
                pod = colocate.build_prepull_pod(cns, cname, node,
                                                 image)
                try:
                    self.kube.create_pod(pod)
                except Conflict:
                    pass
                self._prepull.setdefault(preemptor, set()).add(
                    (cns, pod["metadata"]["name"]))
        for ckey in list(self._prepull):
            claim_cr = by_key.get(ckey)
            done = claim_cr is None
            if not done:
                want = int((claim_cr.get("spec") or {})
                           .get("numSlices", 0) or 0)
                done = self.scheduler.claim_count(ckey) >= want
            if done:
                for ns, name in self._prepull.pop(ckey):
                    try:
                        self.kube.delete_pod(ns, name)
                    except NotFound:
                        pass

    # -- fused gangs -------------------------------------------------------

    def _fused_gang_spec(self, job: crd.TPUJobSpec,
                         decision) -> crd.TPUJobSpec:
        """The shared workload spec for a fused gang: the member's spec
        renamed to the gang's pod/service-safe name, with the member
        roster injected so the worker entrypoint can build its
        FusedTrainer member array."""
        member_names = ",".join(
            k.split("/", 1)[-1] for k in decision.fused_members)
        worker = dataclasses.replace(
            job.worker,
            env={**job.worker.env, "KFT_FUSED_MEMBERS": member_names})
        return dataclasses.replace(
            job, name=fuse.fused_gang_name(decision.fused_gang),
            worker=worker)

    def _reconcile_fused_member(self, cr_obj: dict, job: crd.TPUJobSpec,
                                status: dict, phase: str, key: str,
                                decision) -> str:
        """Drive one member CR of a fused gang.

        The gang claim, pod set and grace deadline are keyed on the
        FUSED key and shared by every member; each member CR keeps its
        own phase/events/restart budget/resumable flag.  Every step is
        idempotent, so whichever member reconciles first performs the
        shared action (offer, pod creation, teardown) and its peers
        observe the result in the same sweep.
        """
        gkey = decision.fused_gang
        gang = self._fused_gang_spec(job, decision)
        admitted = self.scheduler.admitted(gkey)
        pods = self.kube.list_pods(job.namespace,
                                   labels={LABEL_JOB: gang.name})
        pod_phases = [(p.get("status") or {}).get("phase", PENDING)
                      for p in pods]

        # Completion first: it outranks both preemption (a gang that
        # finishes during the grace is a completion, not an eviction)
        # and the post-release sweep order (a peer may have released
        # the claim moments ago).
        if pods and len(pods) == gang.num_workers and all(
                ph == SUCCEEDED for ph in pod_phases):
            self.kube.record_event(
                job.namespace, f"TPUJob/{job.name}",
                "FusedMemberCompleted",
                f"fused gang {gkey} completed; member done")
            self._set_phase(cr_obj, JOB_SUCCEEDED,
                            reason="AllWorkersDone",
                            message=f"fused gang {gkey} completed")
            if admitted:
                self.scheduler.release(gkey)
                self._admitted_at.pop(gkey, None)
                self._preempt_deadline.pop(gkey, None)
            if self.cluster is not None:
                self.cluster.forget(key)
            return JOB_SUCCEEDED

        if decision.action == "preempt":
            if not admitted:
                # A peer already tore the gang down this sweep.
                if phase != QUEUED:
                    if self.cluster is not None:
                        self.cluster.note_preempted(key)
                    self._set_phase(
                        cr_obj, QUEUED, reason="PreemptedRequeued",
                        message="fused gang preempted; member resumes "
                                "from its own checkpoint",
                        extra={"resumable": True, "fusedGang": "",
                               "fusedMembers": 0})
                return QUEUED
            now = faults.monotonic()
            grace = (self.cluster.config.preemption.grace_period_s
                     if self.cluster is not None else 0.0)
            if decision.grace_s >= 0:
                grace = decision.grace_s
            deadline = self._preempt_deadline.setdefault(
                gkey, now + grace)
            if phase != JOB_PREEMPTING:
                preemptions = int(status.get("preemptions", 0))
                self.kube.record_event(
                    job.namespace, f"TPUJob/{job.name}", "Preempted",
                    f"{decision.message}; checkpoint grace {grace:g}s",
                    type_="Warning")
                self._set_phase(
                    cr_obj, JOB_PREEMPTING, reason="Preempted",
                    message=(f"{decision.message}; "
                             f"checkpoint grace {grace:g}s"),
                    extra={"resumable": True,
                           "preemptions": preemptions + 1})
            if now < deadline:
                return JOB_PREEMPTING
            # Grace spent: THIS member performs the shared teardown;
            # peers requeue through the not-admitted branch above.
            self._teardown_pods(gang)
            self.scheduler.release(gkey)
            self._admitted_at.pop(gkey, None)
            self._preempt_deadline.pop(gkey, None)
            if self.cluster is not None:
                self.cluster.note_preempted(key)
            self.metrics.append({"event": "gang_preempted", "job": gkey,
                                 "member": key,
                                 "preemptor": decision.preemptor})
            self._set_phase(
                cr_obj, QUEUED, reason="PreemptedRequeued",
                message="fused gang preempted; member resumes from "
                        "its own checkpoint",
                extra={"resumable": True, "fusedGang": "",
                       "fusedMembers": 0})
            return QUEUED

        if decision.action == "unsatisfiable":
            self._set_phase(cr_obj, JOB_FAILED,
                            reason=decision.reason or
                            "UnsatisfiableResources",
                            message=decision.message)
            if self.cluster is not None:
                self.cluster.forget(key)
            return JOB_FAILED
        if decision.action != "admit":
            reason = decision.reason or "WaitingForSlices"
            if phase != QUEUED or status.get("reason") != reason:
                self._set_phase(cr_obj, QUEUED, reason=reason,
                                message=decision.message)
            return QUEUED

        if admitted and phase == JOB_PREEMPTING:
            # The plan withdrew the gang's eviction mid-grace: every
            # member reverts its own stamps (deadline pop idempotent).
            self._preempt_deadline.pop(gkey, None)
            status = dict(status)
            status["resumable"] = False
            status["preemptions"] = max(
                0, int(status.get("preemptions", 1)) - 1)
            cr_obj["status"] = status
            self.kube.record_event(
                job.namespace, f"TPUJob/{job.name}",
                "PreemptionCancelled", decision.message)
        if not admitted:
            admitted = self.scheduler.offer(
                gkey, job.slice_type, job.num_slices, queue="fused")
            if not admitted:
                if phase != QUEUED:
                    self._set_phase(
                        cr_obj, QUEUED, reason="WaitingForSlices",
                        message=f"fused gang {gkey} awaiting slices")
                return QUEUED
            self._admitted_at.setdefault(gkey, faults.monotonic())
        stamp: dict = {}
        if not status.get("fusedGang"):
            # First admission of THIS member into the gang: count it,
            # consume its resumable flag, stamp the gang reference
            # (persisted by the guaranteed phase transition below).
            if self.cluster is not None:
                self.cluster.note_admitted(
                    key, backfilled=decision.backfilled,
                    resumed=bool(status.get("resumable")))
            self.kube.record_event(
                job.namespace, f"TPUJob/{job.name}",
                "FusedMemberAdmitted",
                f"admitted as member of fused gang {gkey} "
                f"({len(decision.fused_members)} members)")
            stamp = {"fusedGang": gkey,
                     "fusedMembers": len(decision.fused_members),
                     "resumable": False}

        # Materialize the SHARED service + pod gang (idempotent; any
        # member creates, Conflict means a peer won the race).
        try:
            self.kube.create_service(build_headless_service(gang))
        except Conflict:
            pass
        existing = {p["metadata"]["name"] for p in pods}
        restarts = int(status.get("restarts", 0))
        avoid_nodes = self.quarantine.quarantined()
        for i in range(gang.num_workers):
            name = worker_name(gang.name, i)
            if name not in existing:
                if phase == JOB_RUNNING:
                    return self._fused_member_restart(
                        cr_obj, gang, key, gkey, restarts, stamp,
                        reason="WorkerLost",
                        message=f"{name} disappeared while Running")
                try:
                    self.kube.create_pod(
                        build_worker_pod(gang, i, avoid_nodes))
                except Conflict:
                    pass

        pods = self.kube.list_pods(job.namespace,
                                   labels={LABEL_JOB: gang.name})
        pod_phases = [(p.get("status") or {}).get("phase", PENDING)
                      for p in pods]
        if any(ph == FAILED for ph in pod_phases):
            return self._fused_member_restart(
                cr_obj, gang, key, gkey, restarts, stamp,
                reason="WorkerFailed",
                message=f"{pod_phases.count(FAILED)} worker(s) failed")
        if len(pods) == gang.num_workers and all(
                ph in (RUNNING, SUCCEEDED) for ph in pod_phases):
            if phase != JOB_RUNNING:
                latency = faults.monotonic() - self._admitted_at.get(
                    gkey, faults.monotonic())
                self.metrics.append({
                    "event": "gang_running", "job": key,
                    "fused_gang": gkey,
                    "schedule_to_running_s": latency,
                })
                from kubeflow_tpu.runtime.prom import REGISTRY

                REGISTRY.histogram(
                    "kft_gang_schedule_to_running_seconds",
                    "gang admission to all-workers-running latency",
                    buckets=(1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0,
                             300.0, 600.0),
                ).observe(latency)
                self._set_phase(cr_obj, JOB_RUNNING,
                                reason="GangRunning",
                                message=f"fused gang {gkey} running",
                                extra={"restarts": restarts, **stamp})
            return JOB_RUNNING
        if phase != STARTING or status.get("restarts") != restarts \
                or stamp:
            self._set_phase(cr_obj, STARTING, reason="CreatingWorkers",
                            message=f"{pod_phases.count(RUNNING)}/"
                                    f"{gang.num_workers} running in "
                                    f"fused gang {gkey}",
                            extra={"restarts": restarts, **stamp})
        return STARTING

    def _fused_member_restart(self, cr_obj: dict, gang: crd.TPUJobSpec,
                              key: str, gkey: str, restarts: int,
                              stamp: dict, reason: str,
                              message: str) -> str:
        """Member-side view of a fused gang restart: the shared pods
        are torn down once (idempotent), each member charges its OWN
        restart budget, and the runtime re-enters through per-member
        ``restore_or_init`` with only still-active members unmasked."""
        self._note_worker_failures(
            gang, self.kube.list_pods(gang.namespace,
                                      labels={LABEL_JOB: gang.name}),
            restarts)
        if restarts + 1 > gang.restart.max_restarts:
            self._set_phase(cr_obj, JOB_FAILED,
                            reason="MaxRestartsExceeded",
                            message=f"{message}; restarts={restarts}",
                            extra={"restarts": restarts})
            self._teardown_pods(gang)
            self.scheduler.release(gkey)
            self._admitted_at.pop(gkey, None)
            return JOB_FAILED
        self.kube.record_event(
            gang.namespace, f"TPUJob/{key.split('/', 1)[-1]}", reason,
            f"{message}; fused gang restart {restarts + 1}/"
            f"{gang.restart.max_restarts} from per-member checkpoints",
            type_="Warning")
        self._teardown_pods(gang)
        self.metrics.append({"event": "gang_restart", "job": gkey,
                             "member": key, "restart": restarts + 1,
                             "reason": reason})
        self._set_phase(cr_obj, STARTING, reason=reason,
                        message=f"fused gang restart {restarts + 1}",
                        extra={"restarts": restarts + 1, **stamp})
        return STARTING

    # -- helpers ----------------------------------------------------------

    def _preempt(self, cr_obj: dict, job: crd.TPUJobSpec,
                 status: dict, decision) -> str:
        """Drive one job through eviction: grace window, then teardown
        and a ``resumable`` re-queue.

        The grace deadline lives on the skewable policy clock
        (``faults.monotonic``) in controller memory, not CR status —
        it is an operator-process promise (like ``_admitted_at``), and
        an operator restart simply restarts the window, which only
        ever gives the victim MORE time to checkpoint."""
        key = f"{job.namespace}/{job.name}"
        now = faults.monotonic()
        grace = (self.cluster.config.preemption.grace_period_s
                 if self.cluster is not None else 0.0)
        if decision.grace_s >= 0:
            # Per-victim override (scheduler/colocate.py): a serving
            # preemptor drains its victim on the short serving grace.
            grace = decision.grace_s
        deadline = self._preempt_deadline.get(key)
        preemptions = int(status.get("preemptions", 0))
        if deadline is None:
            self._preempt_deadline[key] = now + grace
            self.kube.record_event(
                job.namespace, f"TPUJob/{job.name}", "Preempted",
                f"{decision.message}; checkpoint grace {grace:g}s",
                type_="Warning")
            self._set_phase(
                cr_obj, JOB_PREEMPTING, reason="Preempted",
                message=(f"{decision.message}; "
                         f"checkpoint grace {grace:g}s"),
                extra={"resumable": True,
                       "preemptions": preemptions + 1})
            return JOB_PREEMPTING
        if now < deadline:
            return JOB_PREEMPTING
        # Grace spent: tear the gang down through the same machinery a
        # worker failure uses and hand the slices back.  The job
        # re-queues resumable — its next admission restarts the gang,
        # and the trainer's restore_or_init picks up the latest
        # checkpoint (no step-0 retraining).
        self._teardown_pods(job)
        self.scheduler.release(key)
        self._admitted_at.pop(key, None)
        self._preempt_deadline.pop(key, None)
        if self.cluster is not None:
            self.cluster.note_preempted(key)
        self.metrics.append({"event": "gang_preempted", "job": key,
                             "preemptor": decision.preemptor})
        self._set_phase(
            cr_obj, QUEUED, reason="PreemptedRequeued",
            message="awaiting re-admission; resumes from latest "
                    "checkpoint",
            extra={"resumable": True})
        return QUEUED

    def _note_worker_failures(self, job: crd.TPUJobSpec,
                              pods: List[dict],
                              restarts: int) -> None:
        """Attribute FAILED pods to their nodes; a node that trips the
        quarantine threshold gets one NodeQuarantined event and is
        excluded from placement until its cooldown expires.  Each
        (pod, restart-generation) counts ONCE — a Failed pod lingering
        through its deletion grace must not re-count every sweep."""
        key = f"{job.namespace}/{job.name}"
        seen = self._attributed.setdefault(key, set())
        for pod in pods:
            if (pod.get("status") or {}).get("phase") != FAILED:
                continue
            mark = (pod["metadata"]["name"], restarts)
            if mark in seen:
                continue
            seen.add(mark)
            node = (pod.get("spec") or {}).get("nodeName") or ""
            if self.quarantine.note_failure(node):
                self.kube.record_event(
                    job.namespace, f"node/{node}", "NodeQuarantined",
                    f"{self.quarantine.threshold} worker failures "
                    f"within {self.quarantine.window_s:g}s (last: "
                    f"{pod['metadata']['name']} of {key}); excluded "
                    f"from gang placement for "
                    f"{self.quarantine.cooldown_s:g}s",
                    type_="Warning")
                self.metrics.append({"event": "node_quarantined",
                                     "node": node, "job": key})

    def _gang_restart(self, cr_obj: dict, job: crd.TPUJobSpec,
                      restarts: int, reason: str, message: str) -> str:
        key = f"{job.namespace}/{job.name}"
        self._note_worker_failures(
            job, self.kube.list_pods(job.namespace,
                                     labels={LABEL_JOB: job.name}),
            restarts)
        if restarts + 1 > job.restart.max_restarts:
            self._set_phase(cr_obj, JOB_FAILED, reason="MaxRestartsExceeded",
                            message=f"{message}; restarts={restarts}",
                            extra={"restarts": restarts})
            self._teardown_pods(job)
            self.scheduler.release(key)
            self._admitted_at.pop(key, None)
            return JOB_FAILED
        self.kube.record_event(
            job.namespace, f"TPUJob/{job.name}", reason,
            f"{message}; gang restart {restarts + 1}/"
            f"{job.restart.max_restarts} from checkpoint", type_="Warning",
        )
        self._teardown_pods(job)
        self.metrics.append({"event": "gang_restart", "job": key,
                             "restart": restarts + 1, "reason": reason})
        self._set_phase(cr_obj, STARTING, reason=reason,
                        message=f"gang restart {restarts + 1}",
                        extra={"restarts": restarts + 1})
        return STARTING

    def _teardown_pods(self, job: crd.TPUJobSpec) -> None:
        for pod in self.kube.list_pods(job.namespace,
                                       labels={LABEL_JOB: job.name}):
            try:
                self.kube.delete_pod(job.namespace, pod["metadata"]["name"])
            except NotFound:
                pass

    def _set_phase(self, cr_obj: dict, phase: str, reason: str = "",
                   message: str = "", extra: Optional[dict] = None) -> None:
        meta = cr_obj["metadata"]
        status = dict(cr_obj.get("status", {}) or {})
        status.update({
            "phase": phase,
            "reason": reason,
            "message": message,
            # Wall-clock CR status stamp read by kubectl/humans — not
            # a policy decision.
            # kft: allow=clock-discipline
            "lastTransition": time.time(),
            **(extra or {}),
        })
        cr_obj["status"] = status
        self.kube.update_custom_status(
            meta.get("namespace", "default"), meta["name"], status
        )
        self.kube.record_event(
            meta.get("namespace", "default"), f"TPUJob/{meta['name']}",
            reason or phase, message or phase,
        )
        self._trace_transition(self._trace_key(meta), phase, reason,
                               message)

    @staticmethod
    def _trace_key(meta: dict) -> str:
        """The one job-trace key derivation, shared by the stamping
        site (_set_phase) and the prune sweep (reconcile_all) — if
        they diverged, a live job's trace state would be wiped every
        sweep."""
        return (f"{meta.get('namespace', 'default')}/"
                f"{meta.get('name', '')}")

    def _trace_transition(self, key: str, phase: str, reason: str,
                          message: str) -> None:
        """Job-lifecycle spans, drain-time stamped: each phase the job
        dwelled in becomes one span (annotated with the queue/quota/
        preemption reason that ENDED it), and the terminal transition
        stamps the root span — Failed jobs complete with status
        "error", so tail sampling always retains them."""
        if not tracing.enabled():
            self._job_traces.pop(key, None)
            return
        now = time.perf_counter()
        tr = self._job_traces.get(key)
        if tr is not None and tr.get("done"):
            # Already terminally stamped.  A permanently invalid CR
            # re-enters the Failed path EVERY sweep (its spec parse
            # fails before the terminal short-circuit); without this
            # tombstone each sweep would mint a fresh error-retained
            # trace and LRU-flush the store in minutes.  The entry
            # stays (bounded by live CRs, like _admitted_at) until the
            # prune sweep sees the CR vanish.
            return
        if tr is None:
            tr = self._job_traces[key] = {
                "t0": now, "phase": None, "since": now, "spans": []}
        prev = tr["phase"]
        if prev is not None and prev != phase:
            # Phase spans buffer in CONTROLLER memory (bounded: a few
            # phases per job) and stamp at the terminal transition —
            # a job Running for hours must not depend on the store's
            # open-trace aging to keep its earlier phases.
            tr["spans"].append(
                (f"job.{prev}", tr["since"], now,
                 {"job": key, "to": phase, "reason": reason,
                  "message": message}))
            tr["since"] = now
        tr["phase"] = phase
        if phase in TERMINAL:
            ctx = tracing.new_root_ctx()
            if ctx is not None:
                for name, start, end, attrs in tr["spans"]:
                    tracing.record_span(name, ctx, start, end,
                                        attrs=attrs)
                tracing.record_span(
                    "job.lifecycle", ctx, tr["t0"], now,
                    status="ok" if phase == JOB_SUCCEEDED
                    else "error",
                    attrs={"job": key, "phase": phase,
                           "reason": reason, "message": message},
                    root=True)
            tr["spans"] = []
            tr["done"] = True
