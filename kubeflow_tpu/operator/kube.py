"""Kubernetes API abstraction + in-memory fake.

The reference's operators were external Go binaries talking to a real API
server, testable only on rented clusters (SURVEY.md §4: "no fake k8s API
server").  Here the reconciler is written against this minimal interface,
and FakeKube gives CI a complete in-memory cluster: pods with controllable
phases, events, CR status updates — so gang semantics and failure recovery
are unit-testable.

A production deployment backs the same interface with the official
``kubernetes`` python client (operator/kube_real.py builds it lazily so
the package never hard-depends on cluster credentials).
"""

from __future__ import annotations

import copy
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

ObjectDict = Dict[str, Any]

# Pod phases (k8s core/v1 semantics).
PENDING = "Pending"
RUNNING = "Running"
SUCCEEDED = "Succeeded"
FAILED = "Failed"


class Conflict(Exception):
    """Create of an object that already exists."""


class NotFound(Exception):
    """Get/delete of a missing object."""


def _key(namespace: str, name: str) -> Tuple[str, str]:
    return (namespace, name)


class FakeKube:
    """In-memory cluster state. Thread-safe; no watches — the reconciler
    polls (level-triggered reconciliation, the controller-runtime model)."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self.pods: Dict[Tuple[str, str], ObjectDict] = {}
        self.services: Dict[Tuple[str, str], ObjectDict] = {}
        self.custom: Dict[Tuple[str, str], ObjectDict] = {}
        self.deployments: Dict[Tuple[str, str], ObjectDict] = {}
        self.events: List[ObjectDict] = []
        self.deleted_pods: List[str] = []
        self.nodes: List[ObjectDict] = []

    def list_nodes(self) -> List[ObjectDict]:
        with self._lock:
            return copy.deepcopy(self.nodes)

    # -- pods -------------------------------------------------------------

    def create_pod(self, pod: ObjectDict) -> ObjectDict:
        with self._lock:
            key = _key(pod["metadata"]["namespace"], pod["metadata"]["name"])
            if key in self.pods:
                raise Conflict(f"pod {key} exists")
            pod = copy.deepcopy(pod)
            pod.setdefault("status", {})["phase"] = PENDING
            self.pods[key] = pod
            return copy.deepcopy(pod)

    def get_pod(self, namespace: str, name: str) -> ObjectDict:
        with self._lock:
            try:
                return copy.deepcopy(self.pods[_key(namespace, name)])
            except KeyError:
                raise NotFound(f"pod {namespace}/{name}") from None

    def list_pods(self, namespace: str,
                  labels: Optional[Dict[str, str]] = None) -> List[ObjectDict]:
        with self._lock:
            out = []
            for (ns, _), pod in self.pods.items():
                if ns != namespace:
                    continue
                pod_labels = pod["metadata"].get("labels", {})
                if labels and any(pod_labels.get(k) != v
                                  for k, v in labels.items()):
                    continue
                out.append(copy.deepcopy(pod))
            return out

    def delete_pod(self, namespace: str, name: str) -> None:
        with self._lock:
            if _key(namespace, name) not in self.pods:
                raise NotFound(f"pod {namespace}/{name}")
            del self.pods[_key(namespace, name)]
            self.deleted_pods.append(f"{namespace}/{name}")

    def set_pod_phase(self, namespace: str, name: str, phase: str) -> None:
        """Test hook: simulate kubelet/scheduler state transitions."""
        with self._lock:
            self.pods[_key(namespace, name)]["status"]["phase"] = phase

    def set_pod_node(self, namespace: str, name: str, node: str) -> None:
        """Test hook: simulate the k8s scheduler binding a pod to a
        node (spec.nodeName) — what the reconciler's bad-node
        quarantine attributes worker failures to."""
        with self._lock:
            pod = self.pods[_key(namespace, name)]
            pod.setdefault("spec", {})["nodeName"] = node

    # -- services ---------------------------------------------------------

    def create_service(self, svc: ObjectDict) -> ObjectDict:
        with self._lock:
            key = _key(svc["metadata"]["namespace"], svc["metadata"]["name"])
            if key in self.services:
                raise Conflict(f"service {key} exists")
            self.services[key] = copy.deepcopy(svc)
            return copy.deepcopy(svc)

    def delete_service(self, namespace: str, name: str) -> None:
        with self._lock:
            self.services.pop(_key(namespace, name), None)

    # -- deployments ------------------------------------------------------
    # apps/v1 slice for the fleet autoscaler: it scales a serving
    # Deployment by patching spec.replicas (fleet/autoscaler.py), so the
    # fake cluster needs just create/get/scale — status.replicas tracks
    # the spec (the fake has no deployment controller; tests flip
    # readiness themselves where they need a lag).

    def create_deployment(self, dep: ObjectDict) -> ObjectDict:
        with self._lock:
            key = _key(dep["metadata"]["namespace"],
                       dep["metadata"]["name"])
            if key in self.deployments:
                raise Conflict(f"deployment {key} exists")
            dep = copy.deepcopy(dep)
            replicas = dep.get("spec", {}).get("replicas", 1)
            dep.setdefault("status", {})["replicas"] = replicas
            self.deployments[key] = dep
            return copy.deepcopy(dep)

    def get_deployment(self, namespace: str, name: str) -> ObjectDict:
        with self._lock:
            try:
                return copy.deepcopy(
                    self.deployments[_key(namespace, name)])
            except KeyError:
                raise NotFound(
                    f"deployment {namespace}/{name}") from None

    def list_deployments(
            self, namespace: str,
            labels: Optional[Dict[str, str]] = None) -> List[ObjectDict]:
        with self._lock:
            out = []
            for (ns, _), dep in self.deployments.items():
                if ns != namespace:
                    continue
                dep_labels = dep["metadata"].get("labels", {})
                if labels and any(dep_labels.get(k) != v
                                  for k, v in labels.items()):
                    continue
                out.append(copy.deepcopy(dep))
            return out

    def patch_deployment_scale(self, namespace: str, name: str,
                               replicas: int) -> ObjectDict:
        """Set spec.replicas — the autoscaler's one write verb.
        Idempotent (PATCH semantics): re-applying the same count is a
        no-op, which is what lets the level-triggered reconcile loop
        repeat itself safely."""
        with self._lock:
            key = _key(namespace, name)
            if key not in self.deployments:
                raise NotFound(f"deployment {namespace}/{name}")
            dep = self.deployments[key]
            dep.setdefault("spec", {})["replicas"] = int(replicas)
            dep.setdefault("status", {})["replicas"] = int(replicas)
            return copy.deepcopy(dep)

    # -- custom resources -------------------------------------------------

    def create_custom(self, cr: ObjectDict) -> ObjectDict:
        with self._lock:
            key = _key(cr["metadata"].get("namespace", "default"),
                       cr["metadata"]["name"])
            if key in self.custom:
                raise Conflict(f"cr {key} exists")
            self.custom[key] = copy.deepcopy(cr)
            return copy.deepcopy(cr)

    def list_custom(self, namespace: Optional[str] = None) -> List[ObjectDict]:
        with self._lock:
            return [copy.deepcopy(cr) for (ns, _), cr in self.custom.items()
                    if namespace is None or ns == namespace]

    def get_custom(self, namespace: str, name: str) -> ObjectDict:
        with self._lock:
            try:
                return copy.deepcopy(self.custom[_key(namespace, name)])
            except KeyError:
                raise NotFound(f"cr {namespace}/{name}") from None

    def update_custom_status(self, namespace: str, name: str,
                             status: ObjectDict) -> None:
        with self._lock:
            if _key(namespace, name) not in self.custom:
                raise NotFound(f"cr {namespace}/{name}")
            self.custom[_key(namespace, name)]["status"] = copy.deepcopy(status)

    def delete_custom(self, namespace: str, name: str) -> None:
        with self._lock:
            self.custom.pop(_key(namespace, name), None)

    # -- events -----------------------------------------------------------

    def record_event(self, namespace: str, involved: str, reason: str,
                     message: str, type_: str = "Normal") -> None:
        with self._lock:
            self.events.append({
                "namespace": namespace, "involvedObject": involved,
                "reason": reason, "message": message, "type": type_,
                # Wall-clock event timestamp leaving the process (the
                # apiserver convention) — not a policy decision.
                # kft: allow=clock-discipline
                "ts": time.time(),
            })
