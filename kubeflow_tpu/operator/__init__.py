"""Control plane: TPUJob CRD model, gang scheduler, reconciler.

First-party heir of the reference's L3 operators (SURVEY.md §1) — the
external tf-operator/pytorch-operator Go binaries become an in-tree
controller with all-or-nothing slice admission and
restart-the-gang-from-checkpoint failure semantics.
"""

from kubeflow_tpu.operator.crd import (
    GROUP,
    KIND,
    VERSION,
    MeshSpec,
    RestartPolicy,
    SpecError,
    StorageSpec,
    TPUJobSpec,
    WorkerSpec,
)
from kubeflow_tpu.operator.gang import GangScheduler
from kubeflow_tpu.operator.kube import FakeKube
from kubeflow_tpu.operator.reconciler import TPUJobController

__all__ = [
    "GROUP",
    "KIND",
    "VERSION",
    "MeshSpec",
    "RestartPolicy",
    "SpecError",
    "StorageSpec",
    "TPUJobSpec",
    "WorkerSpec",
    "GangScheduler",
    "FakeKube",
    "TPUJobController",
]
