"""Zero-dependency Kubernetes API backend: stdlib HTTP against the REST
contract.

The reference's operator was a Go binary using client-go; the python
``kubernetes`` client is this framework's RealKube path (kube_real.py)
but is a heavyweight optional dependency.  This backend implements the
same reconciler-facing surface (operator/kube.py FakeKube) with nothing
but ``urllib`` + ``ssl``: the half-dozen REST verbs the operator needs
map directly onto the API server's JSON endpoints, and in-cluster
credentials are the standard service-account token + CA files.

Because it is plain HTTP, the suite exercises it against a REAL server
(kubeflow_tpu/testing/fake_apiserver.py speaks the same REST contract
over a localhost socket) — the request construction, label selectors,
status PATCH content type, and 404/409 -> NotFound/Conflict mapping all
run over real sockets in CI, which neither client-go nor the python
client ever did in this repo's environment.
"""

from __future__ import annotations

import json
import os
import random
import ssl
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Dict, List, Optional

from kubeflow_tpu.operator import crd
from kubeflow_tpu.operator.kube import Conflict, NotFound, ObjectDict
from kubeflow_tpu.testing import faults

SA_DIR = "/var/run/secrets/kubernetes.io/serviceaccount"


def _retry_after(headers) -> Optional[float]:
    """Parse a Retry-After header into seconds (None when absent or in
    the HTTP-date form — the delta-seconds form is what the serving
    stack and the apiserver emit)."""
    value = headers.get("Retry-After") if headers is not None else None
    if value is None:
        return None
    try:
        return float(value)
    except (TypeError, ValueError):
        return None


class HttpKube:
    """Reconciler kube backend over the raw Kubernetes REST API.

    Transient apiserver weather — 5xx (leader elections, webhook blips)
    and connection resets — is retried with capped, jittered
    exponential backoff, so one blip does not fail a whole reconcile
    pass.  Two hard limits on the retry policy: semantic statuses
    (404/409 and other 4xx) are NEVER retried — they are answers, not
    weather — and only IDEMPOTENT verbs (GET/PUT/PATCH) retry at all.
    A POST or DELETE whose response was lost may have landed
    server-side; replaying it would double-apply (duplicate create ->
    spurious Conflict, re-delete -> spurious NotFound), so mutations
    fail fast and lean on the reconciler's level-triggered resweep as
    their natural retry."""

    def __init__(
        self,
        base_url: Optional[str] = None,
        token: Optional[str] = None,
        ca_cert: Optional[str] = None,
        timeout_s: float = 30.0,
        retries: int = 3,
        retry_backoff_s: float = 0.05,
        retry_backoff_cap_s: float = 2.0,
    ):
        if base_url is None:
            host = os.environ.get("KUBERNETES_SERVICE_HOST")
            port = os.environ.get("KUBERNETES_SERVICE_PORT", "443")
            if not host:
                raise RuntimeError(
                    "not in-cluster (KUBERNETES_SERVICE_HOST unset) and "
                    "no base_url given")
            base_url = f"https://{host}:{port}"
        self.base_url = base_url.rstrip("/")
        if token is None and os.path.exists(f"{SA_DIR}/token"):
            with open(f"{SA_DIR}/token") as f:
                token = f.read().strip()
        self._token = token
        if ca_cert is None and os.path.exists(f"{SA_DIR}/ca.crt"):
            ca_cert = f"{SA_DIR}/ca.crt"
        self._timeout_s = timeout_s
        self._retries = max(0, int(retries))
        self._retry_backoff_s = retry_backoff_s
        self._retry_backoff_cap_s = retry_backoff_cap_s
        if self.base_url.startswith("https"):
            self._ssl = ssl.create_default_context(cafile=ca_cert)
        else:
            self._ssl = None

    # -- transport --------------------------------------------------------

    def _request(
        self,
        method: str,
        path: str,
        body: Optional[ObjectDict] = None,
        params: Optional[Dict[str, str]] = None,
        content_type: str = "application/json",
    ) -> ObjectDict:
        url = self.base_url + path
        if params:
            url += "?" + urllib.parse.urlencode(params)
        data = json.dumps(body).encode() if body is not None else None
        # See the class docstring: replaying a mutation whose response
        # was lost can double-apply it, so only idempotent verbs retry.
        retries = self._retries if method in ("GET", "PUT", "PATCH") \
            else 0
        attempt = 0
        while True:
            # Rebuilt per attempt: a urllib Request is not guaranteed
            # reusable after a failed send.
            req = urllib.request.Request(url, data=data, method=method)
            req.add_header("Accept", "application/json")
            if data is not None:
                req.add_header("Content-Type", content_type)
            if self._token:
                req.add_header("Authorization", f"Bearer {self._token}")
            try:
                # Chaos hook: scripted connection failures land here,
                # BEFORE the socket — the retry layer sees them exactly
                # as it would a refused connect.
                faults.fire("kube.request")
                with urllib.request.urlopen(
                        req, timeout=self._timeout_s,
                        context=self._ssl) as r:
                    payload = r.read()
                break
            except urllib.error.HTTPError as e:
                detail = e.read().decode(errors="replace")[:500]
                if e.code == 404:
                    raise NotFound(f"{method} {path}: {detail}") from None
                if e.code == 409:
                    raise Conflict(f"{method} {path}: {detail}") from None
                # 429 is weather too (apiserver flow control), and like
                # 5xx it may carry the server's own backoff hint — a
                # Retry-After header overrides the local jittered
                # schedule (capped): the server knows when it will have
                # room, the client's exponential guess does not.
                if e.code in (429,) or e.code >= 500:
                    if attempt < retries:
                        self._backoff(attempt,
                                      hint_s=_retry_after(e.headers))
                        attempt += 1
                        continue
                raise RuntimeError(
                    f"{method} {path} -> {e.code}: {detail}") from None
            except (urllib.error.URLError, ConnectionError,
                    TimeoutError, faults.FaultInjected) as e:
                if attempt < retries:
                    self._backoff(attempt)
                    attempt += 1
                    continue
                raise RuntimeError(
                    f"{method} {path} failed after "
                    f"{attempt + 1} attempts: {e}") from e
        return json.loads(payload) if payload else {}

    def _backoff(self, attempt: int,
                 hint_s: Optional[float] = None) -> None:
        if hint_s is not None:
            # Server-supplied hint wins over the local schedule; still
            # capped (a hostile/confused server must not park the
            # reconciler) and lightly jittered so a herd told the same
            # number does not return in phase.
            delay = min(self._retry_backoff_cap_s, max(0.0, hint_s))
            time.sleep(delay * (1.0 + 0.1 * random.random()))
            return
        delay = min(self._retry_backoff_cap_s,
                    self._retry_backoff_s * (2 ** attempt))
        # Full jitter: concurrent reconcilers must not retry in phase.
        time.sleep(delay * (0.5 + 0.5 * random.random()))

    @staticmethod
    def _selector(labels: Optional[Dict[str, str]]) -> Dict[str, str]:
        if not labels:
            return {}
        return {"labelSelector":
                ",".join(f"{k}={v}" for k, v in sorted(labels.items()))}

    # -- pods -------------------------------------------------------------

    def create_pod(self, pod: ObjectDict) -> ObjectDict:
        ns = pod["metadata"]["namespace"]
        return self._request("POST", f"/api/v1/namespaces/{ns}/pods", pod)

    def get_pod(self, namespace: str, name: str) -> ObjectDict:
        return self._request(
            "GET", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def list_pods(self, namespace: str,
                  labels: Optional[Dict[str, str]] = None) -> List[ObjectDict]:
        out = self._request(
            "GET", f"/api/v1/namespaces/{namespace}/pods",
            params=self._selector(labels))
        return out.get("items", [])

    def delete_pod(self, namespace: str, name: str) -> None:
        self._request(
            "DELETE", f"/api/v1/namespaces/{namespace}/pods/{name}")

    def list_nodes(self) -> List[ObjectDict]:
        return self._request("GET", "/api/v1/nodes").get("items", [])

    # -- services ---------------------------------------------------------

    def create_service(self, svc: ObjectDict) -> ObjectDict:
        ns = svc["metadata"]["namespace"]
        return self._request(
            "POST", f"/api/v1/namespaces/{ns}/services", svc)

    def delete_service(self, namespace: str, name: str) -> None:
        try:
            self._request(
                "DELETE",
                f"/api/v1/namespaces/{namespace}/services/{name}")
        except NotFound:
            pass  # FakeKube semantics: service delete is idempotent

    # -- deployments ------------------------------------------------------

    def create_deployment(self, dep: ObjectDict) -> ObjectDict:
        ns = dep["metadata"]["namespace"]
        return self._request(
            "POST", f"/apis/apps/v1/namespaces/{ns}/deployments", dep)

    def get_deployment(self, namespace: str, name: str) -> ObjectDict:
        return self._request(
            "GET",
            f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}")

    def list_deployments(
            self, namespace: str,
            labels: Optional[Dict[str, str]] = None) -> List[ObjectDict]:
        out = self._request(
            "GET", f"/apis/apps/v1/namespaces/{namespace}/deployments",
            params=self._selector(labels))
        return out.get("items", [])

    def patch_deployment_scale(self, namespace: str, name: str,
                               replicas: int) -> ObjectDict:
        """The autoscaler's one write verb: merge-patch spec.replicas.
        PATCH is idempotent, so it rides the transient-retry policy —
        replaying a lost scale-to-N lands on N either way."""
        return self._request(
            "PATCH",
            f"/apis/apps/v1/namespaces/{namespace}/deployments/{name}",
            {"spec": {"replicas": int(replicas)}},
            content_type="application/merge-patch+json")

    # -- custom resources -------------------------------------------------

    def _custom_path(self, namespace: Optional[str], name: str = "") -> str:
        base = f"/apis/{crd.GROUP}/{crd.VERSION}"
        if namespace:
            base += f"/namespaces/{namespace}"
        base += f"/{crd.PLURAL}"
        return base + (f"/{name}" if name else "")

    def create_custom(self, cr: ObjectDict) -> ObjectDict:
        ns = cr["metadata"].get("namespace", "default")
        return self._request("POST", self._custom_path(ns), cr)

    def list_custom(self, namespace: Optional[str] = None) -> List[ObjectDict]:
        return self._request(
            "GET", self._custom_path(namespace)).get("items", [])

    def get_custom(self, namespace: str, name: str) -> ObjectDict:
        return self._request("GET", self._custom_path(namespace, name))

    def update_custom_status(self, namespace: str, name: str,
                             status: ObjectDict) -> None:
        self._request(
            "PATCH", self._custom_path(namespace, name) + "/status",
            {"status": status},
            content_type="application/merge-patch+json")

    def delete_custom(self, namespace: str, name: str) -> None:
        try:
            self._request(
                "DELETE", self._custom_path(namespace, name))
        except NotFound:
            pass  # FakeKube semantics: CR delete is idempotent

    # -- events -----------------------------------------------------------

    def record_event(self, namespace: str, involved: str, reason: str,
                     message: str, type_: str = "Normal") -> None:
        # Best-effort, like every backend: never fail a reconcile over
        # event bookkeeping.
        try:
            import datetime
            import uuid

            self._request(
                "POST", f"/api/v1/namespaces/{namespace}/events", {
                    "metadata": {
                        "name": f"tpujob-{uuid.uuid4().hex[:12]}",
                        "namespace": namespace,
                    },
                    "involvedObject": {
                        "kind": involved.split("/")[0],
                        "name": involved.split("/")[-1],
                        "namespace": namespace,
                    },
                    "reason": reason,
                    "message": message,
                    "type": type_,
                    "firstTimestamp":
                        datetime.datetime.now(datetime.timezone.utc)
                        .strftime("%Y-%m-%dT%H:%M:%SZ"),
                })
        except Exception:
            pass
