"""Version introspection.

Heir of the reference's version ConfigMap (kubeflow/core/version.libsonnet:1-15),
which embedded a version-info.json into the cluster for deployed-version
introspection; here the same dict is importable and also rendered into a
ConfigMap by manifests/core.py.
"""

__version__ = "0.1.0"


def version_info() -> dict:
    return {
        "version": __version__,
        "framework": "kubeflow_tpu",
        "accelerator": "tpu",
    }
