"""Inception-v3 in Flax — the reference's serving showcase model.

The reference's serving E2E test deployed an Inception SavedModel and
diffed a gRPC Predict against golden outputs
(testing/test_tf_serving.py; goldens at
components/k8s-model-server/images/test-worker/result.txt).  This is the
TPU-first re-implementation used by the serving path's classifier loader
(serving/loaders.py): bf16 compute, NHWC, BatchNorm with fp32 stats.

Architecture per Szegedy et al. 2015 ("Rethinking the Inception
Architecture"): stem -> 3xInceptionA -> InceptionB -> 4xInceptionC ->
InceptionD -> 2xInceptionE -> pool -> logits; 299x299 canonical input.
"""

from __future__ import annotations

import functools
from typing import Any, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class ConvBN(nn.Module):
    """conv -> BN -> relu, the basic Inception unit."""

    features: int
    kernel: Tuple[int, int]
    strides: Tuple[int, int] = (1, 1)
    padding: Any = "SAME"
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(self.features, self.kernel, self.strides,
                    padding=self.padding, use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9997,
                         epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


def _pool(x, window=(3, 3), strides=(1, 1), kind="avg"):
    fn = nn.avg_pool if kind == "avg" else nn.max_pool
    return fn(x, window, strides=strides, padding="SAME")


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(64, (1, 1))(x, train)
        b2 = c(64, (5, 5))(c(48, (1, 1))(x, train), train)
        b3 = c(96, (3, 3))(c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b4 = c(self.pool_features, (1, 1))(_pool(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35x35 -> 17x17."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = c(96, (3, 3), strides=(2, 2), padding="VALID")(
            c(96, (3, 3))(c(64, (1, 1))(x, train), train), train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    channels_7x7: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = c(192, (1, 1))(x, train)
        b2 = c(192, (7, 1))(c(c7, (1, 7))(c(c7, (1, 1))(x, train), train), train)
        b3 = c(192, (1, 7))(
            c(c7, (7, 1))(
                c(c7, (1, 7))(
                    c(c7, (7, 1))(c(c7, (1, 1))(x, train), train),
                    train), train), train)
        b4 = c(192, (1, 1))(_pool(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17x17 -> 8x8."""

    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (3, 3), strides=(2, 2), padding="VALID")(
            c(192, (1, 1))(x, train), train)
        b2 = c(192, (3, 3), strides=(2, 2), padding="VALID")(
            c(192, (7, 1))(
                c(192, (1, 7))(c(192, (1, 1))(x, train), train), train),
            train)
        b3 = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionE(nn.Module):
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train=True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        b1 = c(320, (1, 1))(x, train)
        b2in = c(384, (1, 1))(x, train)
        b2 = jnp.concatenate([
            c(384, (1, 3))(b2in, train), c(384, (3, 1))(b2in, train)
        ], axis=-1)
        b3in = c(384, (3, 3))(c(448, (1, 1))(x, train), train)
        b3 = jnp.concatenate([
            c(384, (1, 3))(b3in, train), c(384, (3, 1))(b3in, train)
        ], axis=-1)
        b4 = c(192, (1, 1))(_pool(x), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    dropout_rate: float = 0.2

    @nn.compact
    def __call__(self, x, train: bool = True):
        c = functools.partial(ConvBN, dtype=self.dtype)
        x = x.astype(self.dtype)
        # Stem: 299x299x3 -> 35x35x192.
        x = c(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = c(32, (3, 3), padding="VALID")(x, train)
        x = c(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = c(80, (1, 1), padding="VALID")(x, train)
        x = c(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        # Inception stacks.
        x = InceptionA(32, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionB(self.dtype)(x, train)
        x = InceptionC(128, self.dtype)(x, train)
        x = InceptionC(160, self.dtype)(x, train)
        x = InceptionC(160, self.dtype)(x, train)
        x = InceptionC(192, self.dtype)(x, train)
        x = InceptionD(self.dtype)(x, train)
        x = InceptionE(self.dtype)(x, train)
        x = InceptionE(self.dtype)(x, train)
        # Head.
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout_rate, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="logits")(x.astype(jnp.float32))
        return x


# Canonical forward FLOPs per 299x299 image (~5.7 GFLOPs, 2*MAC).
FWD_FLOPS_299 = 11.4e9 / 2
