"""Mixture-of-Experts MLP with expert parallelism.

Absent from the reference (SURVEY.md §2.3 "Expert parallel: absent").
GShard-style dense dispatch, shaped for the TPU:

  - routing, dispatch and combine are einsums (MXU work, no gather/scatter
    with dynamic shapes — XLA keeps static tiling);
  - **grouped dispatch**: tokens are routed in fixed-size groups, each
    filling its own per-group expert slots (GShard's group dimension).
    The one-hot dispatch/combine einsums cost O(tokens * E*C * d); with a
    single group E*C grows with top_k * tokens, making dispatch O(N^2 d)
    — measured 675 ms/step at the bench config, dwarfing the experts
    themselves.  Fixed groups make E*C a constant (group * top_k *
    capacity_factor), so dispatch is linear in N;
  - fixed per-group expert capacity C = ceil(group * top_k / E *
    capacity_factor) (slots scale with top_k, the GShard convention —
    otherwise uniform top-2 routing already drops second choices):
    tokens over capacity are dropped (residual connection carries them),
    the standard trade for static shapes;
  - expert weight tensors carry the ("expert", ...) logical axis, so the
    rule table places experts on the `expert` mesh axis and XLA inserts
    the all-to-alls implied by the dispatch einsums;
  - Switch-style load-balancing aux loss over ALL tokens (not per group),
    sown into the "losses" collection (models/transformer.py threads it
    into the train loss).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from flax import linen as nn

kernel_init = nn.initializers.lecun_normal()


def default_group_size(impl: str) -> int:
    """Measured per-impl routing-group optimum (v5e bench config):
    einsum 128 (dispatch one-hot cost is linear in the group), gather
    256 (smaller groups degrade its scatter/gather, 28.1k vs 31.0k
    tok/s).  Single source of truth for the group_size=0 sentinel."""
    return 256 if impl == "gather" else 128


def fit_divisor(n: int, limit: int, label: str, consequence: str) -> int:
    """Largest divisor of ``n`` <= ``limit`` — the trace-time tiling
    fit shared by the MoE routing-group and the chunked-CE scan (a gcd
    shortcut degenerates badly for counts sharing few factors with a
    power-of-two limit: gcd(2046, 256) = 2).

    The scan itself can still degenerate for prime-ish ``n`` (the fit
    collapses toward 1); below limit//4 a trace-time warning names the
    ``label`` and its ``consequence`` so the config is fixed rather
    than silently paid every step."""
    want = min(limit, n)
    got = next(c for c in range(want, 0, -1) if n % c == 0)
    if got < want // 4:
        import warnings

        warnings.warn(
            f"{label} degenerated: {n} has no divisor near {limit} "
            f"(fitted {got}).  {consequence}",
            stacklevel=3,
        )
    return got


class MoEMLP(nn.Module):
    """Drop-in replacement for the dense SwiGLU MLP block."""

    d_model: int
    d_ff: int
    num_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    # Routing group size (tokens): dispatch cost per token is
    # proportional to it, capacity granularity (and drop variance)
    # inversely.  The effective size is a divisor of the token count <=
    # this (gcd fallback), so any batch shape works.  0 = each impl's
    # measured optimum (default_group_size above).  Sweep on v5e with
    # the E-major rank-3 einsums: 128 wins (MFU 0.404 vs 0.399 at 256,
    # dispatch one-hot cost halved) and 64 plateaus (0.402) while
    # shrinking per-group statistics.
    group_size: int = 0
    dtype: object = jnp.bfloat16
    # Dispatch/combine implementation:
    #   "einsum" — GShard one-hot einsums: dispatch builds a [g, E, C]
    #     one-hot tensor and contracts over the g tokens, O(g*E*C*d)
    #     MACs each way.  The contraction is pure token MOVEMENT priced
    #     as MXU work — but the MXU is exactly where the TPU is fast.
    #   "gather" — the same routing decisions materialized as indices:
    #     a [E, C] slot->token scatter, a row gather into the expert
    #     batch (O(E*C*d) bytes moved, no MACs), and a per-choice row
    #     gather back out (O(g*top_k*d)).  Identical numerics and drop
    #     semantics; the g-fold reduction dimension disappears.
    # Swept on-chip at the bench config (v5e, 4 experts, top-2,
    # artifacts/r4_onchip_sweeps.log): einsum 38.8k tok/s (MFU 0.404,
    # E-major rank-3 form, group 128) vs gather 31.0k (0.322, at its
    # own best group 256 — each impl runs its optimum via the
    # group_size=0 sentinel).  The asymptotic-MAC win loses to XLA's
    # dynamic-gather lowering (vector-unit + HBM bound); the one-hot
    # contractions ride the MXU.  Default follows the measurement.
    impl: str = "einsum"

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg_e, d, f = self.num_experts, self.d_model, self.d_ff
        b, s, _ = x.shape
        n_tokens = b * s
        group_size = self.group_size or default_group_size(self.impl)
        g = fit_divisor(
            n_tokens, group_size, "MoE routing group",
            "Per-group capacity clamps to top_k and expert "
            "compute/memory inflates by up to num_experts/top_k x.  "
            "Choose batch*seq with a divisor close to group_size.")
        n_groups = n_tokens // g
        capacity = max(
            self.top_k,
            int(math.ceil(g * self.top_k / cfg_e * self.capacity_factor)),
        )

        wr = self.param(
            "router",
            nn.with_logical_partitioning(kernel_init, ("embed", "expert")),
            (d, cfg_e), jnp.float32,
        )
        wi = self.param(
            "wi",
            nn.with_logical_partitioning(
                kernel_init, ("expert", None, "embed", "mlp")),
            (cfg_e, 2, d, f), jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(
                kernel_init, ("expert", "mlp", "embed")),
            (cfg_e, f, d), jnp.float32,
        )

        tokens = x.reshape(n_groups, g, d)
        # Routing in fp32 (softmax stability matters more than MXU here).
        logits = jnp.einsum(
            "gnd,de->gne", tokens.astype(jnp.float32), wr)
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k dispatch with per-group capacity.  Greedy per-choice
        # cumsum positions along the token axis of each group.
        gate_vals, gate_idx = jax.lax.top_k(probs, self.top_k)  # [G, g, k]
        # Renormalise the kept gates.
        gate_vals = gate_vals / jnp.maximum(
            gate_vals.sum(-1, keepdims=True), 1e-9)

        # Greedy per-choice routing: slot positions along each group's
        # token axis via cumsum (shared by both implementations).
        route_idx, route_pos, route_keep = [], [], []      # [k] x [G, g]
        counts = jnp.zeros((n_groups, cfg_e), jnp.int32)
        for choice in range(self.top_k):
            idx = gate_idx[..., choice]                    # [G, g]
            onehot = jax.nn.one_hot(idx, cfg_e, dtype=jnp.int32)
            pos = counts[:, None, :] + jnp.cumsum(onehot, axis=1) - 1
            my_pos = jnp.take_along_axis(
                pos, idx[..., None], axis=2)[..., 0]       # [G, g]
            counts = counts + onehot.sum(1)
            route_idx.append(idx)
            route_pos.append(my_pos)
            route_keep.append(my_pos < capacity)

        dt = self.dtype
        if self.impl == "gather":
            # Slot -> source-token index map, built by scatter (a [g]
            # write per choice; dropped tokens write column `capacity`,
            # which is out of bounds and dropped).  Sentinel g points at
            # the zero row appended to the token table, so unfilled
            # slots read zeros exactly as the one-hot contraction gave.
            slot_src = jnp.full((n_groups, cfg_e, capacity), g, jnp.int32)
            token_ids = jnp.broadcast_to(
                jnp.arange(g)[None, :], (n_groups, g))
            for choice in range(self.top_k):
                pos_or_oob = jnp.where(
                    route_keep[choice], route_pos[choice], capacity)
                slot_src = jax.vmap(
                    lambda s, e, p, t: s.at[e, p].set(t, mode="drop")
                )(slot_src, route_idx[choice], pos_or_oob, token_ids)
            tokens_pad = jnp.concatenate(
                [tokens.astype(dt),
                 jnp.zeros((n_groups, 1, d), dt)], axis=1)
            expert_in = jax.vmap(lambda tp, ss: tp[ss])(
                tokens_pad, slot_src)                      # [G, E, C, d]
        else:
            if self.impl != "einsum":
                raise ValueError(f"unknown moe impl {self.impl!r}")
            # One contrib tensor per choice feeds BOTH the dispatch and
            # combine accumulations — the drop/sentinel logic lives in
            # exactly one place.
            dispatch = jnp.zeros(
                (n_groups, g, cfg_e, capacity), jnp.bfloat16)
            combine = jnp.zeros(
                (n_groups, g, cfg_e, capacity), jnp.float32)
            for choice in range(self.top_k):
                onehot = jax.nn.one_hot(
                    route_idx[choice], cfg_e, dtype=jnp.float32)
                pos_onehot = jax.nn.one_hot(
                    jnp.where(route_keep[choice], route_pos[choice],
                              capacity),
                    capacity + 1, dtype=jnp.float32)[..., :capacity]
                contrib = onehot[..., :, None] * pos_onehot[..., None, :]
                dispatch = dispatch + contrib.astype(jnp.bfloat16)
                combine = combine \
                    + contrib * gate_vals[..., choice, None, None]
            # Expert axis LEADING on the dispatch output: the expert
            # einsums batch over E, and producing [G, E, C, d] makes
            # XLA materialize a G<->E transpose between dispatch and
            # the first expert matmul (profiled at ~18 ms/step, ~4% of
            # the MoE step, pure data movement).  E-major feeds them
            # in place.
            expert_in = jnp.einsum(
                "gnec,gnd->egcd", dispatch, tokens.astype(jnp.bfloat16))

        def expert_mlp(x, spec, x_axes, h_axes):
            """Batched SwiGLU over the expert slot tensor; `spec` is the
            up-projection einsum (its transpose is the down-projection),
            `x_axes`/`h_axes` the logical shardings of the input and
            the f-dim activations."""
            x = nn.with_logical_constraint(x, x_axes)
            lhs, out = spec.split("->")
            lhs = lhs.split(",")[0]
            gate = jnp.einsum(spec, x, wi[:, 0].astype(dt))
            up = jnp.einsum(spec, x, wi[:, 1].astype(dt))
            h = nn.with_logical_constraint(nn.silu(gate) * up, h_axes)
            return jnp.einsum(f"{out},efd->{lhs}", h, wo.astype(dt))

        if self.impl == "gather":
            # The slot map is [G, E, C]; vmap over G builds [G, E, C,
            # d], and the combine row-gathers index it per group.
            expert_out = expert_mlp(
                expert_in, "gecd,edf->gecf",
                (None, "expert", None, None),
                (None, "expert", None, "mlp"))
        else:
            # [E, G*C, d] — one big MXU batch, expert axis outermost
            # end to end (dispatch through combine).  The G and C dims
            # are collapsed for the matmuls: rank-3 inputs lower to one
            # clean batched dot per expert, where the rank-4 form kept
            # G as a second batch dim.
            expert_out = expert_mlp(
                expert_in.reshape(cfg_e, n_groups * capacity, d),
                "end,edf->enf", ("expert", None, None),
                ("expert", None, "mlp"),
            ).reshape(cfg_e, n_groups, capacity, d)

        if self.impl == "gather":
            # Each token reads its top_k slots back out: a per-choice
            # row gather weighted by the (renormalized, kept) gates.
            out = jnp.zeros((n_groups, g, d), dt)
            for choice in range(self.top_k):
                rows = jax.vmap(lambda eo, e, p: eo[e, p])(
                    expert_out, route_idx[choice],
                    jnp.clip(route_pos[choice], 0, capacity - 1),
                )                                          # [G, g, d]
                w = (gate_vals[..., choice]
                     * route_keep[choice]).astype(dt)[..., None]
                out = out + rows * w
        else:
            out = jnp.einsum(
                "gnec,egcd->gnd", combine.astype(dt), expert_out)

        # Switch load-balance loss: E * sum_e (fraction of tokens routed
        # to e) * (mean router prob of e); minimised by uniform routing.
        # Global over all tokens — routing balance is a model property,
        # not a per-group one.
        top1 = jax.nn.one_hot(
            gate_idx[..., 0].reshape(n_tokens), cfg_e, dtype=jnp.float32)
        fraction = top1.mean(0)
        mean_prob = probs.reshape(n_tokens, cfg_e).mean(0)
        aux = cfg_e * jnp.sum(fraction * mean_prob)
        self.sow("losses", "moe_aux", aux)

        return out.reshape(b, s, d).astype(self.dtype)
