"""ResNet family (v1.5) in Flax — the framework's image-classification
reference model.

Heir of the reference's benchmark workload: the tf-cnn prototype ran
``tf_cnn_benchmarks.py --model=resnet50`` as an external TF program
(kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:40-62,
tf-controller-examples/tf-cnn/create_job_specs.py:98-119).  Here the model
is first-party JAX, designed for the MXU:

  - compute dtype bfloat16 end-to-end, fp32 master params and batch stats;
  - NHWC layout (XLA:TPU's native conv layout) — the reference had to flag
    NHWC manually for CPU (`--data_format=NHWC`, create_job_specs.py:111);
  - channel counts multiples of 128 in all hot convs -> clean MXU tiling;
  - data parallelism only (conv nets saturate a slice with DP alone), so
    kernels carry no sharding annotations; batch-norm statistics are
    per-shard during training and synced at use (matching the standard
    large-batch recipe).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Sequence, Tuple

import jax.numpy as jnp
from flax import linen as nn

ModuleDef = Any


class ResNetBlock(nn.Module):
    """Basic 3x3+3x3 residual block (ResNet-18/34)."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (3, 3), self.strides)(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class BottleneckBlock(nn.Module):
    """1x1 -> 3x3 -> 1x1 bottleneck (ResNet-50/101/152), v1.5 variant:
    stride lives on the 3x3 (not the first 1x1), worth ~0.5% top-1."""

    filters: int
    conv: ModuleDef
    norm: ModuleDef
    act: Callable
    strides: Tuple[int, int] = (1, 1)

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters, (3, 3), self.strides)(y)
        y = self.norm()(y)
        y = self.act(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        # Zero-init the last norm scale: the block starts as identity,
        # stabilising large-batch training (the DP regime we target).
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(self.filters * 4, (1, 1), self.strides,
                                 name="conv_proj")(residual)
            residual = self.norm(name="norm_proj")(residual)
        return self.act(residual + y)


class ResNet(nn.Module):
    """Configurable ResNet; see constructors below for standard depths."""

    stage_sizes: Sequence[int]
    block_cls: ModuleDef
    num_classes: int = 1000
    num_filters: int = 64
    dtype: Any = jnp.bfloat16
    act: Callable = nn.relu
    # Rematerialise each residual block in backward — a memory knob for
    # HBM-limited configs (deep nets, large batch).  NOT a throughput win
    # for ResNet-50 on v5e: the step is bandwidth-bound (cost analysis:
    # ~77 GB / ~6 TFLOP per 256-image step) and XLA's recompute cluster
    # re-materialises traffic (measured 78 -> 96 GB with remat on).
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = functools.partial(
            nn.Conv, use_bias=False, dtype=self.dtype, padding="SAME"
        )
        norm = functools.partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=0.9,
            epsilon=1e-5,
            dtype=self.dtype,
            axis_name=None,
        )
        x = x.astype(self.dtype)
        x = conv(self.num_filters, (7, 7), (2, 2), name="conv_init")(x)
        x = norm(name="bn_init")(x)
        x = self.act(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        block_cls = self.block_cls
        if self.remat:
            block_cls = nn.remat(block_cls, static_argnums=())
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = block_cls(
                    filters=self.num_filters * 2 ** i,
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    act=self.act,
                )(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32,
                     name="head")(x.astype(jnp.float32))
        return x


ResNet18 = functools.partial(ResNet, stage_sizes=[2, 2, 2, 2],
                             block_cls=ResNetBlock)
ResNet34 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=ResNetBlock)
ResNet50 = functools.partial(ResNet, stage_sizes=[3, 4, 6, 3],
                             block_cls=BottleneckBlock)
ResNet101 = functools.partial(ResNet, stage_sizes=[3, 4, 23, 3],
                              block_cls=BottleneckBlock)
ResNet152 = functools.partial(ResNet, stage_sizes=[3, 8, 36, 3],
                              block_cls=BottleneckBlock)

# Forward-pass useful FLOPs per image for MFU accounting; the canonical
# figures for 224x224 inputs (multiply-accumulate counted as 2 FLOPs).
FWD_FLOPS_224 = {
    "resnet18": 3.6e9,
    "resnet34": 7.3e9,
    "resnet50": 8.2e9,
    "resnet101": 15.7e9,
    "resnet152": 23.1e9,
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    """Typed model selector, heir of the prototype's stringly `--model`
    param (kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:7)."""

    name: str = "resnet50"
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    remat: bool = False

    _FACTORIES = {
        "resnet18": ResNet18,
        "resnet34": ResNet34,
        "resnet50": ResNet50,
        "resnet101": ResNet101,
        "resnet152": ResNet152,
    }

    def build(self) -> ResNet:
        try:
            factory = self._FACTORIES[self.name]
        except KeyError:
            raise ValueError(
                f"unknown resnet {self.name!r}; known: {sorted(self._FACTORIES)}"
            ) from None
        return factory(num_classes=self.num_classes, dtype=self.dtype,
                       remat=self.remat)

    @property
    def fwd_flops_per_image(self) -> float:
        return FWD_FLOPS_224[self.name]
