"""Glue: turn a Flax image-classification model into Trainer callables.

Heir of the reference's launcher/benchmark split: tf_cnn_benchmarks owned
the loss/optimizer recipe outside the platform
(kubeflow/tf-job/prototypes/tf-cnn-benchmarks.jsonnet:40-62); here the task
recipe is a first-party, testable unit.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import linen as nn


def classification_task(
    model: nn.Module, input_shape: Tuple[int, ...]
) -> Tuple[Callable, Callable]:
    """Build (init_fn, loss_fn) for softmax cross-entropy training.

    Handles BatchNorm-style mutable collections: everything the model
    ``init``s besides 'params' rides TrainState.mutable and is threaded
    through apply(mutable=...) each step.
    """

    def init_fn(rng: jax.Array):
        variables = model.init(rng, jnp.zeros(input_shape), train=False)
        params = variables["params"]
        mutable = {k: v for k, v in variables.items() if k != "params"}
        return params, mutable

    def loss_fn(params, mutable, batch, rng):
        images, labels = batch["image"], batch["label"]
        outputs = model.apply(
            {"params": params, **mutable},
            images,
            train=True,
            mutable=list(mutable.keys()),
            rngs={"dropout": rng},
        )
        logits, new_mutable = outputs
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels
        ).mean()
        accuracy = jnp.mean(jnp.argmax(logits, -1) == labels)
        return loss, ({"accuracy": accuracy}, new_mutable)

    return init_fn, loss_fn


def eval_step(model: nn.Module) -> Callable[[Any, Any, Dict], Dict]:
    """Jittable eval step (running BN averages, no mutation)."""

    @jax.jit
    def step(params, mutable, batch):
        logits = model.apply(
            {"params": params, **mutable}, batch["image"], train=False
        )
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, batch["label"]
        ).mean()
        return {
            "loss": loss,
            "accuracy": jnp.mean(jnp.argmax(logits, -1) == batch["label"]),
        }

    return step
