"""Model families: ResNet/Inception classifiers, Transformer LM, MoE."""
