"""Autoregressive decoding for the Transformer LM.

The reference's serving story was TF-Serving for classifiers; LMs are this
framework's flagship, so decode is first-party.  TPU-shaped choices:

  - the KV cache is a preallocated [layers, 2, b, max_len, h, d] buffer
    carried through ``lax.scan`` — static shapes end to end, one compiled
    program for the whole generation;
  - prefill and decode are the same jitted function: the prompt is
    processed in one batched forward (MXU-efficient), then tokens stream
    one position at a time against the cache;
  - greedy or temperature sampling under ``jax.random``.

Kept outside the Flax module on purpose: the cache is explicit function
state (scan carry), not module state — no mutable-collection plumbing,
and the whole loop jits/shards like any other pure function.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from kubeflow_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    rope,
)
from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.quantize import (
    QTensor,
    embed_lookup,
    qeinsum,
    quantize_array,
)


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    max_new_tokens: int = 64
    temperature: float = 0.0   # 0 = greedy
    # Sampling filters (applied in this order when temperature > 0):
    # top_k keeps the k highest-logit tokens (0 = off); top_p keeps the
    # smallest set of tokens whose probability mass reaches p (1.0 =
    # off, i.e. nucleus sampling).  Both are static-shape TPU code: a
    # top_k threshold compare and a sorted-cumsum mask — no dynamic
    # vocabulary subsets.
    top_k: int = 0
    top_p: float = 1.0
    eos_token: int = -1        # -1 = never stop early

    def __post_init__(self):
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p} "
                "(1.0 disables nucleus filtering)")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
    # "model" = the model compute dtype; "int8" = quantized cache with
    # per-(position, head) scales (halves cache HBM traffic and memory —
    # the binding resource for batched decode; ops/attention.py folds the
    # scales through both matmuls so nothing dequantized materializes).
    kv_cache_dtype: str = "model"


def _lora(x, a, b, spec_a, spec_b):
    """Per-row low-rank delta: contract ``x`` against PER-ROW factor
    slices ``a``/``b`` (leading batch axis — row i's slice is its own
    adapter's, gathered by ``_forward_with_cache`` from the stacked
    [n_adapters, ...] arrays) in two rank-r hops, so the full-rank
    delta matrix never materializes and the cost stays O(r) of the
    base projection.  Row independence is what makes a mixed-adapter
    batch bit-identical to per-adapter sequential runs."""
    mid = jnp.einsum(spec_a, x, a)
    return jnp.einsum(spec_b, mid, b).astype(x.dtype)


def _layer_step(cfg: TransformerConfig, layer_params, x, cache_kv,
                cache_len, positions, pad_amount=None, write_cols=None,
                tables=None, adapters=None):
    """One decoder block against the KV cache.

    x: [b, t, e] new activations (t = prompt len at prefill, 1 at decode);
    cache_kv: (k, v) each [b, max_len, hkv, d] — or, when ``tables`` is
    given, a paged block POOL [num_blocks, block_tokens, hkv, d] shared
    by every slot;
    cache_len: number of valid cache positions before this call — a
    scalar (whole batch at one length, the generate() path) or a [b]
    array (per-row lengths, the slot-based decode_step / verify_step
    paths; each row writes its t new k/v columns starting at its OWN
    frontier and attends under its own causal mask via the per-row
    kv_offset — t is 1 at decode and k+1 at speculative verify);
    pad_amount: per-row [b] left-pad width (bucketed mixed-length
    prompts) — cache columns before it hold pad-token garbage and are
    masked out of every attention.
    write_cols: per-row [b] cache column for the new k/v when cache_len
    is per-row (defaults to cache_len); rows that must not write this
    step (retired slots) pass an out-of-range column — the scatter
    drops it.
    tables: [b, max_blocks] int32 per-row block tables mapping each
    row's LOGICAL block index (position // block_tokens) to a physical
    pool block.  Fresh k/v scatter straight into the pool at their
    (block, offset) coordinates — a logical index past the table span,
    or a table entry holding the sentinel ``num_blocks`` (unallocated),
    drops the write — and attention runs over the row's gathered
    [max_blocks * block_tokens] view of the pool (sentinel entries
    clamp onto an arbitrary block whose columns all sit beyond the
    causal frontier, so the garbage they contribute is masked).
    Mirrors models/transformer.py Block but with explicit cache state.
    """
    from kubeflow_tpu.models.transformer import MLP, RMSNorm

    attn = layer_params["attn"]
    dt = cfg.dtype

    def norm(x, scale):
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
        return (normed * scale).astype(dt)

    y = norm(x, layer_params["attn_norm"]["scale"])
    # qeinsum keeps int8 serving weights quantized through the dot
    # (per-output-channel scales applied after; ops/quantize.py).
    q = qeinsum("bse,ehd->bshd", y, attn["wq"], dt)
    k = qeinsum("bse,ehd->bshd", y, attn["wkv"][0], dt)
    v = qeinsum("bse,ehd->bshd", y, attn["wkv"][1], dt)
    if adapters is not None:
        # Adapter-array serving (§5.11): each row adds ITS adapter's
        # low-rank delta to every projection, pre-rope so the delta is
        # part of the projection itself.  Row 0 of the stack is the
        # all-zero base delta, so base traffic co-batches with tenant
        # traffic at identical math.
        ad = adapters["attn"]
        q = q + _lora(y, ad["wq_a"], ad["wq_b"],
                      "bse,ber->bsr", "bsr,brhd->bshd")
        k = k + _lora(y, ad["wkv_a"][:, 0], ad["wkv_b"][:, 0],
                      "bse,ber->bsr", "bsr,brhd->bshd")
        v = v + _lora(y, ad["wkv_a"][:, 1], ad["wkv_b"][:, 1],
                      "bse,ber->bsr", "bsr,brhd->bshd")
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    ck, cv = cache_kv
    t = x.shape[1]
    per_row = not isinstance(cache_len, int) and cache_len.ndim == 1
    if tables is not None:
        vals = ck.values if isinstance(ck, QTensor) else ck
        nb, bt = vals.shape[0], vals.shape[1]
        mb = tables.shape[1]
        if per_row:
            base = cache_len if write_cols is None else write_cols
            pos = base[:, None] + jnp.arange(t)[None, :]
        else:
            pos = cache_len + jnp.arange(t)[None, :]
            pos = jnp.broadcast_to(pos, (x.shape[0], t))
        blk_slot = pos // bt
        # Physical block per position: sentinel table entries (== nb)
        # and logical indices past the table both park the write out
        # of the pool's range — the scatter drops them.
        blk = jnp.take_along_axis(
            tables, jnp.clip(blk_slot, 0, mb - 1), axis=1)
        blk = jnp.where(blk_slot < mb, blk, nb)
        off = pos % bt

        def store(c, new):  # new: [b, t, hk, d]
            if isinstance(c, QTensor):
                qvals, s = quantize_array(new, (-1,))
                return QTensor(
                    c.values.at[blk, off].set(qvals, mode="drop"),
                    c.scale.at[blk, off].set(s, mode="drop"),
                    c.axes,
                )
            return c.at[blk, off].set(new.astype(c.dtype), mode="drop")

        ck = store(ck, k)
        cv = store(cv, v)

        def paged_view(c):
            # Row view of the (just-updated) pool: OOB sentinel
            # entries clamp, contributing finite garbage that the
            # kv_offset mask discards.
            def gather(p):
                g = p[tables]
                return g.reshape(
                    (tables.shape[0], mb * bt) + p.shape[2:])

            if isinstance(c, QTensor):
                return QTensor(gather(c.values), gather(c.scale),
                               c.axes)
            return gather(c)

        out = dot_product_attention(
            q, paged_view(ck), paged_view(cv), causal=True,
            kv_offset=cache_len, kv_valid_start=pad_amount,
        )
    elif per_row:
        # Slot-based decode/verify: t new tokens per row, scattered to
        # each row's own columns [base, base + t).  mode="drop" makes
        # an out-of-range column a no-op — that is how retired slots
        # skip the write without a separate program, and how a verify
        # window overhanging the cache end drops only its unreachable
        # tail columns.
        rows = jnp.arange(x.shape[0])[:, None]
        base = cache_len if write_cols is None else write_cols
        cols = base[:, None] + jnp.arange(t)[None, :]

        def store(c, new):  # new: [b, t, hk, d]
            if isinstance(c, QTensor):
                vals, s = quantize_array(new, (-1,))
                return QTensor(
                    c.values.at[rows, cols].set(vals, mode="drop"),
                    c.scale.at[rows, cols].set(s, mode="drop"),
                    c.axes,
                )
            return c.at[rows, cols].set(
                new.astype(c.dtype), mode="drop")

        ck = store(ck, k)
        cv = store(cv, v)
    elif isinstance(ck, QTensor):
        def store(c, new):
            vals, s = quantize_array(new, (-1,))    # [b, t, hk, d]
            return QTensor(
                jax.lax.dynamic_update_slice_in_dim(
                    c.values, vals, cache_len, axis=1),
                jax.lax.dynamic_update_slice_in_dim(
                    c.scale, s, cache_len, axis=1),
                c.axes,
            )

        ck = store(ck, k)
        cv = store(cv, v)
    else:
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k.astype(ck.dtype),
                                                 cache_len, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v.astype(cv.dtype),
                                                 cache_len, axis=1)
    # Attend over the whole buffer; positions beyond cache_len + t are
    # masked by the causal rule (their k_pos > any live q_pos... they are
    # zeros at positions >= cache_len+t, masked via kv_offset arithmetic).
    #
    # Prefill of a LONG prompt on a flash-configured model uses the
    # Pallas flash kernel over the fresh q/k/v instead (the cache is
    # empty at prefill, so causal attention over the prompt alone is the
    # whole computation): the dot path materializes the [b, h, t, t]
    # score matrix in HBM — O(t^2) memory that defeats the point of
    # serving a long-context model whose TRAINING path is O(t).
    # Left-padded bucketed batches ride the kernel's forward-only
    # per-row key-start mask (kv_valid_start — pad keys get zero
    # weight), so DEPLOYED bucketed serving flash-prefills too.  Gated
    # off only for quantized caches (the dot path attends against the
    # freshly quantized cache, and serving goldens pin that rounding).
    # cache_len is a static python 0 at prefill and a TRACED scalar in
    # the decode scan — the gate must only ever inspect the static case.
    static_prefill = (tables is None and isinstance(cache_len, int)
                      and cache_len == 0)
    if (cfg.attention == "flash" and t > 1 and static_prefill
            and not isinstance(ck, QTensor)):
        from kubeflow_tpu.ops.flash import flash_attention

        out = flash_attention(
            q, k, v, causal=True,
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
            kv_valid_start=pad_amount,
        )
    elif tables is None:
        out = dot_product_attention(
            q, ck, cv, causal=True, kv_offset=cache_len,
            kv_valid_start=pad_amount,
        )
    y = qeinsum("bshd,hde->bse", out, attn["wo"], dt)
    if adapters is not None:
        ad = adapters["attn"]
        y = y + _lora(out, ad["wo_a"], ad["wo_b"],
                      "bshd,bhdr->bsr", "bsr,bre->bse")
    x = x + y
    y = norm(x, layer_params["mlp_norm"]["scale"])
    mlp = layer_params["mlp"]
    gate = qeinsum("bse,ef->bsf", y, mlp["wi"][0], dt)
    up = qeinsum("bse,ef->bsf", y, mlp["wi"][1], dt)
    if adapters is not None:
        ad = adapters["mlp"]
        gate = gate + _lora(y, ad["wi_a"][:, 0], ad["wi_b"][:, 0],
                            "bse,ber->bsr", "bsr,brf->bsf")
        up = up + _lora(y, ad["wi_a"][:, 1], ad["wi_b"][:, 1],
                        "bse,ber->bsr", "bsr,brf->bsf")
    h = jax.nn.silu(gate) * up
    y = qeinsum("bsf,fe->bse", h, mlp["wo"], dt)
    if adapters is not None:
        ad = adapters["mlp"]
        y = y + _lora(h, ad["wo_a"], ad["wo_b"],
                      "bsf,bfr->bsr", "bsr,bre->bse")
    return x + y, (ck, cv)


def _forward_with_cache(cfg: TransformerConfig, params, tokens, cache,
                        cache_len, pad_amount=None, write_cols=None,
                        tables=None, adapter_ids=None):
    """tokens [b, t] -> (logits [b, t, v], new cache).

    cache_len scalar: the whole batch sits at one length (generate()).
    cache_len [b] array: per-row lengths (slot-based decode_step /
    verify_step) — each row ropes its t tokens at its own positions
    [len, len + t), writes its own cache columns (write_cols,
    defaulting to cache_len), and attends under its own causal
    frontier (t = 1 at decode, k+1 at speculative verify).
    tables: per-row block tables for the paged block-pool cache (the
    serving engine's unified KV store — see _layer_step); None keeps
    the contiguous per-row layout generate() uses.
    adapter_ids ([b] int32, optional): per-row index into the stacked
    ``params["adapters"]`` low-rank delta arrays (multi-model adapter
    serving, §5.11) — ignored when the params tree carries no adapter
    stack, so the base model's programs are untouched.
    """
    from flax import linen as nn

    params = nn.unbox(params)  # accept raw model.init output
    dt = cfg.dtype
    embed = params["embed"]
    x = embed_lookup(embed, tokens, dt)  # int8-aware row gather
    per_row = not isinstance(cache_len, int) and cache_len.ndim == 1
    if per_row:
        positions = (cache_len[:, None]
                     + jnp.arange(tokens.shape[1])[None, :])
    else:
        positions = cache_len + jnp.arange(tokens.shape[1])[None, :]
        positions = jnp.broadcast_to(positions, tokens.shape)
    if pad_amount is not None:
        # Left-padded rows: real token i of a row sits at buffer column
        # pad + i but must see rope position i.  Pad columns clamp to 0
        # — their keys are masked from every attention anyway.
        positions = jnp.maximum(positions - pad_amount[:, None], 0)

    layer_stack = params["layers"]
    adapter_stack = None
    if adapter_ids is not None and "adapters" in params:
        # Per-row adapter gather (§5.11): each row pulls ITS adapter's
        # low-rank factors out of the stacked [n_adapters, layers, ...]
        # arrays (row 0 is the all-zero base delta), then the layer
        # axis moves out front so the factors ride the scan xs beside
        # the base layer stack — one gather per forward, ONE SPMD
        # program for every mix of co-batched variants.
        adapter_stack = jax.tree_util.tree_map(
            lambda arr: jnp.moveaxis(
                jnp.asarray(arr, dt)[adapter_ids], 1, 0),
            dict(params["adapters"]))

    # The caches ride the scan as xs/ys (sliced per layer on the leading
    # axis, re-stacked from the per-layer outputs) — NOT as carry with
    # `cache.at[idx].set(...)`.  Indexed whole-cache updates in the body
    # compile to a copy of the full [L, b, s, h, d] buffer per layer per
    # token (measured 235 ms/token for a 188M model on v5e — ~20 GB of
    # HBM traffic per 128-token request); scan ys write each layer's
    # slice in place.
    def body(x, inputs):
        if adapter_stack is None:
            layer_params, ck, cv = inputs
            ad = None
        else:
            layer_params, ck, cv, ad = inputs
        x, (ck, cv) = _layer_step(
            cfg, layer_params, x, (ck, cv), cache_len, positions,
            pad_amount=pad_amount, write_cols=write_cols,
            tables=tables, adapters=ad,
        )
        return x, (ck, cv)

    cache_k, cache_v = cache
    xs = (layer_stack, cache_k, cache_v)
    if adapter_stack is not None:
        xs = xs + (adapter_stack,)
    x, (cache_k, cache_v) = jax.lax.scan(body, x, xs)

    scale = params["final_norm"]["scale"]
    x32 = x.astype(jnp.float32)
    x = (x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6) * scale
    ).astype(dt)
    if cfg.tied_embeddings:
        logits = qeinsum("bse,ve->bsv", x, embed, dt)
    else:
        logits = qeinsum("bse,ev->bsv", x, params["w_out"], dt)
    return logits.astype(jnp.float32), (cache_k, cache_v)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               kv_cache_dtype: str = "model"):
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    if kv_cache_dtype == "int8":
        def buf():
            return QTensor(
                jnp.zeros(shape, jnp.int8),
                jnp.zeros(shape[:-1], jnp.float32),
                (-1,),
            )

        return (buf(), buf())
    if kv_cache_dtype != "model":
        raise ValueError(f"unknown kv_cache_dtype {kv_cache_dtype!r}")
    return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


def _filter_logits(decode: DecodeConfig, logits: jax.Array) -> jax.Array:
    """Temperature/top_k/top_p-filtered logits ([..., vocab]), shared by
    generate()'s batched sampler and the slot engine's per-slot one.
    Static-shape TPU code: a top_k threshold compare and a sorted-cumsum
    mask — no dynamic vocabulary subsets."""
    logits = logits / decode.temperature
    if decode.top_k > 0:
        # Clamp to the vocabulary: an oversized k means "no filter",
        # not a trace-time lax.top_k error on the first request.
        k = min(decode.top_k, logits.shape[-1])
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
        logits = jnp.where(logits >= kth, logits, -jnp.inf)
    if decode.top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
        cum = jnp.cumsum(
            jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
        # Keep every token whose PRECEDING mass is < p (so the
        # boundary token crossing p stays in, matching the
        # standard nucleus definition), then threshold by the
        # smallest kept logit.
        keep = cum - jax.nn.softmax(sorted_logits, axis=-1) \
            < decode.top_p
        cutoff = jnp.min(
            jnp.where(keep, sorted_logits, jnp.inf),
            axis=-1, keepdims=True)
        logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
    return logits


@partial(jax.jit, static_argnums=(0, 3))
def generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,
    decode: DecodeConfig = DecodeConfig(),
    rng: Optional[jax.Array] = None,
    prompt_len: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """prompt [b, t] -> (tokens [b, t+max_new], logits_last [b, vocab]).

    One jitted program: prefill the prompt, then scan max_new_tokens
    single-token steps against the cache.  With ``eos_token >= 0`` the
    step loop exits early once every row is done; tokens are identical
    to the fixed-length run (pads are 0), and logits_last are from the
    exit step rather than after max_new_tokens of pad-forwarding.

    prompt_len ([b] int32, optional): per-row real prompt lengths for
    LEFT-padded prompts — rows shorter than t carry (t - len) pad
    tokens on the left.  Pad keys are masked out of every attention
    and rope positions count from the first real token, so a padded
    row decodes exactly as it would alone at its natural length.
    This is what lets mixed-length requests share one bucketed batch
    (serving/model_server.py BucketedLMBatcher).
    """
    b, t = prompt.shape
    max_len = t + decode.max_new_tokens
    cache = init_cache(cfg, b, max_len, decode.kv_cache_dtype)
    if rng is None:
        rng = jax.random.key(0)
    pad_amount = None if prompt_len is None else t - prompt_len

    logits, cache = _forward_with_cache(cfg, params, prompt, cache, 0,
                                        pad_amount=pad_amount)
    last = logits[:, -1]

    def sample(logits, key):
        if decode.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        return jax.random.categorical(
            key, _filter_logits(decode, logits), axis=-1)

    def step(carry, _):
        cache, last_logits, cache_len, key, done = carry
        key, sub = jax.random.split(key)
        nxt = sample(last_logits, sub)
        nxt = jnp.where(done, jnp.zeros_like(nxt), nxt)
        logits, cache = _forward_with_cache(
            cfg, params, nxt[:, None], cache, cache_len,
            pad_amount=pad_amount)
        done = done | (nxt == decode.eos_token)
        return (cache, logits[:, -1], cache_len + 1, key, done), nxt

    done0 = jnp.zeros((b,), bool)
    if decode.eos_token >= 0:
        # EOS configured: early-exit with lax.while_loop the moment
        # every row is done — completions shorter than max_new_tokens
        # stop paying per-token forwards.  Emitted TOKENS are identical
        # to the fixed-length scan (done rows emit 0s, and the output
        # buffer starts zeroed), so the goldens hold either way; the
        # returned final logits are those of the step the loop exited
        # at (the scan path kept forwarding pad zeros and returned
        # logits after step max_new_tokens — values no caller should
        # score from anyway once every row is done).
        out0 = jnp.zeros((decode.max_new_tokens, b), jnp.int32)

        def cond(state):
            i, carry, _ = state
            done = carry[4]
            return (i < decode.max_new_tokens) & ~jnp.all(done)

        def body(state):
            i, carry, out = state
            carry, nxt = step(carry, None)
            return i + 1, carry, jax.lax.dynamic_update_index_in_dim(
                out, nxt.astype(jnp.int32), i, axis=0)

        _, (_, final_logits, _, _, _), new_tokens = jax.lax.while_loop(
            cond, body, (0, (cache, last, t, rng, done0), out0))
    else:
        (_, final_logits, _, _, _), new_tokens = jax.lax.scan(
            step, (cache, last, t, rng, done0), None,
            length=decode.max_new_tokens)
    tokens = jnp.concatenate([prompt, new_tokens.T], axis=1)
    return tokens, final_logits


# ---------------------------------------------------------------------------
# Continuous-batching slot engine: jitted programs over a PERSISTENT
# PAGED KV block pool (serving/engine.py drives them).
#
# generate() is one program per (batch, bucket) that owns its rows from
# prefill to the last token — a row admitted mid-generation waits for the
# whole program, and every row pays the batch bucket's padded KV span.
# These entry points split that lifecycle so a serving loop can interleave
# admission with decode.  The unified KV store is a device-side BLOCK
# POOL — [layers, num_blocks, block_tokens, hkv, d], fp or int8 QTensor
# alike — and every program takes the current per-slot block tables as
# a plain argument: which pool block backs which logical block of which
# slot is HOST bookkeeping (serving/prefix_cache.py BlockManager), so
# capacity is bounded by TOKENS RESIDENT rather than slots x max_len,
# and sharing a cached prefix between slots is a refcounted table edit
# (zero device copies; no copy program exists).
#
#   prefill_chunk_into_slot  EXTEND a slot's KV by a static chunk width
#                            starting at a traced offset — the serving
#                            loop splits long prompts into chunks and
#                            schedules them BETWEEN decode steps, so an
#                            arriving prompt can never stall in-flight
#                            decode for longer than one chunk.  Also
#                            FREEZES the slot (done=True) until the
#                            final chunk arms it — the engine dispatches
#                            the first chunk at claim time, which is
#                            what makes reusing a deadline-expired
#                            slot safe
#   decode_step              ALL live slots advance one token, each at
#                            its OWN length (per-row rope position,
#                            per-row causal frontier, per-row block-
#                            scatter through its table)
#   verify_step              speculative decoding: score k host-drafted
#                            candidate tokens per slot in ONE forward
#                            pass at each slot's frontier, accept the
#                            longest exact greedy prefix (+1 token from
#                            the verify logits), and roll rejected
#                            columns back by NOT advancing cache_len
#                            over them — the cache_len-gated attention
#                            masks stale columns past the frontier, so
#                            rollback is a length reset, not a scatter-
#                            erase (the engine additionally returns the
#                            rejected tail's blocks to the pool)
#
# Static shapes throughout: slot count, chunk width, pool geometry,
# draft width, and the per-slot table span are fixed at engine
# construction, so the whole serving lifetime compiles at most THREE
# programs (chunked prefill, step, verify — the third only when
# speculation is enabled).  Retirement is a device-side `done` flag (a
# slot that hits its stop length or EOS stops advancing and drops its
# block writes), so freeing + reusing a slot needs no extra program —
# the next admission's first chunk freezes and overwrites it.
# ---------------------------------------------------------------------------


def init_paged_state(cfg: TransformerConfig, slots: int,
                     num_blocks: int, block_tokens: int,
                     kv_cache_dtype: str = "model"):
    """Fresh paged engine state: every slot retired, block pool zeroed.

    The state dict is the carry the jitted entry points thread (and
    donate): the [layers, num_blocks, block_tokens, hkv, d] KV block
    pool plus per-slot scalars — lengths (valid cache positions),
    stop_len (length at which the slot stops sampling), last_token
    (sampled but not yet in cache), done, a per-slot PRNG key
    (uint32[2]) so temperature sampling is per-REQUEST deterministic
    regardless of co-batched slots, and adapter_ids — each slot's
    index into the stacked adapter-delta array (0 = base; armed by
    prefill_chunk_into_slot, read by every step program, inert when
    the params tree carries no adapter stack).  Block tables are NOT
    device state: the host owns them and passes the current snapshot
    into every program call.
    """
    cache_k, cache_v = init_cache(cfg, num_blocks, block_tokens,
                                  kv_cache_dtype)
    return {
        "cache_k": cache_k,
        "cache_v": cache_v,
        "lengths": jnp.zeros((slots,), jnp.int32),
        "stop_len": jnp.zeros((slots,), jnp.int32),
        "last_token": jnp.zeros((slots,), jnp.int32),
        "done": jnp.ones((slots,), bool),
        "keys": jnp.zeros((slots, 2), jnp.uint32),
        "adapter_ids": jnp.zeros((slots,), jnp.int32),
    }


def _pool_block_tokens(cache) -> int:
    """Static block width of a paged pool array ([L, NB, bt, ...])."""
    vals = cache.values if isinstance(cache, QTensor) else cache
    return vals.shape[2]


@partial(jax.jit, donate_argnums=(0,))
def import_kv_pages(state, pages_k, pages_v, ids):
    """Disaggregated-serving KV handoff, device side: scatter a list
    of transferred block PAGES into this engine's pool at physical
    blocks ``ids`` ([n] int32; entries holding the pool-size sentinel
    are padding and drop).  ``pages_k``/``pages_v`` are
    [layers, n, block_tokens, hkv, d] page stacks (QTensor values +
    scale for int8 pools) — exactly the prefill replica's pool rows,
    so after the scatter the decode replica's pool holds bit-identical
    k/v and the slot resumes through the ordinary cached-prefix path
    (chunked prefill from the covered offset).  ``n`` is static (the
    engine pads to its table span), so one compiled program covers
    every handoff; it runs once per imported request, never in the
    step loop."""
    nb = (state["cache_k"].values if isinstance(state["cache_k"], QTensor)
          else state["cache_k"]).shape[1]
    ids = jnp.where(ids < nb, ids, nb)

    def scatter(pool, pages):
        if isinstance(pool, QTensor):
            return QTensor(
                pool.values.at[:, ids].set(pages.values, mode="drop"),
                pool.scale.at[:, ids].set(pages.scale, mode="drop"),
                pool.axes)
        return pool.at[:, ids].set(pages.astype(pool.dtype),
                                   mode="drop")

    state = dict(state)
    state["cache_k"] = scatter(state["cache_k"], pages_k)
    state["cache_v"] = scatter(state["cache_v"], pages_v)
    return state


def gather_kv_pages(state, ids):
    """The inverse of ``import_kv_pages``, host side: pull physical
    blocks ``ids`` out of the pool as HOST page stacks — one batched
    fancy index per pool side ([layers, n, block_tokens, hkv, d] in a
    single transfer, never a per-block loop).  Returns
    ``((k_vals, k_scale), (v_vals, v_scale))`` as numpy arrays (scale
    is None for fp pools).  Deliberately NOT jitted: ``n`` varies per
    record and a traced gather would mint a new executable per shape,
    breaking the engine's compiled-program guarantee.  Feeds the KV
    export handoff (§5.9) and the host spill tier (§5.10); callers run
    it on the engine loop thread only, between program dispatches,
    because the pool buffers are donated to the step programs."""
    ids = np.asarray(ids, np.int32)

    def gather(pool):
        if isinstance(pool, QTensor):
            return (np.asarray(pool.values[:, ids]),
                    np.asarray(pool.scale[:, ids]))
        return np.asarray(pool[:, ids]), None

    return gather(state["cache_k"]), gather(state["cache_v"])


def _advance_slots(cfg: TransformerConfig, params, decode: DecodeConfig,
                   tables: jax.Array, park, state):
    """One batched decode step over every slot: the shared body of
    ``decode_step`` and ``decode_rounds``.  Returns (state, nxt [S])
    where ``nxt`` is the sampled token per slot (0 for frozen slots).
    ``park`` is the column past the table span where retired slots
    aim their dropped cache writes."""
    lengths, done = state["lengths"], state["done"]
    advance = ~done
    # Retired slots park their write past the table span; the
    # block scatter drops it.
    write_cols = jnp.where(advance, lengths, park)
    logits, (ck, cv) = _forward_with_cache(
        cfg, params, state["last_token"][:, None],
        (state["cache_k"], state["cache_v"]), lengths,
        write_cols=write_cols, tables=tables,
        adapter_ids=state.get("adapter_ids"))
    last = logits[:, -1]
    if decode.temperature <= 0.0:
        nxt = jnp.argmax(last, axis=-1)
        keys = state["keys"]
    else:
        # Per-slot keys, split per step: slot r's sample stream
        # depends only on its own seed and step index, never on
        # which other requests happen to share the batch.
        split = jax.vmap(jax.random.split)(state["keys"])
        keys, subs = split[:, 0], split[:, 1]
        nxt = jax.vmap(jax.random.categorical)(
            subs, _filter_logits(decode, last))
    nxt = jnp.where(advance, nxt.astype(jnp.int32), 0)
    new_lengths = lengths + advance.astype(jnp.int32)
    new_done = done | (new_lengths >= state["stop_len"])
    if decode.eos_token >= 0:
        new_done = new_done | (advance & (nxt == decode.eos_token))
    state = dict(state)
    state["cache_k"], state["cache_v"] = ck, cv
    state["lengths"] = new_lengths
    state["last_token"] = nxt
    state["done"] = new_done
    state["keys"] = keys
    return state, nxt


@partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=(2,))
def decode_step(cfg: TransformerConfig, params, state,
                decode: DecodeConfig, steps: int, tables: jax.Array):
    """Advance every live slot; returns (state, sampled [steps, S]).

    One batched forward at t=1 per step: each slot ropes at its own
    length, attends under its own causal frontier (vector kv_offset)
    over its block-table-gathered view of the pool, and scatters its
    new k/v to its own (block, offset) through ``tables``
    ([S, max_blocks] int32, host-owned).  Retired slots ride along
    with dropped writes and zero emissions — the static shape never
    changes, so this is the engine's single step program for its
    whole lifetime.

    ``steps`` (static) fuses that many steps into one program via scan:
    per-call dispatch and runtime overhead amortize over k tokens at
    the cost of k-token admission granularity (slots finishing mid-call
    freeze via `done` on device, so at most k-1 slot-steps idle).  One
    engine uses ONE value, so the three-program guarantee holds.
    """
    park = tables.shape[1] * _pool_block_tokens(state["cache_k"])

    def one(state, _):
        return _advance_slots(cfg, params, decode, tables, park, state)

    if steps == 1:  # skip the scan wrapper on the canonical path
        state, toks = one(state, None)
        return state, toks[None]
    state, toks = jax.lax.scan(one, state, None, length=steps)
    return state, toks


@partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=(2,))
def decode_rounds(cfg: TransformerConfig, params, state,
                  decode: DecodeConfig, k: int, tables: jax.Array,
                  max_steps: jax.Array):
    """Device-resident multi-step decode: up to ``k`` decode steps in
    ONE dispatch via ``lax.while_loop``, with device-side early exit
    the moment every slot is done (EOS/budget) — the host never pays
    per-step dispatch, and a round that finishes all slots at step 3
    stops at step 3 instead of burning k-3 dead forwards.

    Returns ``(state, toks, counts, steps_run)``:

    - ``toks`` [S, k] int32, slot-major: slot s's tokens for this
      round occupy ``toks[s, :counts[s]]`` contiguously (a live slot
      advances every step from round start until it freezes, so its
      emissions never leave gaps), matching the verify drain's
      ``(arr, snapshot, counts)`` stream shape.
    - ``counts`` [S] int32: tokens emitted per slot (EOS included).
    - ``steps_run`` scalar int32: loop iterations actually executed.

    ``k`` is static (it sizes the output buffer and is the ceiling one
    compiled program serves); ``max_steps`` is a TRACED operand the
    host clamps per round, so adaptive round width reuses this single
    executable instead of compiling one program per width.  Block
    tables ride in unchanged as the host-owned snapshot — the host
    must pre-cover every slot for the worst case (``k`` new positions)
    before dispatch.  Per-step math is ``_advance_slots``, the same
    body ``decode_step`` runs, so greedy tokens are bit-identical to
    k single-step dispatches; under a mesh the loop body partitions
    exactly like ``decode_step`` does.
    """
    park = tables.shape[1] * _pool_block_tokens(state["cache_k"])
    slots = state["done"].shape[0]
    len0 = state["lengths"]
    cap = jnp.minimum(jnp.asarray(max_steps, jnp.int32),
                      jnp.int32(k))

    def cond(carry):
        i, state, _ = carry
        return (i < cap) & ~jnp.all(state["done"])

    def body(carry):
        i, state, out = carry
        state, nxt = _advance_slots(cfg, params, decode, tables, park,
                                    state)
        return i + 1, state, out.at[:, i].set(nxt)

    steps_run, state, toks = jax.lax.while_loop(
        cond, body,
        (jnp.zeros((), jnp.int32), state,
         jnp.zeros((slots, k), jnp.int32)))
    counts = state["lengths"] - len0
    return state, toks, counts, steps_run


@partial(jax.jit, static_argnums=(0, 3, 4), donate_argnums=(2,))
def verify_step(cfg: TransformerConfig, params, state,
                decode: DecodeConfig, k: int, draft: jax.Array,
                draft_len: jax.Array, tables: jax.Array):
    """Speculative verify: score up to ``k`` host-drafted tokens per
    slot in ONE forward pass; returns (state, tokens [S, k+1],
    emitted [S]).

    ``draft`` [S, k] carries each slot's candidate continuation
    (prompt-lookup / n-gram proposals — serving/engine.py drafts them
    host-side) and ``draft_len`` [S] how many are real (0 = the slot
    rides along undrafted, a mixed batch).  The forward runs at t =
    k+1 — column 0 is the slot's pending ``last_token``, columns 1..k
    the draft — with per-row rope positions, per-row causal frontiers,
    and per-row cache-column scatters, i.e. decode_step's math widened
    to a k+1 window, so position j's logits are bit-for-bit the logits
    the (j+1)-th sequential decode_step would have produced whenever
    the first j draft tokens match greedy decode.

    Acceptance is exact-match greedy (the engine only speculates at
    temperature 0, which is what makes speculation token-IDENTICAL to
    the non-speculative path): with ``a`` = the longest prefix of the
    draft equal to the argmax targets, the slot emits a+1 tokens —
    the a accepted drafts plus one free token from the verify logits
    (the first disagreement, or the bonus continuation after a full
    accept) — clipped to the slot's remaining budget and cut at EOS.

    Rollback is DEVICE-SIDE and free: the k+1 fresh k/v columns were
    written at [len, len + k] as the forward ran (through each slot's
    block table), but ``lengths`` advances only over the emitted
    prefix.  Columns past the new frontier hold rejected-draft garbage
    that the cache_len-gated attention masks out of every later call,
    and the next step's write window starts at the new frontier and
    overwrites them before its own attention runs — a length reset,
    never a scatter-erase (the engine additionally trims whole
    rejected-tail BLOCKS back to the pool host-side).  Retired slots
    park their writes out of range and emit 0 tokens, exactly like
    decode_step.
    """
    lengths, done = state["lengths"], state["done"]
    park = tables.shape[1] * _pool_block_tokens(state["cache_k"])
    advance = ~done
    write_cols = jnp.where(advance, lengths, park)
    tokens = jnp.concatenate(
        [state["last_token"][:, None], draft.astype(jnp.int32)], axis=1)
    logits, (ck, cv) = _forward_with_cache(
        cfg, params, tokens, (state["cache_k"], state["cache_v"]),
        lengths, write_cols=write_cols, tables=tables,
        adapter_ids=state.get("adapter_ids"))
    targets = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [S, k+1]
    # Longest accepted draft prefix (positions beyond draft_len never
    # match), then +1 free token, clipped to the per-slot budget: a
    # live slot always has stop_len - lengths >= 1 emission of room,
    # so every advancing slot nets at least one token per call — a
    # verify call never delivers less than a decode step would.
    pos = jnp.arange(k)[None, :]
    match = (draft.astype(jnp.int32) == targets[:, :k]) \
        & (pos < draft_len[:, None])
    accepted = jnp.sum(
        jnp.cumprod(match.astype(jnp.int32), axis=1), axis=1)
    emit = jnp.minimum(accepted + 1,
                       jnp.maximum(state["stop_len"] - lengths, 0))
    if decode.eos_token >= 0:
        is_eos = targets == decode.eos_token
        eos_cut = jnp.where(jnp.any(is_eos, axis=1),
                            jnp.argmax(is_eos, axis=1) + 1, k + 2)
        done_eos = advance & (eos_cut <= emit)
        emit = jnp.minimum(emit, eos_cut)
    else:
        done_eos = jnp.zeros_like(done)
    emit = jnp.where(advance, emit, 0)
    out = jnp.where(jnp.arange(k + 1)[None, :] < emit[:, None],
                    targets, 0)
    new_lengths = lengths + emit
    last_tok = jnp.take_along_axis(
        targets, jnp.maximum(emit - 1, 0)[:, None], axis=1)[:, 0]
    state = dict(state)
    state["cache_k"], state["cache_v"] = ck, cv
    state["lengths"] = new_lengths
    state["last_token"] = jnp.where(emit > 0, last_tok,
                                    state["last_token"])
    state["done"] = done | done_eos \
        | (advance & (new_lengths >= state["stop_len"]))
    return state, out, emit.astype(jnp.int32)


@partial(jax.jit, static_argnums=(0, 3), donate_argnums=(2,))
def prefill_chunk_into_slot(
    cfg: TransformerConfig,
    params,
    state,
    decode: DecodeConfig,
    tokens: jax.Array,
    start: jax.Array,
    prompt_len: jax.Array,
    new_tokens: jax.Array,
    slot: jax.Array,
    seed: jax.Array,
    table_row: jax.Array,
    adapter_id: Optional[jax.Array] = None,
):
    """Extend slot ``slot``'s KV by one static-width chunk of prompt
    starting at traced cache offset ``start``; returns
    (state, first sampled token [1]).

    adapter_id (traced int32 scalar, optional): the request's index
    into the stacked adapter-delta array (§5.11) — applied to THIS
    chunk's forward (prefill k/v must carry the tenant's delta too)
    and written to ``state["adapter_ids"][slot]`` so the step programs
    gather the same delta.  The write is unconditional at ``slot``
    (not gated on the final chunk): the freeze below already parks the
    slot, so an interleaved step reads a harmless id from a frozen
    row.  Omitted/None means base (0) and traces a separate program —
    engines without an adapter stack never pay the operand.

    tokens [1, chunk_w]: the prompt's tokens [start, start + chunk_w),
    right-padded past ``prompt_len`` on the final chunk.  table_row
    [1, max_blocks]: the slot's block table — fresh k/v scatter into
    the pool through it, and the chunk's queries attend over the
    slot's gathered pool view under the causal frontier ``start`` (the
    same ``cache_len``-gated attention path the decode scan uses with
    a traced offset), so earlier chunks' — or an aliased shared
    prefix's — k/v participate exactly as if the prompt had prefilled
    in one call, and garbage columns at/after start + chunk_w stay
    masked.  A resumed cached prefix needs NO device copy: the engine
    simply places the cached blocks in the table and starts the first
    chunk at the cached offset.  Chunk width is static and fixed per
    engine, so every admission, resumed at any offset, reuses ONE
    compiled program; the serving loop schedules these calls between
    decode steps under a token budget, which is what bounds how long
    an arriving prompt can stall in-flight decode.

    On the final chunk (start + chunk_w >= prompt_len, decided on
    device) the program samples the request's first token from the
    last real prompt position and arms the slot's scalars (lengths /
    stop_len / last_token / done / keys — what decode_step needs to
    advance the slot); intermediate chunks leave the slot frozen and
    park the scalar writes out of range.

    The unconditional ``done`` = True FREEZE is load-bearing: a slot
    freed by mid-generation deadline expiry still has ``done`` = False
    on device, so without it an interleaved decode_step would keep
    advancing the dead occupant and scatter garbage through the NEW
    request's block table.  The engine therefore dispatches the first
    chunk of every admission at claim time, before any step program
    can run.
    """
    slots_n = state["done"].shape[0]
    w = tokens.shape[1]
    aid = (jnp.zeros((), jnp.int32) if adapter_id is None
           else jnp.reshape(jnp.asarray(adapter_id, jnp.int32), ()))
    logits, (ck, cv) = _forward_with_cache(
        cfg, params, tokens, (state["cache_k"], state["cache_v"]),
        start, tables=table_row, adapter_ids=aid[None])
    # First-token sampling from the last REAL prompt position of this
    # chunk (only meaningful on the final chunk; clamped otherwise).
    idx = jnp.clip(prompt_len - 1 - start, 0, w - 1)
    last = jnp.take_along_axis(
        logits, jnp.reshape(idx, (1, 1, 1)), axis=1)[:, 0]  # [1, V]
    useed = jnp.reshape(seed, (1,)).astype(jnp.uint32)
    keys = jnp.stack([jnp.zeros_like(useed), useed], axis=-1)
    split = jax.vmap(jax.random.split)(keys)
    keys, subs = split[:, 0], split[:, 1]
    if decode.temperature <= 0.0:
        tok = jnp.argmax(last, axis=-1)
    else:
        tok = jax.vmap(jax.random.categorical)(
            subs, _filter_logits(decode, last))
    tok = tok.astype(jnp.int32)

    is_last = (start + w) >= prompt_len
    final_slot = jnp.where(is_last, slot, slots_n)  # OOB mid-prefill
    stop = prompt_len + jnp.maximum(new_tokens, 1) - 1
    done_final = new_tokens <= 1
    if decode.eos_token >= 0:
        done_final = done_final | (tok[0] == decode.eos_token)

    state = dict(state)
    state["cache_k"], state["cache_v"] = ck, cv
    if "adapter_ids" in state:
        state["adapter_ids"] = state["adapter_ids"].at[slot].set(aid)
    state["done"] = state["done"].at[slot].set(True)
    state["done"] = state["done"].at[final_slot].set(
        done_final, mode="drop")
    state["lengths"] = state["lengths"].at[final_slot].set(
        prompt_len, mode="drop")
    state["stop_len"] = state["stop_len"].at[final_slot].set(
        stop, mode="drop")
    state["last_token"] = state["last_token"].at[final_slot].set(
        tok[0], mode="drop")
    state["keys"] = state["keys"].at[final_slot].set(
        keys[0], mode="drop")
    return state, tok
