"""Autoregressive decoding for the Transformer LM.

The reference's serving story was TF-Serving for classifiers; LMs are this
framework's flagship, so decode is first-party.  TPU-shaped choices:

  - the KV cache is a preallocated [layers, 2, b, max_len, h, d] buffer
    carried through ``lax.scan`` — static shapes end to end, one compiled
    program for the whole generation;
  - prefill and decode are the same jitted function: the prompt is
    processed in one batched forward (MXU-efficient), then tokens stream
    one position at a time against the cache;
  - greedy or temperature sampling under ``jax.random``.

Kept outside the Flax module on purpose: the cache is explicit function
state (scan carry), not module state — no mutable-collection plumbing,
and the whole loop jits/shards like any other pure function.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from kubeflow_tpu.models.transformer import (
    Transformer,
    TransformerConfig,
    rope,
)
from kubeflow_tpu.ops.attention import dot_product_attention
from kubeflow_tpu.ops.quantize import (
    QTensor,
    embed_lookup,
    qeinsum,
    quantize_array,
)


@dataclasses.dataclass(frozen=True)
class DecodeConfig:
    max_new_tokens: int = 64
    temperature: float = 0.0   # 0 = greedy
    # Sampling filters (applied in this order when temperature > 0):
    # top_k keeps the k highest-logit tokens (0 = off); top_p keeps the
    # smallest set of tokens whose probability mass reaches p (1.0 =
    # off, i.e. nucleus sampling).  Both are static-shape TPU code: a
    # top_k threshold compare and a sorted-cumsum mask — no dynamic
    # vocabulary subsets.
    top_k: int = 0
    top_p: float = 1.0
    eos_token: int = -1        # -1 = never stop early

    def __post_init__(self):
        if not 0.0 < self.top_p <= 1.0:
            raise ValueError(
                f"top_p must be in (0, 1], got {self.top_p} "
                "(1.0 disables nucleus filtering)")
        if self.top_k < 0:
            raise ValueError(f"top_k must be >= 0, got {self.top_k}")
    # "model" = the model compute dtype; "int8" = quantized cache with
    # per-(position, head) scales (halves cache HBM traffic and memory —
    # the binding resource for batched decode; ops/attention.py folds the
    # scales through both matmuls so nothing dequantized materializes).
    kv_cache_dtype: str = "model"


def _layer_step(cfg: TransformerConfig, layer_params, x, cache_kv,
                cache_len, positions, pad_amount=None):
    """One decoder block against the KV cache.

    x: [b, t, e] new activations (t = prompt len at prefill, 1 at decode);
    cache_kv: (k, v) each [b, max_len, hkv, d];
    cache_len: number of valid cache positions before this call;
    pad_amount: per-row [b] left-pad width (bucketed mixed-length
    prompts) — cache columns before it hold pad-token garbage and are
    masked out of every attention.
    Mirrors models/transformer.py Block but with explicit cache state.
    """
    from kubeflow_tpu.models.transformer import MLP, RMSNorm

    attn = layer_params["attn"]
    dt = cfg.dtype

    def norm(x, scale):
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6)
        return (normed * scale).astype(dt)

    y = norm(x, layer_params["attn_norm"]["scale"])
    # qeinsum keeps int8 serving weights quantized through the dot
    # (per-output-channel scales applied after; ops/quantize.py).
    q = qeinsum("bse,ehd->bshd", y, attn["wq"], dt)
    k = qeinsum("bse,ehd->bshd", y, attn["wkv"][0], dt)
    v = qeinsum("bse,ehd->bshd", y, attn["wkv"][1], dt)
    q = rope(q, positions, cfg.rope_theta)
    k = rope(k, positions, cfg.rope_theta)

    ck, cv = cache_kv
    t = x.shape[1]
    if isinstance(ck, QTensor):
        def store(c, new):
            vals, s = quantize_array(new, (-1,))    # [b, t, hk, d]
            return QTensor(
                jax.lax.dynamic_update_slice_in_dim(
                    c.values, vals, cache_len, axis=1),
                jax.lax.dynamic_update_slice_in_dim(
                    c.scale, s, cache_len, axis=1),
                c.axes,
            )

        ck = store(ck, k)
        cv = store(cv, v)
    else:
        ck = jax.lax.dynamic_update_slice_in_dim(ck, k.astype(ck.dtype),
                                                 cache_len, axis=1)
        cv = jax.lax.dynamic_update_slice_in_dim(cv, v.astype(cv.dtype),
                                                 cache_len, axis=1)
    # Attend over the whole buffer; positions beyond cache_len + t are
    # masked by the causal rule (their k_pos > any live q_pos... they are
    # zeros at positions >= cache_len+t, masked via kv_offset arithmetic).
    #
    # Prefill of a LONG prompt on a flash-configured model uses the
    # Pallas flash kernel over the fresh q/k/v instead (the cache is
    # empty at prefill, so causal attention over the prompt alone is the
    # whole computation): the dot path materializes the [b, h, t, t]
    # score matrix in HBM — O(t^2) memory that defeats the point of
    # serving a long-context model whose TRAINING path is O(t).
    # Left-padded bucketed batches ride the kernel's forward-only
    # per-row key-start mask (kv_valid_start — pad keys get zero
    # weight), so DEPLOYED bucketed serving flash-prefills too.  Gated
    # off only for quantized caches (the dot path attends against the
    # freshly quantized cache, and serving goldens pin that rounding).
    # cache_len is a static python 0 at prefill and a TRACED scalar in
    # the decode scan — the gate must only ever inspect the static case.
    static_prefill = isinstance(cache_len, int) and cache_len == 0
    if (cfg.attention == "flash" and t > 1 and static_prefill
            and not isinstance(ck, QTensor)):
        from kubeflow_tpu.ops.flash import flash_attention

        out = flash_attention(
            q, k, v, causal=True,
            block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
            kv_valid_start=pad_amount,
        )
    else:
        out = dot_product_attention(
            q, ck, cv, causal=True, kv_offset=cache_len,
            kv_valid_start=pad_amount,
        )
    y = qeinsum("bshd,hde->bse", out, attn["wo"], dt)
    x = x + y
    y = norm(x, layer_params["mlp_norm"]["scale"])
    mlp = layer_params["mlp"]
    gate = qeinsum("bse,ef->bsf", y, mlp["wi"][0], dt)
    up = qeinsum("bse,ef->bsf", y, mlp["wi"][1], dt)
    h = jax.nn.silu(gate) * up
    y = qeinsum("bsf,fe->bse", h, mlp["wo"], dt)
    return x + y, (ck, cv)


def _forward_with_cache(cfg: TransformerConfig, params, tokens, cache,
                        cache_len, pad_amount=None):
    """tokens [b, t] -> (logits [b, t, v], new cache)."""
    from flax import linen as nn

    params = nn.unbox(params)  # accept raw model.init output
    dt = cfg.dtype
    embed = params["embed"]
    x = embed_lookup(embed, tokens, dt)  # int8-aware row gather
    positions = cache_len + jnp.arange(tokens.shape[1])[None, :]
    positions = jnp.broadcast_to(positions, tokens.shape)
    if pad_amount is not None:
        # Left-padded rows: real token i of a row sits at buffer column
        # pad + i but must see rope position i.  Pad columns clamp to 0
        # — their keys are masked from every attention anyway.
        positions = jnp.maximum(positions - pad_amount[:, None], 0)

    layer_stack = params["layers"]

    # The caches ride the scan as xs/ys (sliced per layer on the leading
    # axis, re-stacked from the per-layer outputs) — NOT as carry with
    # `cache.at[idx].set(...)`.  Indexed whole-cache updates in the body
    # compile to a copy of the full [L, b, s, h, d] buffer per layer per
    # token (measured 235 ms/token for a 188M model on v5e — ~20 GB of
    # HBM traffic per 128-token request); scan ys write each layer's
    # slice in place.
    def body(x, inputs):
        layer_params, ck, cv = inputs
        x, (ck, cv) = _layer_step(
            cfg, layer_params, x, (ck, cv), cache_len, positions,
            pad_amount=pad_amount,
        )
        return x, (ck, cv)

    cache_k, cache_v = cache
    x, (cache_k, cache_v) = jax.lax.scan(
        body, x, (layer_stack, cache_k, cache_v))

    scale = params["final_norm"]["scale"]
    x32 = x.astype(jnp.float32)
    x = (x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + 1e-6) * scale
    ).astype(dt)
    if cfg.tied_embeddings:
        logits = qeinsum("bse,ve->bsv", x, embed, dt)
    else:
        logits = qeinsum("bse,ev->bsv", x, params["w_out"], dt)
    return logits.astype(jnp.float32), (cache_k, cache_v)


def init_cache(cfg: TransformerConfig, batch: int, max_len: int,
               kv_cache_dtype: str = "model"):
    shape = (cfg.n_layers, batch, max_len, cfg.n_kv_heads, cfg.head_dim)
    if kv_cache_dtype == "int8":
        def buf():
            return QTensor(
                jnp.zeros(shape, jnp.int8),
                jnp.zeros(shape[:-1], jnp.float32),
                (-1,),
            )

        return (buf(), buf())
    if kv_cache_dtype != "model":
        raise ValueError(f"unknown kv_cache_dtype {kv_cache_dtype!r}")
    return (jnp.zeros(shape, cfg.dtype), jnp.zeros(shape, cfg.dtype))


@partial(jax.jit, static_argnums=(0, 3))
def generate(
    cfg: TransformerConfig,
    params,
    prompt: jax.Array,
    decode: DecodeConfig = DecodeConfig(),
    rng: Optional[jax.Array] = None,
    prompt_len: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """prompt [b, t] -> (tokens [b, t+max_new], logits_last [b, vocab]).

    One jitted program: prefill the prompt, then scan max_new_tokens
    single-token steps against the cache.  With ``eos_token >= 0`` the
    step loop exits early once every row is done; tokens are identical
    to the fixed-length run (pads are 0), and logits_last are from the
    exit step rather than after max_new_tokens of pad-forwarding.

    prompt_len ([b] int32, optional): per-row real prompt lengths for
    LEFT-padded prompts — rows shorter than t carry (t - len) pad
    tokens on the left.  Pad keys are masked out of every attention
    and rope positions count from the first real token, so a padded
    row decodes exactly as it would alone at its natural length.
    This is what lets mixed-length requests share one bucketed batch
    (serving/model_server.py BucketedLMBatcher).
    """
    b, t = prompt.shape
    max_len = t + decode.max_new_tokens
    cache = init_cache(cfg, b, max_len, decode.kv_cache_dtype)
    if rng is None:
        rng = jax.random.key(0)
    pad_amount = None if prompt_len is None else t - prompt_len

    logits, cache = _forward_with_cache(cfg, params, prompt, cache, 0,
                                        pad_amount=pad_amount)
    last = logits[:, -1]

    def sample(logits, key):
        if decode.temperature <= 0.0:
            return jnp.argmax(logits, axis=-1)
        logits = logits / decode.temperature
        if decode.top_k > 0:
            # Clamp to the vocabulary: an oversized k means "no filter",
            # not a trace-time lax.top_k error on the first request.
            k = min(decode.top_k, logits.shape[-1])
            kth = jax.lax.top_k(logits, k)[0][..., -1:]
            logits = jnp.where(logits >= kth, logits, -jnp.inf)
        if decode.top_p < 1.0:
            sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
            cum = jnp.cumsum(
                jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
            # Keep every token whose PRECEDING mass is < p (so the
            # boundary token crossing p stays in, matching the
            # standard nucleus definition), then threshold by the
            # smallest kept logit.
            keep = cum - jax.nn.softmax(sorted_logits, axis=-1) \
                < decode.top_p
            cutoff = jnp.min(
                jnp.where(keep, sorted_logits, jnp.inf),
                axis=-1, keepdims=True)
            logits = jnp.where(logits >= cutoff, logits, -jnp.inf)
        return jax.random.categorical(key, logits, axis=-1)

    def step(carry, _):
        cache, last_logits, cache_len, key, done = carry
        key, sub = jax.random.split(key)
        nxt = sample(last_logits, sub)
        nxt = jnp.where(done, jnp.zeros_like(nxt), nxt)
        logits, cache = _forward_with_cache(
            cfg, params, nxt[:, None], cache, cache_len,
            pad_amount=pad_amount)
        done = done | (nxt == decode.eos_token)
        return (cache, logits[:, -1], cache_len + 1, key, done), nxt

    done0 = jnp.zeros((b,), bool)
    if decode.eos_token >= 0:
        # EOS configured: early-exit with lax.while_loop the moment
        # every row is done — completions shorter than max_new_tokens
        # stop paying per-token forwards.  Emitted TOKENS are identical
        # to the fixed-length scan (done rows emit 0s, and the output
        # buffer starts zeroed), so the goldens hold either way; the
        # returned final logits are those of the step the loop exited
        # at (the scan path kept forwarding pad zeros and returned
        # logits after step max_new_tokens — values no caller should
        # score from anyway once every row is done).
        out0 = jnp.zeros((decode.max_new_tokens, b), jnp.int32)

        def cond(state):
            i, carry, _ = state
            done = carry[4]
            return (i < decode.max_new_tokens) & ~jnp.all(done)

        def body(state):
            i, carry, out = state
            carry, nxt = step(carry, None)
            return i + 1, carry, jax.lax.dynamic_update_index_in_dim(
                out, nxt.astype(jnp.int32), i, axis=0)

        _, (_, final_logits, _, _, _), new_tokens = jax.lax.while_loop(
            cond, body, (0, (cache, last, t, rng, done0), out0))
    else:
        (_, final_logits, _, _, _), new_tokens = jax.lax.scan(
            step, (cache, last, t, rng, done0), None,
            length=decode.max_new_tokens)
    tokens = jnp.concatenate([prompt, new_tokens.T], axis=1)
    return tokens, final_logits
