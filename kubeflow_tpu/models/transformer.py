"""Decoder-only Transformer LM — the framework's flagship model.

New capability relative to the reference (its model zoo was whatever TF
image you mounted; SURVEY.md §2.2), designed TPU-first:

  - bfloat16 activations, fp32 params; every matmul MXU-shaped
    (d_model/d_ff/head_dim multiples of 128 in real configs);
  - logical-axis annotations on every kernel (nn.with_logical_partitioning)
    so the parallel/mesh.py rule table alone decides dp/fsdp/tp/sp layout;
  - layers stacked with ``nn.scan``: one compiled block body regardless of
    depth (compile time O(1) in n_layers), with selective rematerialisation
    via ``nn.remat`` to trade FLOPs for HBM;
  - RoPE positions, RMSNorm, SwiGLU MLP, grouped-query attention —
    the contemporary LLM block;
  - attention dispatches to ops/ (XLA now, Pallas flash / ring attention
    over the `sequence` axis for long context).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from kubeflow_tpu.ops.attention import dot_product_attention

Dtype = Any

init = nn.initializers
kernel_init = init.lecun_normal()
embed_init = init.normal(stddev=0.02)


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32_000
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    n_kv_heads: int = 8
    d_ff: int = 1408
    head_dim: int = 64
    max_seq_len: int = 2048
    rope_theta: float = 10_000.0
    dropout_rate: float = 0.0
    dtype: Dtype = jnp.bfloat16
    remat: bool = False
    # Checkpoint policy under remat: "nobatch" saves only dots without
    # batch dims (minimum memory); "dots" saves every matmul output so
    # backward recomputes only elementwise/norms.  Measured equal on
    # v5e at the bench config (231 vs 233 ms/step — the flash kernel
    # recomputes its own internals either way), so the default is the
    # memory-minimal policy.
    remat_policy: str = "nobatch"
    # Save the flash kernel's (out, lse) residuals across the remat
    # boundary.  The Pallas custom call is invisible to dots_saveable, so
    # without this every rematted block re-runs the forward flash kernel
    # inside the backward pass just to rebuild the residuals its backward
    # kernels need — one full extra fwd attention pass per step (measured
    # ~13 ms/step at the v5e bench config, 231 -> 218 ms/step when saved).
    # Costs O(b*s*d) bf16 per layer of extra live memory; disable only
    # when that doesn't fit.
    save_attn_residuals: bool = True
    # Tie input embedding and output projection (small models benefit).
    tied_embeddings: bool = True
    # Attention backend: "dot" (XLA einsum), "flash" (Pallas kernel, heads
    # TP-sharded via shard_map when a mesh is given), "ring" (context
    # parallel over the `sequence` mesh axis; requires a mesh).
    attention: str = "dot"
    # On-chip sweep (v5e, seq 2048, head_dim 128, bench.py --model=lm):
    # k-block 1024 runs 4.8% faster than the old 512 default (231 vs
    # 242 ms/step); 2048 gives it back (234), larger q-blocks lose.
    # _fit_block clamps both to the actual sequence length.
    flash_block_q: int = 512
    flash_block_k: int = 1024
    # >0 = two-pass causal forward (ops/flash.py): full blocks at
    # (block_q, block_k) mask-free + the diagonal band at this fine
    # tiling, merged in log space — shrinks the masked-MAC waste of
    # diagonal-straddling blocks.  0 = classic single pass.
    flash_block_diag: int = 0
    # Mixture-of-Experts: 0 = dense MLP; >0 replaces every block's MLP
    # with a MoE layer of that many experts (expert-parallel over the
    # `expert` mesh axis; models/moe.py).
    moe_experts: int = 0
    moe_top_k: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_coef: float = 0.01
    # GShard routing group (tokens); dispatch-einsum cost per token is
    # proportional to it, capacity granularity inversely.  0 = the
    # measured per-impl optimum (einsum 128, gather 256 — each impl's
    # best from the on-chip sweeps; pinning one shared default would
    # silently pair the other impl with its worst config).  Sweep
    # history at the bench config (4 experts, ms/step): round-3
    # G-major einsums 128 -> 516, 256 -> 471, 512 -> 495,
    # 1024 -> 528; after the round-4 E-major rank-3 rework 64 -> 423,
    # 128 -> 421, 256 -> 427 — see models/moe.py for why the optimum
    # moved.
    moe_group_size: int = 0
    # MoE dispatch/combine implementation: "einsum" (GShard one-hot
    # contractions — the measured on-chip winner, MXU-bound) or
    # "gather" (slot-index scatter + row gathers, no O(g) contraction,
    # but XLA's dynamic-gather lowering loses ~12% end to end).  See
    # models/moe.py MoEMLP.impl for the sweep numbers.
    moe_impl: str = "einsum"
    # Cross-entropy input precision.  "f32" materializes the full
    # [b, s, vocab] logits tensor in float32 before the loss (simple,
    # maximally precise).  "compute" keeps logits in the compute dtype
    # and evaluates a fused max/logsumexp/gather loss with f32
    # accumulation — on a bf16 model the 4-byte logits copy (2.1 GB at
    # the bench config) never exists in HBM, and the loss cotangent is
    # half the bytes.  Loss differs only in bf16 rounding of individual
    # logits (reductions still accumulate f32).
    ce_dtype: str = "f32"
    # Sequence-chunked cross-entropy: >0 unembeds and evaluates the
    # loss `ce_chunk` positions at a time under a rematerialized
    # lax.scan, so no [b, s, vocab] logits tensor ever exists in HBM
    # (peak extra memory is O(b * chunk * vocab)).  The long-context
    # loss lever above ce_dtype: at seq 128k even bf16 logits are
    # 8.4 GB.  The effective chunk is the largest divisor of s <= this
    # (any s works); numerics follow ce_dtype within each chunk.
    # 0 = unchunked.
    ce_chunk: int = 0
    # Pipeline parallelism: >0 streams this many microbatches through the
    # layer stack under the GPipe schedule (parallel/pipeline.py) whenever
    # the model's mesh has a `pipeline` axis > 1.  The nn.scan param stack
    # [L, ...] is sharded L/S layers per stage via the ("layers", PIPELINE)
    # rule; embed / final norm / logits stay replicated across stages.
    # 0 (or a pipeline-less mesh) runs the plain sequential scan.
    pipeline_microbatches: int = 0

    def __post_init__(self):
        assert self.n_heads % self.n_kv_heads == 0
        if self.ce_dtype not in ("f32", "compute"):
            raise ValueError(
                f"ce_dtype={self.ce_dtype!r} not in ('f32', 'compute')")
        if self.pipeline_microbatches:
            # MoE composes (aux losses ride pipelined_scan's with_aux
            # accumulator) and ring composes (the GPipe shard_map goes
            # manual over {pipeline, sequence} and calls the per-shard
            # ring body directly — see _pipelined_layers).  Dropout is
            # the one documented residual: the functional per-layer
            # body threads no flax rngs, and every shipped config
            # trains at dropout 0 (the contemporary LLM default), so
            # the rng plumbing would be dead weight on the hot path.
            if self.dropout_rate:
                raise ValueError(
                    "pipeline_microbatches requires dropout_rate=0 "
                    "(the GPipe functional body does not thread "
                    "dropout rngs; all shipped configs train "
                    "dropout-free)")

    def resolved_moe_group_size(self) -> int:
        """The routing group actually used: the configured value, or
        each impl's measured on-chip optimum when left at 0 (the
        single source of truth is models/moe.py default_group_size)."""
        if self.moe_group_size:
            return self.moe_group_size
        from kubeflow_tpu.models.moe import default_group_size

        return default_group_size(self.moe_impl)

    def flops_per_token(self) -> float:
        """Forward useful FLOPs per token (2*params matmul convention +
        attention term) — the MFU numerator, bwd counted as 2x by caller."""
        p_attn = self.d_model * self.head_dim * (
            self.n_heads + 2 * self.n_kv_heads
        ) + self.n_heads * self.head_dim * self.d_model
        p_mlp = 3 * self.d_model * self.d_ff
        if self.moe_experts > 0:
            # Useful MLP flops per token = the top_k experts it routes to
            # plus the router matmul; idle experts' weights are not work.
            p_mlp = self.moe_top_k * p_mlp \
                + self.d_model * self.moe_experts
        p_embed = self.vocab_size * self.d_model
        matmul = 2 * (self.n_layers * (p_attn + p_mlp) + p_embed)
        attn = 2 * 2 * self.n_layers * self.n_heads * self.head_dim \
            * self.max_seq_len  # qk^T + av, causal halving ignored
        return float(matmul + attn)


def rope(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    """Rotary position embedding, applied per head. x: [b, s, h, d]."""
    d = x.shape[-1]
    freqs = theta ** (-jnp.arange(0, d, 2, dtype=jnp.float32) / d)
    angles = positions[..., None].astype(jnp.float32) * freqs  # [b, s, d/2]
    cos = jnp.cos(angles)[:, :, None, :]
    sin = jnp.sin(angles)[:, :, None, :]
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


class RMSNorm(nn.Module):
    dtype: Dtype = jnp.bfloat16
    eps: float = 1e-6

    @nn.compact
    def __call__(self, x):
        scale = self.param(
            "scale",
            nn.with_logical_partitioning(init.ones_init(), ("norm",)),
            (x.shape[-1],),
            jnp.float32,
        )
        x32 = x.astype(jnp.float32)
        norm = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps
        )
        return (norm * scale).astype(self.dtype)


class Attention(nn.Module):
    cfg: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None
    # Inside an enclosing shard_map that is ALREADY manual over the
    # `sequence` axis (the GPipe pipeline path): call the per-shard ring
    # body directly instead of wrapping a second shard_map — nested
    # manual regions over the same mesh do not compose, exposing the
    # body does (parallel/ring.py ring_attention's documented contract).
    ring_manual: bool = False

    def _attend(self, q, k, v, segment_ids):
        cfg = self.cfg
        if cfg.attention == "ring":
            if self.ring_manual:
                from kubeflow_tpu.parallel.ring import ring_attention

                return ring_attention(q, k, v, causal=True)
            if self.mesh is None:
                raise ValueError("attention='ring' requires a mesh")
            from kubeflow_tpu.parallel.ring import make_ring_attention

            return make_ring_attention(self.mesh, causal=True)(q, k, v)
        if cfg.attention == "flash":
            from kubeflow_tpu.ops.flash import (
                flash_attention,
                make_sharded_flash,
            )

            if self.mesh is not None:
                return make_sharded_flash(
                    self.mesh, causal=True,
                    block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
                    block_diag=cfg.flash_block_diag,
                )(q, k, v)
            return flash_attention(
                q, k, v, causal=True,
                block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
                block_diag=cfg.flash_block_diag,
            )
        return dot_product_attention(q, k, v, causal=True,
                                     segment_ids=segment_ids)

    @nn.compact
    def __call__(self, x, positions, segment_ids=None):
        cfg = self.cfg
        wq = self.param(
            "wq",
            nn.with_logical_partitioning(kernel_init, ("embed", "heads", "kv")),
            (cfg.d_model, cfg.n_heads, cfg.head_dim),
            jnp.float32,
        )
        wkv = self.param(
            "wkv",
            nn.with_logical_partitioning(kernel_init, (None, "embed", "heads", "kv")),
            (2, cfg.d_model, cfg.n_kv_heads, cfg.head_dim),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(kernel_init, ("heads", "kv", "embed")),
            (cfg.n_heads, cfg.head_dim, cfg.d_model),
            jnp.float32,
        )
        dt = cfg.dtype
        q = jnp.einsum("bse,ehd->bshd", x, wq.astype(dt))
        k = jnp.einsum("bse,ehd->bshd", x, wkv[0].astype(dt))
        v = jnp.einsum("bse,ehd->bshd", x, wkv[1].astype(dt))
        q = rope(q, positions, cfg.rope_theta)
        k = rope(k, positions, cfg.rope_theta)
        q = nn.with_logical_constraint(q, ("batch", "seq", "heads", "kv"))
        k = nn.with_logical_constraint(k, ("batch", "seq", "heads", "kv"))
        out = self._attend(q, k, v, segment_ids)
        return jnp.einsum("bshd,hde->bse", out, wo.astype(dt))


class MLP(nn.Module):
    """SwiGLU feed-forward, column->row parallel under the rule table."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        wi = self.param(
            "wi",
            nn.with_logical_partitioning(kernel_init, (None, "embed", "mlp")),
            (2, cfg.d_model, cfg.d_ff),
            jnp.float32,
        )
        wo = self.param(
            "wo",
            nn.with_logical_partitioning(kernel_init, ("mlp", "embed")),
            (cfg.d_ff, cfg.d_model),
            jnp.float32,
        )
        dt = cfg.dtype
        gate = jnp.einsum("bse,ef->bsf", x, wi[0].astype(dt))
        up = jnp.einsum("bse,ef->bsf", x, wi[1].astype(dt))
        h = nn.silu(gate) * up
        h = nn.with_logical_constraint(h, ("batch", "seq", "mlp"))
        return jnp.einsum("bsf,fe->bse", h, wo.astype(dt))


class Block(nn.Module):
    """One decoder block in nn.scan carry form: (x, bcast...) -> (x, None)."""

    cfg: TransformerConfig
    deterministic: bool = True
    mesh: Optional[jax.sharding.Mesh] = None
    ring_manual: bool = False

    @nn.compact
    def __call__(self, x, positions, segment_ids):
        cfg = self.cfg
        y = RMSNorm(dtype=cfg.dtype, name="attn_norm")(x)
        y = Attention(cfg, mesh=self.mesh, ring_manual=self.ring_manual,
                      name="attn")(y, positions, segment_ids)
        if cfg.dropout_rate:
            y = nn.Dropout(cfg.dropout_rate,
                           deterministic=self.deterministic)(y)
        x = x + y
        y = RMSNorm(dtype=cfg.dtype, name="mlp_norm")(x)
        if cfg.moe_experts > 0:
            from kubeflow_tpu.models.moe import MoEMLP

            y = MoEMLP(
                d_model=cfg.d_model, d_ff=cfg.d_ff,
                num_experts=cfg.moe_experts, top_k=cfg.moe_top_k,
                capacity_factor=cfg.moe_capacity_factor,
                group_size=cfg.resolved_moe_group_size(),
                dtype=cfg.dtype,
                impl=cfg.moe_impl,
                name="moe",
            )(y)
        else:
            y = MLP(cfg, name="mlp")(y)
        if cfg.dropout_rate:
            y = nn.Dropout(cfg.dropout_rate,
                           deterministic=self.deterministic)(y)
        x = x + y
        x = nn.with_logical_constraint(x, ("batch", "seq", "act_embed"))
        return x, None


def _remat_policy(cfg: TransformerConfig):
    """Checkpoint policy for one decoder block under remat (shared by the
    sequential nn.scan path and the GPipe per-layer body)."""
    policies = {
        "dots": jax.checkpoint_policies.dots_saveable,
        "nobatch":
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
        # Save nothing but the (composed-below) flash residuals —
        # every matmul recomputes in the bwd.  The long-context
        # policy: at seq 32k the nobatch-saved MLP activations alone
        # are 2 x 2.06 GB and the program OOMs a 16 GB v5e; minimal
        # fits (measured in BASELINE.md's long-context ladder).
        "minimal": jax.checkpoint_policies.nothing_saveable,
    }
    if cfg.remat_policy not in policies:
        raise ValueError(
            f"remat_policy={cfg.remat_policy!r} not in "
            f"{sorted(policies)}")
    policy = policies[cfg.remat_policy]
    if cfg.attention == "flash" and cfg.save_attn_residuals:
        policy = jax.checkpoint_policies.save_from_both_policies(
            policy,
            jax.checkpoint_policies.save_only_these_names(
                "flash_out", "flash_lse"),
        )
    return policy


class Transformer(nn.Module):
    """LM: token ids [b, s] -> logits [b, s, vocab].

    With ``return_hidden=True`` the unembed projection is skipped and
    the call returns ``(hidden [b, s, d], unembed [v, d] or [d, v])``
    instead — the chunked-CE contract (lm_task, cfg.ce_chunk > 0).
    """

    cfg: TransformerConfig
    mesh: Optional[jax.sharding.Mesh] = None

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        *,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        deterministic: bool = True,
        return_hidden: bool = False,
    ) -> "jax.Array | Tuple[jax.Array, jax.Array]":
        cfg = self.cfg
        embed = self.param(
            "embed",
            nn.with_logical_partitioning(embed_init, ("vocab", "embed")),
            (cfg.vocab_size, cfg.d_model),
            jnp.float32,
        )
        default_positions = positions is None
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1]), tokens.shape
            )
        x = embed.astype(cfg.dtype)[tokens]
        x = nn.with_logical_constraint(x, ("batch", "seq", "act_embed"))

        use_pipeline = (
            cfg.pipeline_microbatches > 0
            and self.mesh is not None
            and self.mesh.shape.get("pipeline", 1) > 1
            and not self.is_initializing()
        )
        if use_pipeline:
            if not default_positions or segment_ids is not None:
                raise ValueError(
                    "the pipelined layer stack supports only default "
                    "positions and no segment_ids")
            x = self._pipelined_layers(x)
        else:
            block = nn.remat(Block, policy=_remat_policy(cfg)) \
                if cfg.remat else Block
            # One compiled body for all layers; params gain a leading
            # 'layers' dim, sharded over the `pipeline` mesh axis by the
            # rule table (a no-op at pipeline=1).
            x, _ = nn.scan(
                block,
                variable_axes={"params": 0, "losses": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
                in_axes=(nn.broadcast, nn.broadcast),
            )(cfg, deterministic, self.mesh, name="layers")(
                x, positions, segment_ids)

        x = RMSNorm(dtype=cfg.dtype, name="final_norm")(x)
        if cfg.tied_embeddings:
            unembed = embed
        else:
            unembed = self.param(
                "w_out",
                nn.with_logical_partitioning(kernel_init, ("embed", "vocab")),
                (cfg.d_model, cfg.vocab_size),
                jnp.float32,
            )
        if return_hidden:
            # Sequence-chunked CE (lm_task, cfg.ce_chunk > 0): the
            # caller unembeds chunk by chunk so the [b, s, vocab]
            # logits never materialize — at seq 128k they are 8.4 GB
            # even in bf16, past what remat can claw back.
            return x, unembed.astype(cfg.dtype)
        spec = "bse,ve->bsv" if cfg.tied_embeddings else "bse,ev->bsv"
        logits = jnp.einsum(spec, x, unembed.astype(cfg.dtype))
        if cfg.ce_dtype == "f32":
            return logits.astype(jnp.float32)
        return logits  # compute dtype; lm_task fuses the f32 reductions

    def _pipelined_layers(self, x: jax.Array) -> jax.Array:
        """GPipe path: parallel/pipeline.py's schedule over the real block.

        The nn.scan param stack [L, ...] (sharded L/S layers per stage over
        the `pipeline` axis by the ("layers", PIPELINE) rule) runs under
        ``pipelined_scan``: microbatches stream through the stage ring via
        ppermute.  shard_map is manual over the pipeline axis (plus the
        sequence axis under ring attention, below) — batch/fsdp/tensor
        stay auto, so XLA still inserts the usual data/tensor collectives
        inside each stage.  Embedding, final norm, and logits run
        replicated across stages (cheap next to the L blocks; the psum at
        the schedule's end hands every stage the full activations).

        Compositions (VERDICT r4 item 3):
          * MoE: each block.apply collects its sown load-balance loss,
            which rides pipelined_scan's ``with_aux`` accumulator
            (bubble steps masked), is averaged over microbatches (the
            sown aux is a token-mean — a model property, not a
            per-microbatch sum), and re-sown at the Transformer level so
            lm_task's existing "losses" plumbing sees it unchanged.
          * Ring attention: ONE shard_map manual over BOTH
            {pipeline, sequence}; each stage calls the per-shard ring
            body (parallel/ring.py ring_attention) directly — nesting a
            second shard_map would not compose.  Activations enter
            sequence-sharded, positions are offset per shard, and the
            block's logical "seq" constraints are re-mapped to None for
            the trace (a constraint naming a manual axis is an error).
        """
        import contextlib
        import functools

        from jax.sharding import PartitionSpec as P

        from kubeflow_tpu.parallel.mesh import PIPELINE, SEQUENCE
        from kubeflow_tpu.parallel.pipeline import (
            microbatch,
            pipelined_scan,
            unmicrobatch,
        )

        cfg = self.cfg
        n_micro = cfg.pipeline_microbatches
        n_stages = self.mesh.shape[PIPELINE]
        if x.shape[0] % n_micro:
            raise ValueError(
                f"batch {x.shape[0]} not divisible by "
                f"pipeline_microbatches={n_micro}")
        if cfg.n_layers % n_stages:
            raise ValueError(
                f"n_layers={cfg.n_layers} not divisible by "
                f"pipeline={n_stages} stages")
        ring = cfg.attention == "ring"
        with_aux = cfg.moe_experts > 0
        seq_ax = self.mesh.shape.get(SEQUENCE, 1)
        if ring and x.shape[1] % seq_ax:
            raise ValueError(
                f"seq {x.shape[1]} not divisible by sequence={seq_ax}")
        stacked = nn.unbox(self.get_variable("params", "layers"))
        block = Block(cfg, deterministic=True, mesh=None,
                      ring_manual=ring)
        if ring:
            # "seq" (and any other SEQUENCE-mapped logical name) must
            # not resolve to the now-manual axis inside the body.
            ring_rules = tuple(
                (name,
                 None if axes == SEQUENCE else
                 tuple(a for a in axes if a != SEQUENCE)
                 if isinstance(axes, tuple) else axes)
                for name, axes in nn.get_logical_axis_rules())

        def body(layer_params, act):
            s_local = act.shape[1]
            offset = (jax.lax.axis_index(SEQUENCE) * s_local if ring
                      else 0)
            pos = jnp.broadcast_to(
                offset + jnp.arange(s_local), act.shape[:2])
            ctx = (nn.logical_axis_rules(list(ring_rules)) if ring
                   else contextlib.nullcontext())
            with ctx:
                if with_aux:
                    (out, _), sown = block.apply(
                        {"params": layer_params}, act, pos, None,
                        mutable=["losses"])
                    aux = sum(jnp.sum(v) for v in
                              jax.tree_util.tree_leaves(sown["losses"]))
                    return out, aux
                out, _ = block.apply(
                    {"params": layer_params}, act, pos, None)
                return out

        if cfg.remat:
            body = jax.checkpoint(body, policy=_remat_policy(cfg))

        pipe_specs = jax.tree_util.tree_map(lambda _: P(PIPELINE), stacked)
        act_spec = P(None, SEQUENCE) if ring else P()

        @functools.partial(
            jax.shard_map, mesh=self.mesh,
            in_specs=(pipe_specs, act_spec),
            out_specs=(act_spec, P()) if with_aux else act_spec,
            axis_names={PIPELINE, SEQUENCE} if ring else {PIPELINE},
        )
        def run(params, act):
            act = act.astype(cfg.dtype)
            res = pipelined_scan(body, params, microbatch(act, n_micro),
                                 with_aux=with_aux)
            if not with_aux:
                return unmicrobatch(res).astype(jnp.float32)
            ys, aux = res
            # The sown aux is a mean over (local) tokens: averaging
            # over microbatches — and over sequence shards under ring —
            # restores the sequential path's scale; summing would
            # multiply the balance penalty by M (x seq shards).
            aux = aux / n_micro
            if ring:
                aux = jax.lax.pmean(aux, SEQUENCE)
            return unmicrobatch(ys).astype(jnp.float32), aux

        # Activations cross the shard_map boundary in f32 (cast back to
        # the compute dtype on each side): the boundary's transpose
        # inserts a psum over the pipeline axis for the activation
        # cotangent, and XLA's partitioner aborts on sub-f32 all-reduce
        # inside a partial-manual region (same bug pipelined_scan works
        # around for its own output psum).
        if with_aux:
            out, aux = run(stacked, x.astype(jnp.float32))
            # Re-sown at this level so lm_task's existing losses
            # plumbing (mutable=["losses"], sum of leaves) is unchanged.
            self.sow("losses", "pipeline_moe_aux", aux)
            return out.astype(cfg.dtype)
        return run(stacked, x.astype(jnp.float32)).astype(cfg.dtype)


def lm_task(cfg: TransformerConfig, mesh=None):
    """(init_fn, loss_fn) pair for Trainer: next-token cross-entropy.

    Batch contract: {"tokens": [b, s] int32}; loss predicts tokens[1:].
    """
    import optax

    model = Transformer(cfg, mesh=mesh)

    def init_fn(rng):
        # Shapes only seed parameter shapes, but sharded attention backends
        # (ring/flash via shard_map) trace with them — keep both batch and
        # seq divisible by the relevant mesh axes.
        b, s = 1, min(cfg.max_seq_len, 16)
        if mesh is not None:
            b = mesh.shape.get("data", 1) * mesh.shape.get("fsdp", 1)
            s_ax = mesh.shape.get("sequence", 1)
            s = max(s, s_ax) // s_ax * s_ax
        toks = jnp.zeros((b, s), jnp.int32)
        variables = model.init(rng, toks)
        return variables["params"], {}

    def ce_per_position(lg, tgt):
        """Per-position CE [*, n] from logits [*, n, v], honoring
        cfg.ce_dtype (shared by the unchunked and chunked paths)."""
        if cfg.ce_dtype == "f32":
            return optax.softmax_cross_entropy_with_integer_labels(
                lg.astype(jnp.float32), tgt)
        # Fused CE on compute-dtype logits: each reduction upcasts
        # per element inside its own fusion, so the only [*, n, v]
        # tensors in HBM are the compute-dtype logits — no 4-byte
        # copy, and the backward's softmax cotangent stays narrow.
        m = jax.lax.stop_gradient(jnp.max(lg, axis=-1, keepdims=True))
        # Subtract in f32 (exact; the casts fuse into the reduce — no
        # [*, n, v] f32 tensor hits HBM): the only precision
        # difference vs the f32 path is the narrow storage of the
        # logits themselves.
        lse = jnp.log(jnp.sum(
            jnp.exp(lg.astype(jnp.float32) - m.astype(jnp.float32)),
            axis=-1,
        )) + m[..., 0].astype(jnp.float32)
        target_logit = jnp.take_along_axis(
            lg, tgt[..., None], axis=-1
        )[..., 0].astype(jnp.float32)
        return lse - target_logit

    def chunked_ce(hidden, unembed, tokens):
        """Mean next-token CE without materializing [b, s, vocab]:
        unembed + loss run `chunk` positions at a time under a
        rematerialized scan (backward recomputes each chunk's logits).
        The final position has no target; a zero weight masks it so
        chunks can tile all s positions regardless of divisibility of
        s - 1 (at seq 128k, s - 1 is prime)."""
        from kubeflow_tpu.models.moe import fit_divisor

        b, s = tokens.shape
        chunk = fit_divisor(
            s, cfg.ce_chunk, "ce_chunk",
            "The chunked CE collapses toward an s-iteration scan of "
            "single-position unembeds (looks like a hang).  Choose a "
            "sequence length with a divisor close to ce_chunk.")
        n = s // chunk
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.zeros((b, 1), tokens.dtype)], axis=1)
        weights = jnp.concatenate(
            [jnp.ones((b, s - 1), jnp.float32),
             jnp.zeros((b, 1), jnp.float32)], axis=1)
        spec = "bce,ve->bcv" if cfg.tied_embeddings else "bce,ev->bcv"

        def body(total, inp):
            hc, tc, wc = inp
            lg = jnp.einsum(spec, hc, unembed)
            return total + jnp.sum(ce_per_position(lg, tc) * wc), None

        total, _ = jax.lax.scan(
            jax.checkpoint(body),
            jnp.zeros((), jnp.float32),
            (hidden.reshape(b, n, chunk, -1).transpose(1, 0, 2, 3),
             targets.reshape(b, n, chunk).transpose(1, 0, 2),
             weights.reshape(b, n, chunk).transpose(1, 0, 2)),
        )
        return total / (b * (s - 1))

    def loss_fn(params, mutable, batch, rng):
        del mutable
        tokens = batch["tokens"]
        apply_kwargs = dict(
            deterministic=False, rngs={"dropout": rng},
            return_hidden=cfg.ce_chunk > 0,
        )
        if cfg.moe_experts > 0:
            out, sown = model.apply(
                {"params": params}, tokens,
                mutable=["losses"], **apply_kwargs,
            )
        else:
            out = model.apply({"params": params}, tokens, **apply_kwargs)
        if cfg.ce_chunk > 0:
            hidden, unembed = out
            loss = chunked_ce(hidden, unembed, tokens)
        else:
            logits = out
            loss = ce_per_position(
                logits[:, :-1], tokens[:, 1:]).mean()
        metrics = {"perplexity": jnp.exp(loss)}
        if cfg.moe_experts > 0:
            aux = sum(jnp.sum(v) for v in
                      jax.tree_util.tree_leaves(sown["losses"]))
            metrics["moe_aux"] = aux
            loss = loss + cfg.moe_aux_coef * aux
        return loss, (metrics, {})

    return init_fn, loss_fn
