"""Preemption: victim selection and the anti-livelock rate limiter.

A higher-priority job that cannot fit may evict running lower-priority
work — but eviction is the most expensive verb the control plane has
(a whole gang's progress since its last checkpoint), so the policy
here is deliberately narrow:

  * victims must hold the SAME slice type the preemptor needs (claims
    are per-type; evicting a v5p gang frees nothing for a v5e ask);
  * victims are strictly LOWER priority — an equal-priority job can
    never be evicted, which kills the direct A-evicts-B-evicts-A
    livelock by construction;
  * among eligible victims, evict the lowest priority first and, at
    equal priority, the job holding the FEWEST chips (cheapest restart
    first); stop as soon as enough capacity frees;
  * a whole-cluster rate limit bounds eviction churn: two priority
    tiers flapping (high jobs arriving as fast as lows resume) can
    cost at most ``max_preemptions`` evictions per ``window_s``.

The victim is not killed outright: the reconciler gives it a
``grace_period_s`` checkpoint window (the SIGTERM contract — see
``PreemptionConfig``) and re-enqueues it ``resumable``, so on
re-admission the trainer's ``CheckpointManager.restore_or_init``
continues from the latest saved step instead of step 0.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Any, Dict, List

from kubeflow_tpu.testing import faults


@dataclasses.dataclass
class PreemptionConfig:
    """Knobs for the eviction path.

    ``grace_period_s`` is the checkpoint-on-SIGTERM window: the
    reconciler holds the victim in ``Preempting`` (pods alive, claim
    held) for this long before tearing the gang down, so an in-flight
    ``CheckpointManager.save`` can land.  It is a *policy* clock
    (``faults.monotonic``): tests and chaos runs skew it instead of
    sleeping through it.
    """

    enable: bool = True
    grace_period_s: float = 30.0
    # Whole-cluster eviction budget: at most max_preemptions evictions
    # per sliding window_s.
    max_preemptions: int = 4
    window_s: float = 300.0
    # Grace window when the preemptor is a SERVING claim
    # (scheduler/colocate.py): a traffic spike cannot wait out the full
    # training grace, and the victim's checkpoint cadence — not the
    # window — bounds lost work, so serving evictions drain short.
    serving_grace_period_s: float = 5.0

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PreemptionConfig":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown preemption config keys {sorted(unknown)}; "
                f"known: {sorted(known)}")
        return cls(**d)


def pick_victims(running: List[Any], preemptor: Any,
                 free: int) -> List[Any]:
    """Choose a minimal victim set for ``preemptor`` (a JobView).

    ``running`` are candidate JobViews already filtered to the
    preemptor's slice type and not mid-preemption; ``free`` is the
    currently free slice count of that type.  Returns ``[]`` when no
    lower-priority set can free enough capacity — partial eviction
    would burn checkpoints without unblocking anyone.
    """
    eligible = [v for v in running
                if v.priority_value < preemptor.priority_value]
    # Lowest priority first; cheapest gang (fewest chips) first within
    # a priority tier; stable on enqueue order via sort stability.
    eligible.sort(key=lambda v: (v.priority_value, v.chips))
    victims: List[Any] = []
    freed = free
    for v in eligible:
        if freed >= preemptor.count:
            break
        victims.append(v)
        freed += v.count
    if freed < preemptor.count:
        return []
    return victims


class PreemptionRateLimiter:
    """Sliding-window eviction budget on the skewable policy clock.

    Locked: ``record`` runs on the reconcile loop while ``in_window``
    is read from /queue status requests on HTTP server threads — an
    unlocked prune-and-rebind would drop a recorded eviction and let
    the budget overshoot."""

    def __init__(self, max_preemptions: int = 4, window_s: float = 300.0):
        self.max_preemptions = max(0, int(max_preemptions))
        self.window_s = float(window_s)
        self._lock = threading.Lock()
        self._events: List[float] = []

    def _prune_locked(self, now: float) -> None:
        cutoff = now - self.window_s
        self._events = [t for t in self._events if t > cutoff]

    def allow(self, n: int = 1) -> bool:
        """True when ``n`` more evictions fit the window — the budget
        is per evicted GANG, so a multi-victim wave must fit whole
        (partial eviction frees nothing, see pick_victims)."""
        now = faults.monotonic()
        with self._lock:
            self._prune_locked(now)
            return len(self._events) + n <= self.max_preemptions

    def record(self) -> None:
        now = faults.monotonic()
        with self._lock:
            self._prune_locked(now)
            self._events.append(now)

    def in_window(self) -> int:
        now = faults.monotonic()
        with self._lock:
            self._prune_locked(now)
            return len(self._events)
