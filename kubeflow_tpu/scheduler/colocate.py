"""Elastic train/serve colocation: serving claims on the shared pool.

Training (the gang placer + policy layer) and serving (the fleet
autoscaler) historically owned disjoint chips, so every diurnal
serving trough stranded the serving pool while training queued.  This
module makes the serving Deployment a first-class TENANT of the
cluster scheduler, Gavel-style (one arbiter over one pool, arXiv
2008.09213):

* The autoscaler's desired-replica delta becomes a TPUJob-shaped
  **ServingClaim** CR (``build_claim_cr``) — high priority class,
  ``kubeflow-tpu.org/workload: serving`` — instead of a raw
  ``spec.replicas`` patch.  One claim per Deployment;
  ``spec.numSlices`` is the desired replica count (one replica per
  slice).
* ``plan()`` admits the claim through the ordinary policy machinery:
  strict priority means a traffic spike preempts strictly-lower
  training via the existing grace-window checkpoint-resume path
  (victims requeue ``resumable: true``, restart budget untouched, the
  PreemptionRateLimiter budget respected) — except the victim drains
  on the SHORT ``serving_grace_period_s`` so the replica cold-start
  overlaps the drain instead of serializing after a full training
  grace.
* A scale-down shrinks the claim in place (``GangScheduler.resize``),
  releasing slices that pending training gangs backfill in the same
  pass.  Scale-to-zero deletes the claim CR outright
  (``numSlices >= 1`` is a spec invariant) and the reconciler's stale
  sweep releases the gang claim.

Elastic growth rides the same fold/merge shape as scheduler/fuse.py:
an admitted claim whose CR asks for MORE than its gang claim holds is
split into a running base view (what it holds — what quota and
preemption see) plus a pending **grow-delta** view (``<key>!grow``)
carrying only the increment; after the plan, ``finalize`` moves the
grow verdict back onto the base key so the reconciler drives one CR.

Speculative placement (arXiv 2010.11307) is the reconciler's half:
when a plan preempts training FOR a serving claim, prepull pods
(``build_prepull_pod``) pinned to the victims' nodes pre-pull the
serving image during the drain.

Hook sites: ``scheduler.colocate`` fires once per serving claim the
fold admits into a plan pass as new demand; ``autoscaler.claim`` fires
on every autoscaler->claim sync.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

from kubeflow_tpu.operator import crd
from kubeflow_tpu.scheduler.policy import (  # noqa: F401 (re-export)
    LABEL_PRIORITY,
    LABEL_TENANT,
    LABEL_WORKLOAD,
    PREEMPT,
    JobView,
    Plan,
)
from kubeflow_tpu.testing import faults

# LABEL_WORKLOAD values.  Training CRs carry no workload label.
WORKLOAD_SERVING = "serving"
WORKLOAD_PREPULL = "prepull"

# Which Deployment a claim elasticizes (same metadata group as the
# tenant/priority labels the policy reads).
LABEL_DEPLOYMENT = "kubeflow-tpu.org/serving-deployment"
# Which claim a prepull pod warms (reconciler-side cleanup key).
LABEL_PREPULL_CLAIM = "kubeflow-tpu.org/prepull-claim"

# Claim defaults: the fleet bills one shared tenant, and serving
# outranks training by priority class — that asymmetry IS the
# colocation policy (latency SLOs preempt batch throughput; batch
# backfills latency troughs).
SERVING_TENANT = "fleet"
SERVING_PRIORITY = "high"
DEFAULT_SERVING_IMAGE = "ghcr.io/kubeflow-tpu/serving:latest"

# Grow-delta view keys live beside their base key; '!' cannot appear
# in a CR name, so the suffix can never collide with a real job key.
GROW_SUFFIX = "!grow"


def claim_name(deployment: str) -> str:
    return f"serving-{deployment}"


def claim_key(namespace: str, deployment: str) -> str:
    return f"{namespace}/{claim_name(deployment)}"


def is_serving_view(view: JobView) -> bool:
    return view.workload == WORKLOAD_SERVING


def is_serving_claim_cr(cr_obj: dict) -> bool:
    labels = (cr_obj.get("metadata") or {}).get("labels") or {}
    return labels.get(LABEL_WORKLOAD) == WORKLOAD_SERVING


def build_claim_cr(namespace: str, deployment: str, *,
                   slice_type: str = "v5e-8", replicas: int = 1,
                   tenant: str = SERVING_TENANT,
                   priority: str = SERVING_PRIORITY,
                   image: str = DEFAULT_SERVING_IMAGE) -> dict:
    """The ServingClaim CR: an ordinary TPUJob wearing serving labels.

    Riding the TPUJob shape (rather than a second CRD) is the point:
    quota, fair share, priority, preemption, rate limiting and the CLI
    all apply to the claim with zero new admission code paths.
    """
    spec = crd.TPUJobSpec(
        name=claim_name(deployment), namespace=namespace,
        slice_type=slice_type, num_slices=int(replicas),
        worker=crd.WorkerSpec(image=image))
    cr = spec.to_custom_resource()
    cr["metadata"]["labels"] = {
        LABEL_WORKLOAD: WORKLOAD_SERVING,
        LABEL_TENANT: tenant,
        LABEL_PRIORITY: priority,
        LABEL_DEPLOYMENT: deployment,
    }
    return cr


def build_prepull_pod(namespace: str, claim: str, node: str,
                      image: str) -> dict:
    """Speculative-placement pod: pins to a node the plan predicts
    will free and pre-pulls the serving image during the victim's
    drain.  Runs no workload (the k8s image-pull side effect is the
    product); requests nothing, so it cannot steal the slice it
    warms."""
    return {
        "apiVersion": "v1",
        "kind": "Pod",
        "metadata": {
            "name": f"prepull-{claim}-{node}",
            "namespace": namespace,
            "labels": {
                LABEL_WORKLOAD: WORKLOAD_PREPULL,
                LABEL_PREPULL_CLAIM: claim,
            },
        },
        "spec": {
            "restartPolicy": "Never",
            "nodeName": node,
            "containers": [{
                "name": "prepull",
                "image": image,
                "command": ["/bin/true"],
                "resources": {},
            }],
        },
    }


# -- plan-pass fold / merge (the fuse.py shape) ---------------------------


def _per_slice(view: JobView) -> int:
    return view.chips // max(1, view.count)


def fold(pending: List[JobView], running: List[JobView], gang,
         queue=None) -> Tuple[List[JobView], List[JobView],
                              List[JobView], set]:
    """Split admitted serving claims into held + grow-delta views.

    The policy must see an admitted claim as what it HOLDS (quota,
    victim cost, inventory) while its unmet increment competes as
    ordinary pending demand.  Returns ``(pending, running,
    grow_views, serving_keys)``: grow views are appended to pending
    under ``<key>!grow`` keys (touched into ``queue`` for stable FIFO
    position across passes), and ``serving_keys`` holds every serving
    claim's BASE key — ``finalize`` uses it to stamp the short grace
    on victims evicted for a claim.
    """
    serving_keys = {v.key for v in pending + running
                    if is_serving_view(v)}
    grow_views: List[JobView] = []
    out_running: List[JobView] = []
    out_pending = list(pending)
    for view in running:
        if not is_serving_view(view):
            out_running.append(view)
            continue
        held = gang.claim_count(view.key)
        per = _per_slice(view)
        if held and view.count > held:
            # Desired outgrew the claim: base view bills what is held,
            # the delta queues as pending demand (high priority — it
            # may preempt).
            faults.fire("scheduler.colocate")
            base = dataclasses.replace(
                view, count=held, chips=per * held)
            grow = dataclasses.replace(
                view, key=view.key + GROW_SUFFIX,
                count=view.count - held,
                chips=per * (view.count - held))
            if queue is not None:
                grow.enqueued_at = queue.touch(grow)
            out_running.append(base)
            grow_views.append(grow)
            out_pending.append(grow)
        else:
            # Steady or shrinking claim: the reconciler resizes
            # shrinks in place; the plan bills the held count.
            if held and held != view.count:
                view = dataclasses.replace(
                    view, count=held, chips=per * held)
            out_running.append(view)
    for view in pending:
        if is_serving_view(view):
            # Initial admission of a claim: ordinary pending demand,
            # announced on the colocate hook like a grow delta.
            faults.fire("scheduler.colocate")
    return out_pending, out_running, grow_views, serving_keys


def finalize(plan: Plan, grow_views: List[JobView], serving_keys: set,
             serving_grace_s: float) -> int:
    """Post-plan merge: move grow-delta verdicts onto their base keys
    and stamp the short serving grace on victims evicted for a serving
    claim.  Returns the number of colocation preemptions (victims
    whose preemptor is a serving claim) planned THIS pass — the plan's
    ``preemptions`` list only ever holds fresh eviction waves, so the
    caller can count it straight into a counter without double
    counting across grace-window passes.

    Runs BEFORE ``fuse.mirror_decisions`` so a fused-gang victim's
    grace override is copied onto its member decisions.
    """
    for gv in grow_views:
        base_key = gv.key[:-len(GROW_SUFFIX)]
        decision = plan.decisions.pop(gv.key, None)
        if decision is not None:
            plan.decisions[base_key] = decision
        if gv.key in plan.order:
            plan.order[plan.order.index(gv.key)] = base_key
        plan.preemptions = [
            (victim, base_key if preemptor == gv.key else preemptor)
            for victim, preemptor in plan.preemptions]
        for d in plan.decisions.values():
            if d.preemptor == gv.key:
                d.preemptor = base_key

    colocated = 0
    for victim, preemptor in plan.preemptions:
        if preemptor not in serving_keys:
            continue
        colocated += 1
        decision = plan.decisions.get(victim)
        if decision is not None and decision.action == PREEMPT:
            decision.grace_s = serving_grace_s
    return colocated


# -- the autoscaler's side ------------------------------------------------


class ServingClaimClient:
    """Translates the autoscaler's desired replica count into the
    ServingClaim CR and observes the arbiter's verdict.

    The CR API is create/status/delete (no spec patch, matching the
    fake apiserver), so a desired-count change REPLACES the claim CR;
    the gang claim keys on namespace/name, so the reconciler sees a
    resize, not a release/re-admit cycle.  Scale-to-zero deletes the
    claim and patches the Deployment to 0 directly — releasing chips
    needs no arbitration.
    """

    def __init__(self, kube, namespace: str, deployment: str, *,
                 slice_type: str = "v5e-8",
                 tenant: str = SERVING_TENANT,
                 priority: str = SERVING_PRIORITY,
                 image: str = DEFAULT_SERVING_IMAGE):
        self.kube = kube
        self.namespace = namespace
        self.deployment = deployment
        self.slice_type = slice_type
        self.tenant = tenant
        self.priority = priority
        self.image = image
        self._last_state = ""
        self._last_pool: Optional[Dict] = None

    @property
    def name(self) -> str:
        return claim_name(self.deployment)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.name}"

    def sync(self, desired: int) -> dict:
        """Reconcile the claim CR to ``desired`` replicas; returns the
        current verdict snapshot (``state`` granted|pending|denied|
        released, ``granted`` replicas, last seen ``pool``)."""
        faults.fire("autoscaler.claim")
        desired = int(desired)
        if desired <= 0:
            self.kube.delete_custom(self.namespace, self.name)
            try:
                self.kube.patch_deployment_scale(
                    self.namespace, self.deployment, 0)
            except Exception:  # NotFound from either kube backend
                pass
            self._note_state("released")
            return {"desired": 0, "granted": 0, "state": "released",
                    "pool": self._last_pool}
        current = None
        try:
            existing = self.kube.get_custom(self.namespace, self.name)
            current = int(
                (existing.get("spec") or {}).get("numSlices", 0) or 0)
        except Exception:
            existing = None
        if current != desired:
            if existing is not None:
                self.kube.delete_custom(self.namespace, self.name)
            self.kube.create_custom(build_claim_cr(
                self.namespace, self.deployment,
                slice_type=self.slice_type, replicas=desired,
                tenant=self.tenant, priority=self.priority,
                image=self.image))
        return self.observe(desired)

    def observe(self, desired: Optional[int] = None) -> dict:
        try:
            cr = self.kube.get_custom(self.namespace, self.name)
        except Exception:
            self._note_state("released")
            return {"desired": 0, "granted": 0, "state": "released",
                    "pool": self._last_pool}
        spec = cr.get("spec") or {}
        status = cr.get("status") or {}
        if desired is None:
            desired = int(spec.get("numSlices", 0) or 0)
        granted = int(status.get("grantedReplicas", 0) or 0)
        pool = status.get("pool")
        if pool:
            self._last_pool = pool
        if status.get("denied"):
            state = "denied"
        elif granted >= desired:
            state = "granted"
        else:
            state = "pending"
        self._note_state(state)
        return {"desired": desired, "granted": granted, "state": state,
                "pool": self._last_pool}

    def pool(self) -> Optional[Dict]:
        """Last combined-pool snapshot the reconciler stamped on the
        claim status (the fleet status footer's data source)."""
        return self._last_pool

    def close(self) -> None:
        """Zero the claim's gauge series so a torn-down fleet scrapes
        0, not its last value (the registry is process-global; the
        scheduler also zeroes stale series every export)."""
        from kubeflow_tpu.runtime.prom import REGISTRY

        gauge = REGISTRY.gauge(
            "kft_scheduler_serving_claim_chips",
            "chips held by admitted serving claims")
        for labels in gauge.labelsets():
            gauge.set(0, **labels)

    def _note_state(self, state: str) -> None:
        if state == self._last_state:
            return
        from kubeflow_tpu.runtime.prom import REGISTRY

        if state == "granted":
            REGISTRY.counter(
                "kft_autoscaler_claim_granted_total",
                "serving claims fully granted by the arbiter",
            ).inc(deployment=self.deployment)
        elif state == "denied":
            REGISTRY.counter(
                "kft_autoscaler_claim_denied_total",
                "serving claims denied (unsatisfiable or "
                "rate-limited) by the arbiter",
            ).inc(deployment=self.deployment)
        self._last_state = state
