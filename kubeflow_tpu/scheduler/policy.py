"""Admission policy: quotas, weighted-fair ordering, priority classes,
and conservative backfill.

Every reconcile pass the policy is handed the pending and admitted
jobs plus a free-inventory snapshot and produces a :class:`Plan` — a
pure function of its inputs (the single-threaded reconcile loop owns
the only mutation window), so every decision is unit-testable without
a cluster and replayable from a dump of its inputs.

Ordering discipline (the Gavel shape — policy above the placer):

  1. strict priority classes: a ``high`` job is considered before any
     ``normal`` job, regardless of tenants or arrival order;
  2. weighted fair sharing within a class: among equal-priority jobs
     the next candidate belongs to the tenant with the least admitted
     chips *per unit weight* (recomputed as the plan simulates
     admissions, so one greedy tenant interleaves rather than drains
     its whole backlog first);
  3. FIFO within a tenant at equal priority (stable tie-break on
     enqueue time).

Quota: per-tenant, per-slice-type admitted-chip caps.  A quota-blocked
job is SKIPPED — it neither consumes capacity nor blocks jobs behind
it (its tenant chose its backlog shape; making others pay for it is
exactly the head-of-line starvation this layer exists to remove).

Backfill (conservative, provable): a job may be admitted ahead of a
capacity-blocked higher-priority job only when doing so provably
cannot delay that job's earliest start.  Without trusted run-time
estimates the only provable cases are (a) the jumper asks for a
DIFFERENT slice type (disjoint pools: claiming v5e frees/starves no
v5p), or (b) after the jump the blocked job's demand still fits the
remaining free pool (it was blocked by ordering, not capacity).  A
same-type jump past a capacity-blocked job is always denied: the
blocked job's ETA depends on released slices, and the jumper's claim
would join the set it must wait on.  EASY-style backfill with
durations is a policy extension point, not the default.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.scheduler.preempt import (
    PreemptionConfig,
    PreemptionRateLimiter,
    pick_victims,
)
from kubeflow_tpu.testing import faults

# CR metadata labels the policy reads (same group as the job labels
# the reconciler stamps on pods).
LABEL_TENANT = "kubeflow-tpu.org/tenant"
LABEL_PRIORITY = "kubeflow-tpu.org/priority"
# Opt-in marker for horizontal fusion (scheduler/fuse.py): singleton
# jobs sharing a family value assert same-architecture compatibility.
LABEL_FUSE_FAMILY = "kubeflow-tpu.org/fuse-family"
# Workload class of a CR: "" (ordinary training) or the values
# scheduler/colocate.py stamps on serving claims / prepull pods.
# Lives HERE so colocate can import it without policy ever importing
# colocate (one-way dependency).
LABEL_WORKLOAD = "kubeflow-tpu.org/workload"

DEFAULT_TENANT = "default"
DEFAULT_PRIORITY = "normal"
DEFAULT_PRIORITY_CLASSES = {"low": 0, "normal": 100, "high": 1000}

# Decision actions.
ADMIT = "admit"
WAIT = "wait"
PREEMPT = "preempt"
UNSATISFIABLE = "unsatisfiable"


@dataclasses.dataclass
class JobView:
    """One TPUJob as the policy sees it for a single plan pass."""

    key: str                 # namespace/name
    tenant: str
    priority: str            # class name (label value)
    priority_value: int
    slice_type: str
    count: int               # whole slices demanded
    chips: int               # total chips = slice chips * count
    phase: str = ""
    enqueued_at: float = 0.0
    resumable: bool = False
    preemptions: int = 0
    # Horizontal fusion (scheduler/fuse.py): ``family`` is the CR's
    # opt-in label; a FUSED view carries its member views in
    # ``members`` (then ``chips`` is the whole gang's slice, billed
    # per-member by :func:`tenant_shares`); a MEMBER view carries the
    # gang it belongs to in ``fused_gang`` plus the member count for
    # status rendering.
    family: str = ""
    members: Tuple["JobView", ...] = ()
    fused_gang: str = ""
    fused_members: int = 0
    # Workload class (scheduler/colocate.py): "serving" marks a
    # ServingClaim riding the TPUJob shape; "" is ordinary training.
    workload: str = ""


def tenant_shares(job: JobView) -> List[Tuple[str, float]]:
    """(tenant, chips) pairs a view bills against quota/fair-share.

    THE fused fair-share rule: a fused gang charges each member's
    tenant its per-member share of the slice — never one tenant for
    the whole gang.  Singletons bill themselves in full."""
    if job.members:
        share = job.chips / len(job.members)
        return [(m.tenant, share) for m in job.members]
    return [(job.tenant, job.chips)]


@dataclasses.dataclass
class SchedulerConfig:
    """Policy configuration, loadable from the operator's controller
    ConfigMap (``scheduler`` key) — see ``from_dict`` for the wire
    shape."""

    # tenant -> {slice_type -> max admitted chips}.  Missing tenant or
    # slice type = unlimited.
    quotas: Dict[str, Dict[str, int]] = dataclasses.field(
        default_factory=dict)
    # tenant -> fair-share weight (default 1.0).
    weights: Dict[str, float] = dataclasses.field(default_factory=dict)
    priority_classes: Dict[str, int] = dataclasses.field(
        default_factory=lambda: dict(DEFAULT_PRIORITY_CLASSES))
    enable_backfill: bool = True
    preemption: PreemptionConfig = dataclasses.field(
        default_factory=PreemptionConfig)

    def priority_value(self, name: str) -> int:
        """Unknown class names sort as the default class rather than
        erroring: a typo'd label must degrade a job's priority, not
        wedge the whole admission plan."""
        if name in self.priority_classes:
            return self.priority_classes[name]
        return self.priority_classes.get(DEFAULT_PRIORITY, 0)

    def weight(self, tenant: str) -> float:
        w = float(self.weights.get(tenant, 1.0))
        return w if w > 0 else 1.0

    def quota_chips(self, tenant: str, slice_type: str) -> Optional[int]:
        per_type = self.quotas.get(tenant)
        if per_type is None:
            return None
        value = per_type.get(slice_type)
        return None if value is None else int(value)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SchedulerConfig":
        """Wire shape (operator ConfigMap ``scheduler`` key)::

            {"quotas": {"team-a": {"v5e-8": 16}},
             "weights": {"team-a": 3.0},
             "priorityClasses": {"low": 0, "normal": 100, "high": 1000},
             "enableBackfill": true,
             "preemption": {"grace_period_s": 30,
                            "max_preemptions": 4, "window_s": 300}}
        """
        d = dict(d)
        preempt_cfg = d.pop("preemption", None)
        kwargs: Dict[str, Any] = {}
        aliases = {"priorityClasses": "priority_classes",
                   "enableBackfill": "enable_backfill"}
        for key, value in d.items():
            name = aliases.get(key, key)
            if name not in {f.name for f in dataclasses.fields(cls)}:
                raise ValueError(f"unknown scheduler config key {key!r}")
            kwargs[name] = value
        if "quotas" in kwargs:
            kwargs["quotas"] = {
                tenant: {st: int(n) for st, n in per_type.items()}
                for tenant, per_type in kwargs["quotas"].items()}
        if "weights" in kwargs:
            kwargs["weights"] = {t: float(w)
                                 for t, w in kwargs["weights"].items()}
        if "priority_classes" in kwargs:
            kwargs["priority_classes"] = {
                n: int(v) for n, v in kwargs["priority_classes"].items()}
        cfg = cls(**kwargs)
        if preempt_cfg is not None:
            cfg.preemption = PreemptionConfig.from_dict(preempt_cfg)
        return cfg


@dataclasses.dataclass
class Decision:
    action: str              # admit | wait | preempt | unsatisfiable
    reason: str = ""
    message: str = ""
    backfilled: bool = False
    preemptor: str = ""      # preempt decisions: who the slices go to
    # Mirrored member decisions (scheduler/fuse.py): the gang claim key
    # this member's admission rides on, every member key in the gang,
    # and whether THIS member leads pod materialization/teardown.
    fused_gang: str = ""
    fused_members: Tuple[str, ...] = ()
    fused_leader: bool = False
    # Grace-window override (scheduler/colocate.py): >= 0 replaces the
    # config grace_period_s for THIS victim — serving claims evict on
    # the short serving grace so cold-start overlaps the drain.
    grace_s: float = -1.0


@dataclasses.dataclass
class Plan:
    """One pass's verdicts.  ``order`` is the policy's consideration
    order over pending jobs — the reconciler offers admissions in this
    order so gang claims land exactly as simulated."""

    order: List[str] = dataclasses.field(default_factory=list)
    decisions: Dict[str, Decision] = dataclasses.field(
        default_factory=dict)
    preemptions: List[Tuple[str, str]] = dataclasses.field(
        default_factory=list)   # (victim_key, preemptor_key)


def job_view(cr_obj: dict, spec: Any, config: SchedulerConfig) -> JobView:
    """Build the policy's view of one CR (spec already parsed)."""
    meta = cr_obj.get("metadata", {})
    labels = meta.get("labels") or {}
    status = cr_obj.get("status", {}) or {}
    tenant = labels.get(LABEL_TENANT, DEFAULT_TENANT)
    priority = labels.get(LABEL_PRIORITY, DEFAULT_PRIORITY)
    return JobView(
        key=f"{spec.namespace}/{spec.name}",
        tenant=tenant,
        priority=priority,
        priority_value=config.priority_value(priority),
        slice_type=spec.slice_type,
        count=spec.num_slices,
        chips=spec.num_devices,
        phase=status.get("phase", ""),
        resumable=bool(status.get("resumable")),
        preemptions=int(status.get("preemptions", 0)),
        family=labels.get(LABEL_FUSE_FAMILY, ""),
        fused_gang=str(status.get("fusedGang") or ""),
        fused_members=int(status.get("fusedMembers", 0) or 0),
        workload=labels.get(LABEL_WORKLOAD, ""),
    )


class SchedulingPolicy:
    def __init__(self, config: Optional[SchedulerConfig] = None,
                 limiter: Optional[PreemptionRateLimiter] = None):
        self.config = config or SchedulerConfig()
        self.limiter = limiter or PreemptionRateLimiter(
            self.config.preemption.max_preemptions,
            self.config.preemption.window_s)

    # -- plan --------------------------------------------------------------

    def plan(self, pending: List[JobView], running: List[JobView],
             free: Dict[str, int], capacity: Dict[str, int]) -> Plan:
        """Simulate one admission pass over a snapshot.

        ``running`` holds every job with a live gang claim, including
        those already mid-preemption (phase Preempting) — their claims
        still count against quota and inventory until torn down.
        """
        plan = Plan()
        free = dict(free)
        usage = self._usage(running)
        tenant_chips: Dict[str, float] = {}
        for job in running:
            for tenant, share in tenant_shares(job):
                tenant_chips[tenant] = \
                    tenant_chips.get(tenant, 0) + share

        # Claims already being torn down: capacity that will free
        # without any new eviction, per slice type.
        preempting_counts: Dict[str, int] = {}
        for job in running:
            if job.phase == "Preempting":
                preempting_counts[job.slice_type] = \
                    preempting_counts.get(job.slice_type, 0) + job.count
                plan.decisions[job.key] = Decision(
                    action=PREEMPT, reason="Preempting",
                    message="eviction in progress")

        blocked: List[JobView] = []   # capacity-blocked, in pick order
        candidates = list(pending)
        while candidates:
            job = self._pick(candidates, tenant_chips)
            candidates.remove(job)
            plan.order.append(job.key)

            if capacity.get(job.slice_type, 0) < job.count:
                plan.decisions[job.key] = Decision(
                    action=UNSATISFIABLE, reason="UnsatisfiableResources",
                    message=(f"requires {job.count} x {job.slice_type} "
                             f"but cluster capacity is "
                             f"{capacity.get(job.slice_type, 0)}"))
                continue

            # Quota checks bill per tenant SHARE: a singleton is its own
            # whole demand; a fused gang charges each member's tenant
            # chips/len(members) (tenant_shares).
            verdict = None
            for tenant, share in tenant_shares(job):
                quota = self.config.quota_chips(tenant, job.slice_type)
                if quota is None:
                    continue
                used = usage.get((tenant, job.slice_type), 0)
                if share > quota:
                    # Exceeds the tenant's ceiling even with NOTHING
                    # else admitted: it can never run under this
                    # config — terminal, like the capacity-
                    # unsatisfiable path, not a permanent queue
                    # squatter.
                    verdict = Decision(
                        action=UNSATISFIABLE, reason="QuotaUnsatisfiable",
                        message=(f"requires {share:g} chips of "
                                 f"{job.slice_type} but tenant "
                                 f"{tenant!r} quota is {quota}"))
                    break
                if used + share > quota:
                    # Skipped, not blocking: quota is the tenant's own
                    # ceiling, and a capped tenant must not wedge
                    # others.
                    verdict = Decision(
                        action=WAIT, reason="QuotaExceeded",
                        message=(f"tenant {tenant!r} at "
                                 f"{used:g}/{quota} chips of "
                                 f"{job.slice_type}"))
                    break
            if verdict is not None:
                plan.decisions[job.key] = verdict
                continue

            fits = free.get(job.slice_type, 0) >= job.count
            if fits and blocked and not self.config.enable_backfill:
                plan.decisions[job.key] = Decision(
                    action=WAIT, reason="BackfillDenied",
                    message="backfill disabled; waiting behind the "
                            "blocked queue head")
                blocked.append(job)
                continue
            if fits and not self._would_delay(job, blocked, free):
                plan.decisions[job.key] = Decision(
                    action=ADMIT, reason="Admitted",
                    backfilled=bool(blocked))
                free[job.slice_type] -= job.count
                for tenant, share in tenant_shares(job):
                    usage[(tenant, job.slice_type)] = \
                        usage.get((tenant, job.slice_type), 0) + share
                    tenant_chips[tenant] = \
                        tenant_chips.get(tenant, 0) + share
                continue

            if fits:
                decision = Decision(
                    action=WAIT, reason="BackfillDenied",
                    message=("admission now could delay a queued "
                             "higher-priority job"))
            else:
                decision = Decision(
                    action=WAIT, reason="WaitingForSlices",
                    message=(f"{free.get(job.slice_type, 0)} free of "
                             f"{job.count} x {job.slice_type} needed"))
            plan.decisions[job.key] = decision
            blocked.append(job)

        if self.config.preemption.enable:
            self._plan_preemptions(plan, blocked, running, free,
                                   preempting_counts)
        # Cancel evictions whose shortage resolved during the grace
        # window (preemptor deleted, or another gang finished): a
        # victim's teardown is only justified while some blocked job
        # of its slice type is still waiting on incoming capacity.
        still_short = {
            job.slice_type for job in blocked
            if plan.decisions[job.key].reason == "WaitingForPreemption"}
        for job in running:
            if job.phase != "Preempting":
                continue
            decision = plan.decisions.get(job.key)
            already_victim = any(v == job.key
                                 for v, _ in plan.preemptions)
            if (decision is not None and decision.action == PREEMPT
                    and not already_victim
                    and job.slice_type not in still_short):
                plan.decisions[job.key] = Decision(
                    action=ADMIT, reason="PreemptionCancelled",
                    message="capacity shortage resolved during the "
                            "grace window; eviction cancelled")
        return plan

    # -- internals ---------------------------------------------------------

    @staticmethod
    def _usage(running: List[JobView]) -> Dict[Tuple[str, str], float]:
        """Admitted chips by (tenant, slice_type) — fused gangs billed
        per-member via :func:`tenant_shares`."""
        usage: Dict[Tuple[str, str], float] = {}
        for job in running:
            for tenant, share in tenant_shares(job):
                key = (tenant, job.slice_type)
                usage[key] = usage.get(key, 0) + share
        return usage

    def _pick(self, candidates: List[JobView],
              tenant_chips: Dict[str, float]) -> JobView:
        """Next job: strict priority, then least admitted chips per
        weight across tenants (recomputed against simulated
        admissions), then FIFO.  A fused gang ranks by its
        LEAST-served member tenant — the gang is pulled forward by
        whichever member fair-share would pick first."""
        def rank(job: JobView):
            fair = min(
                tenant_chips.get(tenant, 0) / self.config.weight(tenant)
                for tenant, _ in tenant_shares(job))
            return (-job.priority_value, fair, job.enqueued_at, job.key)
        return min(candidates, key=rank)

    @staticmethod
    def _would_delay(job: JobView, blocked: List[JobView],
                     free: Dict[str, int]) -> bool:
        """True when admitting ``job`` could push back any already
        capacity-blocked (hence higher pick-order) job's earliest
        start.  Same-type: safe only if the blocked demand still fits
        the post-admission free pool.  Cross-type claims are disjoint
        and always safe."""
        for b in blocked:
            if b.slice_type != job.slice_type:
                continue
            if free.get(job.slice_type, 0) - job.count < b.count:
                return True
        return False

    def _plan_preemptions(self, plan: Plan, blocked: List[JobView],
                          running: List[JobView], free: Dict[str, int],
                          preempting_counts: Dict[str, int]) -> None:
        """Evict for capacity-blocked jobs, highest pick-order first.

        Claims already mid-teardown count as incoming capacity: a
        blocked job whose demand is covered by in-progress evictions
        waits for them instead of triggering more (one eviction wave
        per shortage, however many passes the grace window spans).
        """
        victims_taken: set = set()
        # Per-type capacity each blocked job can draw on WITHOUT a new
        # eviction wave: free slices plus claims already mid-teardown.
        # Every satisfied blocked job RESERVES its demand from this
        # pool — one incoming slice must not absolve two waiters.
        avail = {t: free.get(t, 0) + preempting_counts.get(t, 0)
                 for t in set(free) | set(preempting_counts)}
        for job in blocked:
            decision = plan.decisions[job.key]
            if decision.reason != "WaitingForSlices":
                continue
            have = avail.get(job.slice_type, 0)
            if have >= job.count:
                avail[job.slice_type] = have - job.count
                decision.reason = "WaitingForPreemption"
                decision.message = "eviction in progress frees capacity"
                continue
            pool = [v for v in running
                    if v.slice_type == job.slice_type
                    and v.phase != "Preempting"
                    and v.key not in victims_taken]
            victims = pick_victims(pool, job, have)
            if not victims:
                continue
            if not self.limiter.allow(len(victims)):
                # Budget is per evicted gang; a wave that doesn't fit
                # whole is deferred (partial eviction frees nothing).
                decision.reason = "PreemptionRateLimited"
                decision.message = (
                    f"eviction budget spent "
                    f"({self.limiter.max_preemptions} per "
                    f"{self.limiter.window_s:.0f}s)")
                continue
            faults.fire("scheduler.preempt")
            for _ in victims:
                self.limiter.record()
            for v in victims:
                victims_taken.add(v.key)
                have += v.count
                plan.decisions[v.key] = Decision(
                    action=PREEMPT, reason="Preempted",
                    message=(f"evicted for higher-priority "
                             f"{job.key}"),
                    preemptor=job.key)
                plan.preemptions.append((v.key, job.key))
            avail[job.slice_type] = have - job.count
            decision.reason = "WaitingForPreemption"
            decision.message = (
                f"evicting {len(victims)} lower-priority job(s)")
