"""Fusable-family detection: fold queued singleton jobs into one gang.

The admission-side half of the HFTA tier (runtime/hfta.py holds the
training half).  A swarm of same-architecture tuning jobs — each a
singleton gang that under-fills a slice — opts in by sharing a
``kubeflow-tpu.org/fuse-family`` label value; every plan pass this
module folds compatible pending singletons into ONE fused
:class:`~kubeflow_tpu.scheduler.policy.JobView` (one gang claim, N
members, near-N× utilization) before the policy sees them, and
regroups the member CRs of an already-admitted fused gang back into
their gang view so inventory is charged once while quota/fair-share
bill each member's tenant its share (``policy.tenant_shares``).

Compatibility is deliberately structural: same namespace + family +
slice type + priority class, singleton demand (``num_slices == 1``,
the compatible-budget floor — a multi-slice job has nothing to gain
from sharing one slice).  Same-architecture/shape is the FAMILY
LABEL'S assertion — the scheduler cannot see model graphs, so a family
value is the user's contract that its members stack (runtime/hfta.py
rejects mismatched pytrees at stack time, the backstop).

Decisions for a fused view are MIRRORED onto every member key
(``Decision.fused_gang`` / ``fused_members`` / ``fused_leader``), so
the reconciler drives ordinary member CRs: the leader materializes one
pod gang under the fused claim, every member's phase follows it, and
preemption requeues all members individually resumable — each resumes
from its own per-member verified checkpoint.

Hook site ``scheduler.fuse`` fires once per fused gang formed — the
chaos harness wedges or skews fold passes exactly like
``scheduler.admit``/``scheduler.preempt``.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from kubeflow_tpu.scheduler.policy import (  # noqa: F401 (re-export)
    LABEL_FUSE_FAMILY,
    Decision,
    JobView,
    Plan,
)
from kubeflow_tpu.testing import faults

# Fused gang claim keys live in their own namespace-prefixed space so
# they can never collide with a CR's "namespace/name" key.
FUSED_PREFIX = "fused:"

# Fusion needs at least two members to buy anything; the ceiling bounds
# per-member HBM headroom loss on one slice (HFTA's own sweep fuses
# single digits of members per accelerator).
MIN_MEMBERS = 2
MAX_MEMBERS = 8


def fused_gang_key(namespace: str, family: str) -> str:
    return f"{FUSED_PREFIX}{namespace}/{family}"


def fused_gang_name(gang_key: str) -> str:
    """Pod/service-safe name for a fused gang's workload objects."""
    family = gang_key[len(FUSED_PREFIX):].split("/", 1)[-1]
    return f"fused-{family}"


def _fused_view(gang_key: str, members: List[JobView]) -> JobView:
    members = sorted(members, key=lambda m: (m.enqueued_at, m.key))
    base = members[0]
    phase = ("Preempting"
             if any(m.phase == "Preempting" for m in members)
             else base.phase)
    return JobView(
        key=gang_key,
        tenant=",".join(sorted({m.tenant for m in members})),
        priority=base.priority,
        priority_value=base.priority_value,
        slice_type=base.slice_type,
        count=base.count,
        chips=base.chips,
        phase=phase,
        enqueued_at=base.enqueued_at,
        resumable=any(m.resumable for m in members),
        preemptions=max(m.preemptions for m in members),
        family=base.family,
        members=tuple(members),
        fused_members=len(members),
    )


def _stamp_members(fused: JobView) -> None:
    for m in fused.members:
        m.fused_gang = fused.key
        m.fused_members = len(fused.members)


def fold_pending(
    pending: List[JobView], gang=None,
) -> Tuple[List[JobView], List[JobView]]:
    """Fold compatible pending singletons into fused views.

    Returns ``(plan_input, fused_views)``: the pending list with folded
    members replaced by their fused view (position = oldest member's),
    and the fused views alone for decision mirroring.  Members keep
    their individual enqueue times; the gang inherits the OLDEST so
    fusion never costs a member its queue position.
    """
    groups: Dict[Tuple[str, str, str, str], List[JobView]] = {}
    for view in pending:
        if not view.family or view.count != 1:
            continue
        namespace = view.key.split("/", 1)[0]
        groups.setdefault(
            (namespace, view.family, view.slice_type, view.priority),
            []).append(view)

    fused_views: List[JobView] = []
    folded: Dict[str, JobView] = {}   # member key -> fused view
    for (namespace, family, _, _), members in sorted(groups.items()):
        if len(members) < MIN_MEMBERS:
            continue
        # One fused gang per family per pass; an overflow tail stays
        # pending as ordinary singletons until the gang completes.
        gkey = fused_gang_key(namespace, family)
        if any(f.key == gkey for f in fused_views):
            # Same family under a second slice type/priority: first
            # (sorted) group wins the key; the rest stay singletons.
            continue
        if gang is not None and gang.admitted(gkey):
            # A fused gang of this family is already running; late
            # arrivals queue as singletons until it completes.
            continue
        members = sorted(members, key=lambda m: (m.enqueued_at, m.key))
        batch = members[:MAX_MEMBERS]
        faults.fire("scheduler.fuse")
        fused = _fused_view(gkey, batch)
        _stamp_members(fused)
        fused_views.append(fused)
        for m in batch:
            folded[m.key] = fused

    plan_input: List[JobView] = []
    seen_fused: set = set()
    for view in pending:
        fused = folded.get(view.key)
        if fused is None:
            plan_input.append(view)
        elif fused.key not in seen_fused:
            seen_fused.add(fused.key)
            plan_input.append(fused)
    return plan_input, fused_views


def fold_running(
    running: List[JobView], gang
) -> Tuple[List[JobView], List[JobView]]:
    """Regroup member CR views of admitted fused gangs into their gang
    view, so inventory/preemption see ONE claim while quota bills per
    member.  Non-fused running views pass through untouched."""
    by_gang: Dict[str, List[JobView]] = {}
    plan_input: List[JobView] = []
    order: List[str] = []
    for view in running:
        if view.fused_gang and gang.admitted(view.fused_gang):
            if view.fused_gang not in by_gang:
                order.append(view.fused_gang)
            by_gang.setdefault(view.fused_gang, []).append(view)
        else:
            plan_input.append(view)
    fused_views: List[JobView] = []
    for gang_key in order:
        fused = _fused_view(gang_key, by_gang[gang_key])
        _stamp_members(fused)
        fused_views.append(fused)
        plan_input.append(fused)
    return plan_input, fused_views


def mirror_decisions(plan: Plan, fused_views: List[JobView]) -> None:
    """Copy each fused view's verdict onto every member key so the
    reconciler can drive ordinary member CRs, and expand the plan's
    consideration order from gang keys back to member keys."""
    for fused in fused_views:
        decision = plan.decisions.get(fused.key)
        if decision is None:
            # The policy only issues verdicts for pending views and
            # preemption victims; a fused view it left alone is a
            # RUNNING admitted gang (fold_running only groups members
            # whose claim is live) — synthesize the keep verdict so
            # members keep reconciling under the fused branch instead
            # of falling back to singleton requeue.
            decision = Decision(action="admit", reason="Admitted",
                                message="fused gang running")
        member_keys = tuple(m.key for m in fused.members)
        for i, m in enumerate(fused.members):
            plan.decisions[m.key] = dataclasses.replace(
                decision,
                message=(f"{decision.message} "
                         f"[fused gang {fused.key}, member "
                         f"{i + 1}/{len(member_keys)}]").strip(),
                fused_gang=fused.key,
                fused_members=member_keys,
                fused_leader=(i == 0),
            )
        if fused.key in plan.order:
            at = plan.order.index(fused.key)
            plan.order[at:at + 1] = list(member_keys)
