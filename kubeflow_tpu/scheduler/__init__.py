"""Multi-tenant cluster scheduling control plane.

The policy layer BETWEEN submitted ``TPUJob`` CRs and the gang placer
(``operator/gang.py``).  The gang scheduler answers "does this job's
full slice demand fit right now"; this package answers "which job
should be offered next, whose claim should be revoked, and why" —
per-tenant quotas, weighted-fair ordering, strict priority classes,
conservative backfill, and preemption-with-resume.  Both *Gavel*
(heterogeneity-aware cluster scheduling) and the speculative-container
scheduling line of work locate the win exactly here: a policy core
above the placer, not a smarter placer.

Layout:
    policy.py   SchedulerConfig + the admission-plan engine
    queue.py    persistent pending-queue bookkeeping + ClusterScheduler
                (the facade the reconciler consults)
    preempt.py  victim selection + the preemption rate limiter
    fuse.py     horizontal fusion: fold fusable singleton swarms into
                one gang (the HFTA admission tier; runtime/hfta.py is
                the training half)
    colocate.py train/serve colocation: the fleet autoscaler's desired
                replicas as a high-priority ServingClaim on the SAME
                pool (elastic grow/shrink, short-grace preemption,
                speculative prepull)
"""

from kubeflow_tpu.scheduler.colocate import (  # noqa: F401
    LABEL_DEPLOYMENT,
    LABEL_WORKLOAD,
    SERVING_PRIORITY,
    SERVING_TENANT,
    WORKLOAD_SERVING,
    ServingClaimClient,
    build_claim_cr,
    claim_key,
    claim_name,
)
from kubeflow_tpu.scheduler.fuse import (  # noqa: F401
    LABEL_FUSE_FAMILY,
    fold_pending,
    fused_gang_key,
    fused_gang_name,
)
from kubeflow_tpu.scheduler.policy import (  # noqa: F401
    DEFAULT_PRIORITY_CLASSES,
    LABEL_PRIORITY,
    LABEL_TENANT,
    Decision,
    JobView,
    Plan,
    SchedulerConfig,
    SchedulingPolicy,
    tenant_shares,
)
from kubeflow_tpu.scheduler.preempt import (  # noqa: F401
    PreemptionConfig,
    PreemptionRateLimiter,
    pick_victims,
)
from kubeflow_tpu.scheduler.queue import (  # noqa: F401
    ClusterScheduler,
    SchedulerQueue,
)
