"""Pending-queue bookkeeping and the ClusterScheduler facade.

The reconcile loop is level-triggered and stateless per pass; the
queue here is the one piece of scheduler state that must PERSIST
across passes — when each job first became pending (fair FIFO
tie-breaks and the queue-wait metric both depend on it surviving the
poll loop), and the counters the ``kft_scheduler_*`` surface exports.

:class:`ClusterScheduler` is what the reconciler consults: it turns
the raw CR list into :class:`~kubeflow_tpu.scheduler.policy.JobView`s,
asks the policy for a :class:`~kubeflow_tpu.scheduler.policy.Plan`,
and owns metrics + the ``queue status`` JSON the CLI renders.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional, Tuple

from kubeflow_tpu.operator import crd
from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.scheduler import colocate, fuse
from kubeflow_tpu.scheduler.policy import (
    ADMIT,
    PREEMPT,
    JobView,
    Plan,
    SchedulerConfig,
    SchedulingPolicy,
    job_view,
)
from kubeflow_tpu.scheduler.preempt import PreemptionRateLimiter
from kubeflow_tpu.testing import faults

log = logging.getLogger(__name__)

# Queue-wait buckets: gang admission waits are seconds to hours, not
# request latencies.
_WAIT_BUCKETS = (0.5, 1.0, 5.0, 15.0, 60.0, 300.0, 900.0, 3600.0,
                 14400.0)


@dataclasses.dataclass
class _QueueEntry:
    enqueued_at: float


class SchedulerQueue:
    """Persistent pending-set bookkeeping (enqueue times + waits).

    ``_waits`` is a bounded window of the most recent admissions: the
    all-time distribution lives in the Prometheus histogram; the CLI
    percentiles should reflect the cluster NOW, and an unbounded list
    re-sorted per /queue request would grow for the operator's whole
    life."""

    WAIT_WINDOW = 512

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, _QueueEntry] = {}
        self._waits: "collections.deque[float]" = collections.deque(
            maxlen=self.WAIT_WINDOW)

    def touch(self, job: JobView) -> float:
        """Record (or refresh) a pending job; returns its stable
        enqueue time on the policy clock."""
        with self._lock:
            entry = self._entries.get(job.key)
            if entry is None:
                entry = _QueueEntry(enqueued_at=faults.monotonic())
                self._entries[job.key] = entry
            return entry.enqueued_at

    def note_admitted(self, key: str) -> Optional[float]:
        """Pending -> admitted: returns the queue wait (None if the
        job was never seen pending, e.g. admitted on its first pass
        before any plan)."""
        with self._lock:
            entry = self._entries.pop(key, None)
            if entry is None:
                return None
            wait = max(0.0, faults.monotonic() - entry.enqueued_at)
            self._waits.append(wait)
            return wait

    def forget(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)

    def prune(self, live_keys) -> None:
        """Drop entries whose CR vanished (deleted while queued)."""
        live = set(live_keys)
        with self._lock:
            for key in [k for k in self._entries if k not in live]:
                del self._entries[key]

    def wait_of(self, key: str) -> Optional[float]:
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                return None
            return max(0.0, faults.monotonic() - entry.enqueued_at)

    def wait_percentiles(self) -> Dict[str, Optional[float]]:
        with self._lock:
            waits = sorted(self._waits)
        if not waits:
            return {"p50": None, "p99": None}
        return {
            "p50": waits[len(waits) // 2],
            "p99": waits[min(len(waits) - 1,
                             int(len(waits) * 0.99))],
        }

    def depth(self) -> int:
        with self._lock:
            return len(self._entries)


class ClusterScheduler:
    """The policy control plane the reconciler consults each pass.

    Sits ABOVE the :class:`~kubeflow_tpu.operator.gang.GangScheduler`:
    the gang owns inventory accounting and atomic claims; this layer
    decides which offers to make, in what order, and which claims to
    revoke.  Single reconcile-thread discipline: ``plan`` and the
    ``note_*`` callbacks are called from the reconcile loop only;
    ``status()`` may be read from the HTTP status route concurrently.
    """

    # Pending phases from the policy's standpoint; anything admitted
    # in the gang is "running" regardless of pod readiness.
    _TERMINAL = ("Succeeded", "Failed")

    def __init__(self, gang, config: Optional[SchedulerConfig] = None):
        self.gang = gang
        self.config = config or SchedulerConfig()
        self.limiter = PreemptionRateLimiter(
            self.config.preemption.max_preemptions,
            self.config.preemption.window_s)
        self.policy = SchedulingPolicy(self.config, self.limiter)
        self.queue = SchedulerQueue()
        self._lock = threading.Lock()
        self._last_plan = Plan()
        self._last_views: Dict[str, JobView] = {}
        self._queue_warned: set = set()
        self._counters = {"admitted": 0, "backfilled": 0,
                          "preempted": 0, "resumed": 0}

    # -- the reconcile-loop surface ---------------------------------------

    def plan(self, cr_objs: List[dict]) -> Plan:
        """Build this pass's admission plan from the raw CR list.

        Unparseable specs are skipped here — the reconciler fails them
        with InvalidSpec through its own error path; the policy must
        not let one bad CR wedge the whole plan (hook site
        ``scheduler.admit`` lets the fault harness do exactly that on
        purpose).
        """
        # Per-pass trace span: one single-span trace per plan pass
        # (tail-sampled like everything else; a raising pass ends with
        # status "error" and is always retained), annotated with the
        # verdict counts — the operator-side analogue of the serving
        # path's request spans.
        span = tracing.start_span("scheduler.plan")
        try:
            plan = self._plan_inner(cr_objs)
        except BaseException:
            span.end(status="error")
            raise
        counts: Dict[str, int] = {}
        for decision in plan.decisions.values():
            counts[decision.action] = counts.get(decision.action, 0) + 1
        span.end(status="ok", **counts)
        return plan

    def _plan_inner(self, cr_objs: List[dict]) -> Plan:
        faults.fire("scheduler.admit")
        pending: List[JobView] = []
        running: List[JobView] = []
        views: Dict[str, JobView] = {}
        for cr_obj in cr_objs:
            if cr_obj.get("kind") != crd.KIND:
                continue
            try:
                spec = crd.TPUJobSpec.from_custom_resource(cr_obj)
            except ValueError:
                continue
            if spec.queue and spec.queue not in self._queue_warned:
                # The gang's per-queue FIFO lanes are superseded here:
                # ordering comes from tenant/priority labels.  Loud
                # once per lane name, because a user relying on
                # `queue:` separation gets different admission order
                # under the (default-on) policy layer.
                self._queue_warned.add(spec.queue)
                log.warning(
                    "TPUJob %s/%s sets spec.queue=%r, which the "
                    "multi-tenant scheduler ignores — use the %s / %s "
                    "labels (or run the operator with --no-scheduler "
                    "for gang-FIFO queue lanes)",
                    spec.namespace, spec.name, spec.queue,
                    "kubeflow-tpu.org/tenant",
                    "kubeflow-tpu.org/priority")
            view = job_view(cr_obj, spec, self.config)
            views[view.key] = view
            if view.phase in self._TERMINAL:
                continue
            if self.gang.admitted(view.key) or (
                    view.fused_gang
                    and self.gang.admitted(view.fused_gang)):
                running.append(view)
            else:
                view.fused_gang = ""   # stale stamp: gang released
                view.enqueued_at = self.queue.touch(view)
                pending.append(view)
        # Train/serve colocation: split admitted serving claims into a
        # held base view plus a pending grow-delta view BEFORE the
        # prune (the grow key's queue entry must survive it), so the
        # policy arbitrates the increment as ordinary high-priority
        # demand.
        pending, running, grow_views, serving_keys = colocate.fold(
            pending, running, self.gang, self.queue)
        self.queue.prune([v.key for v in pending])
        # Horizontal fusion: fold compatible pending singletons into
        # one gang view, regroup admitted fused members back into
        # theirs, then mirror the gang verdicts onto member keys so
        # the reconciler drives ordinary member CRs.
        pending, fused_pending = fuse.fold_pending(pending, self.gang)
        running, fused_running = fuse.fold_running(running, self.gang)
        free = {t: self.gang.free(t) for t in self.gang.capacity}
        plan = self.policy.plan(pending, running, free,
                                dict(self.gang.capacity))
        # Merge grow verdicts onto base keys and stamp the short
        # serving grace BEFORE mirroring, so a fused victim's members
        # inherit the override.
        colocated = colocate.finalize(
            plan, grow_views, serving_keys,
            self.config.preemption.serving_grace_period_s)
        if colocated:
            from kubeflow_tpu.runtime.prom import REGISTRY

            REGISTRY.counter(
                "kft_scheduler_colocation_preemptions_total",
                "training gangs evicted for serving claims",
            ).inc(colocated)
        fuse.mirror_decisions(plan, fused_pending + fused_running)
        with self._lock:
            self._last_plan = plan
            self._last_views = views
        self._export_metrics(pending, running,
                             fused_pending + fused_running)
        return plan

    def note_admitted(self, key: str, backfilled: bool = False,
                      resumed: bool = False) -> None:
        wait = self.queue.note_admitted(key)
        from kubeflow_tpu.runtime.prom import REGISTRY

        # View lookup joins the counter update under the lock:
        # plan() REBINDS _last_views under it, and the tenant label
        # must come from the same snapshot the caller's plan produced
        # (status() reads both under this lock too).
        with self._lock:
            view = self._last_views.get(key)
            self._counters["admitted"] += 1
            if backfilled:
                self._counters["backfilled"] += 1
            if resumed:
                self._counters["resumed"] += 1
        tenant = view.tenant if view else "default"
        REGISTRY.counter(
            "kft_scheduler_admitted_total",
            "jobs admitted through the policy layer").inc(tenant=tenant)
        if backfilled:
            REGISTRY.counter(
                "kft_scheduler_backfills_total",
                "jobs admitted ahead of blocked higher-priority work "
                "(provably no ETA delay)").inc(tenant=tenant)
        if resumed:
            REGISTRY.counter(
                "kft_scheduler_resumes_total",
                "preempted jobs re-admitted to resume from their "
                "latest checkpoint").inc(tenant=tenant)
        if wait is not None:
            REGISTRY.histogram(
                "kft_scheduler_queue_wait_seconds",
                "pending-to-admitted wait through the policy queue",
                buckets=_WAIT_BUCKETS).observe(wait)

    def note_preempted(self, key: str) -> None:
        with self._lock:
            view = self._last_views.get(key)
            self._counters["preempted"] += 1
        tenant = view.tenant if view else "default"
        from kubeflow_tpu.runtime.prom import REGISTRY

        REGISTRY.counter(
            "kft_scheduler_preemptions_total",
            "gangs evicted for higher-priority work").inc(tenant=tenant)

    def forget(self, key: str) -> None:
        """Job reached a terminal phase (or its CR vanished)."""
        self.queue.forget(key)

    # -- observability -----------------------------------------------------

    def _export_metrics(self, pending: List[JobView],
                        running: List[JobView],
                        fused: List[JobView] = ()) -> None:
        from kubeflow_tpu.runtime.prom import REGISTRY

        REGISTRY.gauge(
            "kft_scheduler_fused_gangs",
            "fused training gangs in the current plan "
            "(pending folds + admitted)").set(float(len(fused)))
        REGISTRY.gauge(
            "kft_scheduler_fused_members",
            "member jobs folded into fused gangs in the current "
            "plan").set(float(sum(len(f.members) for f in fused)))

        claim = REGISTRY.gauge(
            "kft_scheduler_serving_claim_chips",
            "chips held by admitted serving claims")
        for labels in claim.labelsets():
            claim.set(0, **labels)
        for job in running:
            # Post-fold base views carry the HELD count (what the
            # gang claim actually bills), not the CR's desired count.
            if job.workload == colocate.WORKLOAD_SERVING:
                claim.set(job.chips, claim=job.key)

        depth = REGISTRY.gauge(
            "kft_scheduler_queue_depth",
            "pending TPUJobs by tenant and priority class")
        by_bucket: Dict[tuple, int] = {}
        for job in pending:
            k = (job.tenant, job.priority)
            by_bucket[k] = by_bucket.get(k, 0) + 1
        # Zero stale series: a bucket that drained must scrape as 0,
        # not hold its last value.
        for labels in depth.labelsets():
            depth.set(0, **labels)
        for (tenant, priority), n in by_bucket.items():
            depth.set(n, tenant=tenant, priority=priority)

        used = REGISTRY.gauge(
            "kft_scheduler_quota_used_chips",
            "admitted chips by tenant and slice type")
        limit = REGISTRY.gauge(
            "kft_scheduler_quota_chips",
            "configured quota ceiling by tenant and slice type")
        usage = SchedulingPolicy._usage(running)
        for labels in used.labelsets():
            used.set(0, **labels)
        for (tenant, slice_type), chips in usage.items():
            used.set(chips, tenant=tenant, slice_type=slice_type)
        for tenant, per_type in self.config.quotas.items():
            for slice_type, chips in per_type.items():
                limit.set(chips, tenant=tenant, slice_type=slice_type)

    def status(self) -> dict:
        """The ``kubeflow-tpu queue status`` payload: every live job
        with its plan verdict, plus quota utilization and waits."""
        with self._lock:
            plan = self._last_plan
            views = dict(self._last_views)
        position = {key: i for i, key in enumerate(plan.order)}
        jobs: List[dict] = []
        for key, view in sorted(
                views.items(),
                key=lambda kv: (position.get(kv[0], len(position)),
                                kv[0])):
            if view.phase in self._TERMINAL:
                continue
            decision = plan.decisions.get(key)
            admitted = self.gang.admitted(key) or (
                view.fused_gang and self.gang.admitted(view.fused_gang))
            if admitted:
                state = ("Preempting"
                         if decision is not None
                         and decision.action == PREEMPT
                         else "Admitted")
            elif decision is None:
                state = "Pending"
            elif decision.action == ADMIT:
                state = "Admitting"
            else:
                state = decision.reason or "Pending"
            wait = self.queue.wait_of(key)
            # A fused member's chips column shows its SHARE of the
            # gang slice — the quantity its tenant is billed.
            chips = (view.chips / view.fused_members
                     if view.fused_members else view.chips)
            jobs.append({
                "job": key,
                "kind": ("serving-claim"
                         if view.workload == colocate.WORKLOAD_SERVING
                         else "train"),
                "tenant": view.tenant,
                "priority": view.priority,
                "slices": f"{view.count}x{view.slice_type}",
                "chips": chips,
                "state": state,
                "detail": (decision.message if decision else ""),
                "position": position.get(key),
                "wait_s": round(wait, 3) if wait is not None else None,
                "resumable": view.resumable,
                "preemptions": view.preemptions,
                "members": view.fused_members or None,
            })
        quotas = []
        # Fused-aware usage over LIVE claims (a job admitted during
        # the current sweep was still pending at plan time, so a
        # plan-time snapshot would under-bill): each fused member
        # bills its tenant its SHARE of the gang slice, a singleton
        # its whole gang.
        usage: Dict[Tuple[str, str], float] = {}
        for view in views.values():
            if view.phase in self._TERMINAL:
                continue
            if not (self.gang.admitted(view.key) or
                    (view.fused_gang and
                     self.gang.admitted(view.fused_gang))):
                continue
            share = (view.chips / view.fused_members
                     if view.fused_members else view.chips)
            slot = (view.tenant, view.slice_type)
            usage[slot] = usage.get(slot, 0) + share
        for tenant, per_type in sorted(self.config.quotas.items()):
            for slice_type, chips in sorted(per_type.items()):
                quotas.append({
                    "tenant": tenant, "slice_type": slice_type,
                    "used_chips": usage.get((tenant, slice_type), 0),
                    "quota_chips": chips})
        with self._lock:
            counters = dict(self._counters)
        return {
            "jobs": jobs,
            "quotas": quotas,
            "queue_wait": self.queue.wait_percentiles(),
            "counters": counters,
            "preemptions_in_window": self.limiter.in_window(),
            "pool": self.pool_status(),
        }

    def pool_status(self) -> dict:
        """Combined-pool chip accounting (train + serve on ONE
        inventory) — the fleet status footer's data source, stamped
        onto claim CR status by the reconciler each grant."""
        from kubeflow_tpu.runtime.topology import parse_slice_type

        capacity = used = 0
        per_type: Dict[str, int] = {}
        for slice_type, count in self.gang.capacity.items():
            try:
                per = parse_slice_type(slice_type).chips
            except ValueError:
                per = 0
            per_type[slice_type] = per
            capacity += per * count
            used += per * (count - self.gang.free(slice_type))
        with self._lock:
            views = dict(self._last_views)
        serving = 0
        for key, view in views.items():
            if view.workload != colocate.WORKLOAD_SERVING:
                continue
            held = self.gang.claim_count(key)
            if held:
                serving += per_type.get(view.slice_type, 0) * held
        return {
            "capacity_chips": capacity,
            "used_chips": used,
            "free_chips": capacity - used,
            "serving_chips": serving,
            "training_chips": used - serving,
        }
