"""Generated protobuf modules for the serving wire contract."""
