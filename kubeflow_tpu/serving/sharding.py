"""Serving-side mesh construction and param/KV-pool partitioning.

The training stack already shards over a ``jax.sharding.Mesh``
(parallel/mesh.py); this module is the SERVING half of that story: one
model spanning several chips of a pod slice, so per-replica batch
capacity multiplies and a model bigger than one chip's HBM still
serves.  Three pieces:

  mesh        ``build_mesh({"tensor": N})`` — a 1-D serving mesh over
              the first N local devices (the ``--mesh tensor=N`` flag;
              multi-device on CPU via
              ``XLA_FLAGS=--xla_force_host_platform_device_count``,
              the same trick the MULTICHIP dryruns and the test
              conftest use).  Tensor parallelism is latency-bound and
              must ride adjacent-ICI links, which is why serving
              exposes exactly one axis: ``jax.devices()`` orders
              contiguous runs ICI-adjacent, so a 1-D reshape lands
              the whole axis on neighbouring chips (and a
              disaggregated fleet keeps each pool's collectives on
              its OWN links instead of contending across tiers).

  rules       ``match_partition_rules(rules, params)`` — regex rules
              over '/'-joined param-tree paths to PartitionSpecs, the
              pattern the big JAX LM codebases converged on.
              ``LM_PARTITION_RULES`` is the megatron-style layout for
              models/generate.py's param tree: attention heads and MLP
              hidden column-split, output projections row-split (XLA
              inserts the all-reduce after the row-parallel matmul),
              vocab split on the embedding table.  A dim that does not
              divide the mesh axis degrades to replicated (with a
              warning) instead of erroring — tiny smoke models shard
              what they can.

  placement   ``shard_params`` / ``shard_paged_state`` device_put the
              param tree and the engine's paged KV block pool with
              NamedShardings.  The pool ([layers, blocks, block_tokens,
              hkv, d], fp or int8 QTensor) shards on the KV-HEAD dim:
              block indices stay replicated, so the HOST-owned block
              tables — and every scatter/gather through them — are
              unchanged, and the three AOT programs (chunked prefill /
              step / verify) compile tensor-parallel from the argument
              shardings alone.  Per-slot scalars replicate.

Everything here is host-side setup that runs once at engine
construction; nothing touches the step loop.
"""

from __future__ import annotations

import logging
import re
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.ops.quantize import QTensor

log = logging.getLogger(__name__)

# The serving mesh axis.  parallel/mesh.py's 6-axis order exists for
# training; decode wants exactly the innermost (tensor) axis.
TENSOR = "tensor"

PartitionRules = Sequence[Tuple[str, PartitionSpec]]

# Megatron-style tensor-parallel layout for the LM param tree
# (models/generate.py _forward_with_cache; layers stacked on a leading
# scan axis).  Column-parallel projections split their OUTPUT dim
# (wq heads, wkv kv-heads, MLP hidden); row-parallel projections split
# their INPUT dim (wo heads, MLP down) so the all-reduce lands after
# the matmul; the (tied) embedding table splits on vocab.  Norm scales
# and anything unmatched replicate via the catch-all.
LM_PARTITION_RULES: PartitionRules = (
    # [L, e, h, d] — attention query projection, heads split.
    (r"layers/attn/wq$", PartitionSpec(None, None, TENSOR, None)),
    # [L, 2, e, hkv, d] — fused k/v projection, kv-heads split (must
    # match the KV pool's head sharding: the cache columns a head
    # writes live on the shard that computed them).
    (r"layers/attn/wkv$", PartitionSpec(None, None, None, TENSOR, None)),
    # [L, h, d, e] — output projection, row-parallel over heads.
    (r"layers/attn/wo$", PartitionSpec(None, TENSOR, None, None)),
    # [L, 2, e, f] — gate/up projections, hidden split.
    (r"layers/mlp/wi$", PartitionSpec(None, None, None, TENSOR)),
    # [L, f, e] — down projection, row-parallel over hidden.
    (r"layers/mlp/wo$", PartitionSpec(None, TENSOR, None)),
    # [V, e] — embedding (and tied LM head), vocab split.
    (r"embed$", PartitionSpec(TENSOR, None)),
    # [e, V] — untied LM head, vocab split.
    (r"w_out$", PartitionSpec(None, TENSOR)),
    # Stacked adapter-delta factors (serving/adapters.py, §5.11):
    # [rows, L, ...] low-rank pairs whose OUT-side factor mirrors its
    # base projection's split — the b-factor of a column-parallel
    # projection shards the same heads/kv-heads/hidden dim, the
    # a-factor of a row-parallel projection shards the same input dim
    # (its rank-r product is the partial sum XLA all-reduces) — so the
    # per-row gathered delta lands with exactly the base activation's
    # sharding.  The leading adapter-row axis always replicates: a
    # gather by slot index must see every row on every shard.  The
    # rank-r factors left unlisted replicate via the catch-all.
    # [rows, L, r, h, d] — q delta out-factor, heads split.
    (r"adapters/attn/wq_b$",
     PartitionSpec(None, None, None, TENSOR, None)),
    # [rows, L, 2, r, hkv, d] — k/v delta out-factor, kv-heads split.
    (r"adapters/attn/wkv_b$",
     PartitionSpec(None, None, None, None, TENSOR, None)),
    # [rows, L, h, d, r] — attn-out delta in-factor, row-parallel.
    (r"adapters/attn/wo_a$",
     PartitionSpec(None, None, TENSOR, None, None)),
    # [rows, L, 2, r, f] — gate/up delta out-factor, hidden split.
    (r"adapters/mlp/wi_b$",
     PartitionSpec(None, None, None, None, TENSOR)),
    # [rows, L, f, r] — MLP-down delta in-factor, row-parallel.
    (r"adapters/mlp/wo_a$",
     PartitionSpec(None, None, TENSOR, None)),
)


def parse_mesh_flag(spec: str) -> Dict[str, int]:
    """``--mesh`` grammar: ``axis=N[,axis=N...]`` — today the only
    serving axis is ``tensor`` (``"tensor=4"``).  Empty string means
    no mesh (single-device engine, exactly the pre-mesh behavior)."""
    out: Dict[str, int] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"mesh axis {part!r} must be axis=N (e.g. tensor=4)")
        axis, _, n = part.partition("=")
        axis = axis.strip()
        if axis != TENSOR:
            raise ValueError(
                f"unknown serving mesh axis {axis!r} (serving shards "
                f"over {TENSOR!r} only; training meshes live in "
                f"parallel/mesh.py)")
        try:
            size = int(n)
        except ValueError:
            raise ValueError(
                f"mesh axis size {n!r} is not an integer") from None
        if size < 1:
            raise ValueError(f"mesh axis size must be >= 1, got {size}")
        out[axis] = size
    return out


def build_mesh(axes: Dict[str, int],
               devices: Optional[Sequence[jax.Device]] = None,
               ) -> Optional[Mesh]:
    """A 1-D serving mesh over the first ``tensor`` local devices, or
    None when the spec is empty / size 1 (single-device engines take
    the untouched pre-mesh path — the mesh layer is strictly
    additive)."""
    size = int(axes.get(TENSOR, 1)) if axes else 1
    if size <= 1:
        return None
    devs = list(devices if devices is not None else jax.devices())
    if size > len(devs):
        raise ValueError(
            f"mesh tensor={size} exceeds the {len(devs)} visible "
            f"devices (on CPU, force more with XLA_FLAGS="
            f"--xla_force_host_platform_device_count={size})")
    return Mesh(np.asarray(devs[:size]), (TENSOR,))


def _path_str(path) -> str:
    """'/'-joined tree path: dict keys, dataclass attrs, and sequence
    indices all normalize to bare tokens so the regex rules read like
    file paths (``layers/attn/wq``)."""
    parts: List[str] = []
    for key in path:
        s = jax.tree_util.keystr((key,))
        parts.append(s.strip(".[]'\""))
    return "/".join(parts)


def match_partition_rules(rules: PartitionRules, params):
    """PartitionSpec per leaf by first regex match over the leaf's
    '/'-joined path (the fmengine/EasyLM pattern).  Scalars and
    unmatched leaves replicate; a matched spec whose rank exceeds the
    leaf's (e.g. a QTensor ``scale`` companion riding its values
    rule) degrades to replicated rather than erroring."""
    compiled = [(re.compile(pat), spec) for pat, spec in rules]

    def leaf_spec(path, leaf):
        ndim = getattr(leaf, "ndim", 0)
        if ndim == 0 or int(np.prod(leaf.shape)) == 1:
            return PartitionSpec()
        pstr = _path_str(path)
        for pat, spec in compiled:
            if pat.search(pstr):
                if len(spec) > ndim:
                    return PartitionSpec()
                return spec
        return PartitionSpec()

    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    return jax.tree_util.tree_unflatten(
        treedef, [leaf_spec(p, l) for p, l in flat])


def _divisible(spec: PartitionSpec, shape, mesh: Mesh,
               what: str) -> PartitionSpec:
    """Degrade each sharded dim that does not divide its mesh-axis
    size to replicated: tiny models (smoke configs, CPU e2e) shard
    the dims they can and replicate the rest, instead of failing the
    whole engine construction."""
    out = []
    changed = False
    for i, axis in enumerate(spec):
        if axis is None:
            out.append(None)
            continue
        size = int(np.prod([mesh.shape[a] for a in (
            (axis,) if isinstance(axis, str) else axis)]))
        if shape[i] % size:
            log.warning(
                "sharding %s: dim %d (size %d) does not divide mesh "
                "axis %r (size %d); replicating that dim", what, i,
                shape[i], axis, size)
            out.append(None)
            changed = True
        else:
            out.append(axis)
    return PartitionSpec(*out) if changed else spec


def shard_params(params, mesh: Mesh,
                 rules: PartitionRules = LM_PARTITION_RULES):
    """device_put the param tree onto the mesh under the rule table.
    Int8-quantized weights ride along: a QTensor's ``values`` leaf
    matches its param's rule (the path ends ``.../wq/values``) and its
    lower-rank ``scale`` replicates via the rank guard."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    spec_flat = jax.tree_util.tree_leaves(
        match_partition_rules(rules, params))
    placed = []
    for (path, leaf), spec in zip(flat, spec_flat):
        spec = _divisible(spec, leaf.shape, mesh, _path_str(path))
        placed.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, placed)


def _pool_spec(arr, mesh: Mesh, what: str) -> NamedSharding:
    """Paged-pool sharding: [L, blocks, block_tokens, hkv(, d)] —
    shard the KV-HEAD dim (index 3), replicate block geometry so the
    host-owned block tables address every shard identically.  An int8
    pool's ``scale`` companion ([L, blocks, bt, hkv]) shards the same
    head dim at rank 4."""
    spec = [None, None, None, TENSOR] + [None] * (arr.ndim - 4)
    return NamedSharding(
        mesh, _divisible(PartitionSpec(*spec), arr.shape, mesh, what))


def shard_paged_state(state: Dict, mesh: Mesh) -> Dict:
    """Place the engine's paged state dict (models/generate.py
    init_paged_state): the KV block pool shards on kv-heads, per-slot
    scalars replicate.  Donation-compatible — every program's output
    sharding matches its input's, so the buffers recycle in place."""
    out = {}
    for key, value in state.items():
        if key in ("cache_k", "cache_v"):
            if isinstance(value, QTensor):
                out[key] = QTensor(
                    jax.device_put(value.values, _pool_spec(
                        value.values, mesh, f"{key}.values")),
                    jax.device_put(value.scale, _pool_spec(
                        value.scale, mesh, f"{key}.scale")),
                    value.axes)
            else:
                out[key] = jax.device_put(
                    value, _pool_spec(value, mesh, key))
        else:
            out[key] = jax.device_put(
                value, NamedSharding(mesh, PartitionSpec()))
    return out


def mesh_devices(mesh: Optional[Mesh]) -> int:
    """Device count an engine spans (1 = single-device) — the
    kft_engine_mesh_devices gauge value."""
    return int(mesh.devices.size) if mesh is not None else 1
