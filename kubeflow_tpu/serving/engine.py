"""Continuous-batching LM decode engine: slot-based serving loop.

The static batchers (MicroBatcher / BucketedLMBatcher) dispatch whole
``generate()`` programs: a batch is assembled, padded, and OWNED by one
device program from prefill to the last token.  Two structural costs
follow — a request that arrives mid-generation waits for the entire
program, and every row pays the batch bucket's padded KV span on every
decode step (models/generate.py's docstring measures ~6x wasted decode
compute on wide length distributions).

This engine runs the slot entry points instead (models/generate.py)
over ONE persistent PAGED KV block pool shared by ``slots`` sequences:

  - the unified KV store is a device-side block pool
    ([layers, kv_pool_blocks, kv_block_tokens, hkv, d], fp and int8
    QTensor alike) with host-owned per-slot block tables passed into
    every program call — a slot holds pages for the tokens it has
    actually produced, so serving capacity is bounded by **tokens
    resident** (free blocks), not slots x max_len, and admission
    sheds typed ``Overloaded`` when the pool is exhausted instead of
    deadlocking (each admission reserves its worst-case page count up
    front; see serving/prefix_cache.py BlockManager);
  - a dedicated step loop advances all live slots one token per
    ``decode_step`` call;
  - new requests are admitted into free slots BETWEEN steps, and their
    prompts prefill in **static-width chunks scheduled between decode
    steps** under a per-step token budget (``prefill_chunk_tokens``) —
    a long arriving prompt can never stall in-flight decode for longer
    than one chunk's compute, where a one-shot full-width prefill
    stalls every active slot for the whole prompt;
  - admission first resumes from the **longest cached shared prefix**:
    the block-hashed index finds the longest token-block prefix a
    previous prompt already computed and the new slot's table ALIASES
    those physical blocks (a refcount bump — zero device copies;
    divergence lands in a fresh private block because sharing is
    block-aligned, i.e. copy-on-write whose copy is statically dead),
    and chunked prefill continues after them — TTFT scales with the
    *uncached suffix* length, not the full prompt (the win for fleets
    of chat requests sharing a system prompt);
  - finished rows retire immediately (device-side ``done`` flag),
    their slots are reused and their private pages return to the pool
    (published prefix pages stay resident until LRU eviction) — no
    request ever waits for the batch to drain, and per-request
    ``max_new_tokens`` is data, not a compiled constant;
  - with ``speculative_tokens`` > 0 (greedy exports only), a host-side
    **n-gram drafter** proposes up to k candidate tokens per slot by
    longest-suffix match against the slot's own prompt + generated
    history (no second model), a single ``verify_step`` forward scores
    the k+1 positions at each slot's frontier, the longest exact
    greedy prefix is accepted (+1 free token from the verify logits),
    and rejected columns roll back device-side by NOT advancing the
    slot's ``cache_len`` over them (rejected-tail BLOCKS return to
    the pool) — per-slot adaptive k backs off when acceptance drops,
    and a round in which no slot drafts runs the plain decode
    program, so low-acceptance traffic never pays the verify window;
  - every shape is static, so the engine's whole lifetime compiles at
    most THREE programs (chunked prefill, step, verify — the third
    only when speculation is enabled; prefix reuse needs no copy
    program at all; a decode-tier engine that imports disaggregated
    KV handoffs adds a fourth, ``kv_import``, run once per imported
    request);
  - with ``mesh`` set (serving/sharding.py) the SAME programs compile
    tensor-parallel: params and the block pool are placed with
    NamedShardings at construction (heads / MLP hidden / vocab split,
    the pool on its kv-head dim) and XLA partitions every program
    from the argument shardings — host-owned block tables, admission,
    and the step loop are untouched, and greedy tokens are identical
    to the single-device engine;
  - disaggregated serving rides the same block pool: a prefill-tier
    request (``kv_export``) returns its finished full-block pages as
    a handoff payload, and a decode-tier admission (``kv_handoff``)
    scatters transferred pages into reserved blocks and resumes
    through the ordinary cached-prefix chunked-prefill path.

The host loop reads sampled tokens with a small LAG (``sync_lag``
steps): step N+lag is dispatched before step N's tokens are
materialized, so host bookkeeping overlaps device compute instead of
serializing on it.  Completion is detected deterministically from the
per-request budget (and, when EOS is configured, from the lagged token
stream — the device flag has already frozen the slot by then, so the
lag costs at most ``sync_lag`` idle slot-steps).

Interface-compatible with the batchers (submit/accepts/stats/close), so
ModelServer.enable_batching wires it behind the REST and gRPC surfaces
unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.serving.errors import (
    BatcherClosed,
    DeadlineExceeded,
    Overloaded,
)
from kubeflow_tpu.serving.model_server import (
    EXPIRED_HELP,
    EXPIRED_TOTAL,
    SHED_HELP,
    SHED_TOTAL,
    locked_snapshot,
)
from kubeflow_tpu.serving.adapters import AdapterNotFound
from kubeflow_tpu.serving.prefix_cache import BlockManager
from kubeflow_tpu.testing import faults

class _SpillShed(Exception):
    """Internal: a spill-tier fault struck mid-admission (the
    engine.spill site raised during re-import).  The admission
    dispatcher catches this and sheds the one affected request typed
    429 — never engine death, never a leaked page."""


# Step-duration histogram buckets: decode steps run ~0.1 ms (tiny CPU
# smoke models) to ~100 ms (big models over a slow tunnel).
_STEP_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                 1.0, 2.5)

PREFIX_HITS_TOTAL = "kft_engine_prefix_hits_total"
PREFIX_HITS_HELP = "admissions resumed from a cached prefix, by engine"
PREFIX_MISSES_TOTAL = "kft_engine_prefix_misses_total"
PREFIX_MISSES_HELP = "admissions with no cached prefix, by engine"
PREFIX_EVICTIONS_TOTAL = "kft_engine_prefix_evictions_total"
PREFIX_EVICTIONS_HELP = "cached prefix records evicted (LRU), by engine"
KV_BLOCKS_GAUGE = "kft_engine_kv_blocks"
KV_BLOCKS_HELP = "paged KV pool capacity in blocks, by engine"
KV_BLOCKS_USED_GAUGE = "kft_engine_kv_blocks_used"
KV_BLOCKS_USED_HELP = \
    "paged KV blocks resident (slot- or cache-held), by engine"
KV_EVICTIONS_TOTAL = "kft_engine_kv_block_evictions_total"
KV_EVICTIONS_HELP = \
    "paged KV blocks freed by prefix-cache LRU eviction, by engine"
KV_SHED_TOTAL = "kft_engine_kv_shed_no_blocks_total"
KV_SHED_HELP = \
    "submissions shed because the KV block pool could not cover " \
    "them, by engine"
PREFILL_CHUNKS_TOTAL = "kft_engine_prefill_chunks_total"
PREFILL_CHUNKS_HELP = "prefill chunk program calls, by engine"
SPEC_DRAFTED_TOTAL = "kft_engine_spec_drafted_total"
SPEC_DRAFTED_HELP = "draft tokens proposed to verify_step, by engine"
SPEC_ACCEPTED_TOTAL = "kft_engine_spec_accepted_total"
SPEC_ACCEPTED_HELP = "draft tokens accepted by verify_step, by engine"
MESH_DEVICES_GAUGE = "kft_engine_mesh_devices"
MESH_DEVICES_HELP = \
    "devices the engine's serving mesh spans (1 = single-device), " \
    "by engine"
HANDOFF_PAGES_TOTAL = "kft_engine_handoff_pages_total"
HANDOFF_PAGES_HELP = \
    "paged-KV pages transferred for disaggregated prefill/decode " \
    "handoff, by engine and direction (export/import)"
FUSED_ROUNDS_TOTAL = "kft_engine_fused_rounds_total"
FUSED_ROUNDS_HELP = \
    "fused multi-step decode rounds dispatched (decode_rounds > 1), " \
    "by engine"
FUSED_WASTED_TOTAL = "kft_engine_fused_steps_wasted_total"
FUSED_WASTED_HELP = \
    "fused-round slot-steps dispatched but not delivered (early-exit " \
    "waste past a slot's EOS/budget/deadline), by engine"
KV_SPILLED_GAUGE = "kft_engine_kv_spilled_blocks"
KV_SPILLED_HELP = \
    "paged-KV pages currently resident in the host spill tier, " \
    "by engine"
HOST_TIER_GAUGE = "kft_engine_host_tier_blocks"
HOST_TIER_HELP = \
    "host spill-tier capacity in pages (0 = tier disabled), by engine"
KV_SPILL_TOTAL = "kft_engine_kv_spill_total"
KV_SPILL_HELP = \
    "paged-KV pages crossing the host spill tier, by engine and " \
    "direction (out = device pages evacuated to host, in = host " \
    "pages re-imported at admission)"
ADAPTER_REQUESTS_TOTAL = "kft_engine_adapter_requests_total"
ADAPTER_REQUESTS_HELP = \
    "requests admitted naming an adapter variant, by engine and " \
    "adapter"

# N-gram drafter bounds: suffixes of up to _SPEC_NGRAM_MAX tokens are
# matched against the request's own history, down to _SPEC_NGRAM_MIN.
# The floor is a BIGRAM on purpose: a single repeated token recurs
# constantly in unrepetitive text (birthday-bound in the vocab) and
# measured ~25% acceptance — pure wasted verify windows — while every
# actually-periodic regime (constant runs, alternations, repeated
# phrases) repeats its bigrams too.  After a slot's adaptive draft
# width backs off to zero it re-probes with width 1 once
# _SPEC_COOLDOWN rounds pass, so a tail that TURNS repetitive can
# recover speculation.
_SPEC_NGRAM_MAX = 4
_SPEC_NGRAM_MIN = 2
_SPEC_COOLDOWN = 8
# When every live slot keeps proposing nothing, the drafting scan
# itself is pure per-round overhead — back off to scanning every
# _SPEC_SCAN_STRIDE_MAX rounds (histories grow one token per round,
# so draftability changes slowly); any hit or any new admission
# resets to every round.
_SPEC_SCAN_STRIDE_MAX = 8
# Throughput gate: speculation keeps running only while the verify
# program's MEASURED delivered token rate (EMA) beats the decode
# program's by this factor — the break-even is model/hardware
# dependent (a k+1-wide window costs ~constant extra on a
# bandwidth-bound TPU but ~linear extra on a compute-bound CPU), so
# the engine measures it instead of assuming it.  While gated off, a
# probe verify runs every _SPEC_PROBE_EVERY gated rounds to refresh
# the estimate (traffic that turns repetitive re-enables itself).
_SPEC_RATE_MARGIN = 0.95
_SPEC_PROBE_EVERY = 4
_SPEC_RATE_ALPHA = 0.3

# Fused decode rounds (decode_rounds > 1): shrink the adaptive round
# width when more than this fraction of a round's dispatched slot-steps
# delivered nothing (early-exit waste: slots frozen at EOS/budget while
# co-resident slots keep stepping), or when an admission is queued
# (smaller rounds reach the admission boundary sooner); grow back one
# step per full, waste-free round — the PR 7 adaptive-width discipline
# applied to the round dimension.  The pace EMA smooths the per-token
# step latency used to clamp the width under live deadlines.
_ROUND_WASTE_FRAC = 0.25
_ROUND_PACE_ALPHA = 0.2


_NO_DRAFT = np.empty((0,), np.int32)


def _ngram_propose(history: np.ndarray, k: int,
                   nmax: int = _SPEC_NGRAM_MAX,
                   nmin: int = _SPEC_NGRAM_MIN) -> np.ndarray:
    """Prompt-lookup drafting: find the most recent earlier occurrence
    of the history's longest matchable suffix (n-gram, longest n
    first) and propose the up-to-k tokens that followed it.  Returns
    an empty array when no suffix recurs — the caller then runs the
    plain decode program.  Proposals carry NO correctness weight
    (verify_step accepts only exact greedy matches); they only set the
    acceptance rate, so a wrong guess costs one verify window, never a
    wrong token.

    This runs once per live slot per decode round, so the no-repeat
    common case must be near-free: every matchable suffix ends with
    the history's last token, and one vectorized scan for its earlier
    occurrences prunes unrepetitive text to a single compare."""
    n_hist = int(history.shape[0])
    if n_hist < nmin + 1 or k <= 0:
        return _NO_DRAFT
    # End positions of candidate occurrences: indices e < n_hist - 1
    # holding the last token (a follower at e + 1 always exists, and
    # the trivial self-match at the suffix itself is excluded).
    ends = np.flatnonzero(history[:n_hist - 1] == history[n_hist - 1])
    if ends.size == 0:
        return _NO_DRAFT
    if nmin >= 2:
        # Fold the bigram floor into the precheck: every matchable
        # suffix must end with the last TWO tokens, which prunes the
        # single-repeated-token noise before any n-gram scan runs.
        ends = ends[ends >= 1]
        ends = ends[history[ends - 1] == history[n_hist - 2]]
        if ends.size == 0:
            return _NO_DRAFT
    for n in range(min(nmax, n_hist - 1), nmin - 1, -1):
        cand = ends[ends >= n - 1]
        if cand.size == 0:
            continue
        if n > 1:
            pattern = history[n_hist - n:]
            idx = (cand - (n - 1))[:, None] + np.arange(n)[None, :]
            cand = cand[(history[idx] == pattern[None, :]).all(axis=1)]
            if cand.size == 0:
                continue
        starts = cand + 1  # continuation start per occurrence
        # Most recent occurrence with a FULL k-token continuation,
        # else the most recent at all.  A short continuation (the
        # match sits near the history's end — the steady state of a
        # periodic tail) extends CYCLICALLY: the tokens between the
        # match and the history's end are the period, and proposing
        # them on repeat is exactly the guess that pays off on the
        # repetitive text speculation targets.
        full = starts[starts + k <= n_hist]
        start = int(full[-1] if full.size else starts[-1])
        proposal = history[start:start + k]
        if proposal.size < k:
            proposal = np.resize(history[start:], k)
        return proposal.astype(np.int32)
    return _NO_DRAFT


def _true_token_len(row: np.ndarray) -> int:
    """Real prompt length of a 1-D token row: trailing pad ids (token
    0, the framework-wide pad convention) do not count.  An all-pad row
    keeps its full width — there is no basis to trim it."""
    nz = np.flatnonzero(row)
    return int(nz[-1]) + 1 if nz.size else int(row.shape[0])


class DecodeEngine:
    """Continuous-batching decode over a persistent slot-based KV cache.

    Args:
      cfg/params/decode: the loaded model (loaders.lm_generate exposes
        them as ``predict.engine_spec`` — params already staged to HBM).
      slots: concurrent sequences (the persistent cache's row count).
      prefill_len: static prompt width bound; prompts with more REAL
        tokens (trailing pad ids don't count) fall back to the direct
        generate() path.
      max_len: cache columns per slot (default prefill_len +
        decode.max_new_tokens).
      sync_lag: how many step calls the host may run ahead of token
        materialization (0 = fully synchronous loop).
      steps_per_call: decode steps fused into one step-program call
        (models/generate.py decode_step's static ``steps``): per-call
        dispatch overhead amortizes over k tokens, admission waits at
        most k steps.  One engine uses one value, so the three-program
        guarantee holds either way.
      admit_width: how many admissions may be MID-PREFILL concurrently
        — further queued requests wait even when slots are free, so a
        burst of long prompts cannot hoard every slot in a half-filled
        state.  Chunk scheduling among the admitted set is FIFO (the
        oldest admission takes the whole budget until it finishes —
        best TTFT for the head of the line).
      prefill_chunk_tokens: per-step prefill token budget AND the
        static chunk program width (clamped to prefill_len): between
        two decode steps the loop spends at most this many prompt
        tokens on chunked prefill, which bounds the inter-token latency
        of in-flight slots regardless of arriving prompt length.
      kv_block_tokens: paged-KV page size in cache positions — also
        the prefix hash/share granularity (prefixes are cached and
        aliased in multiples of this many tokens).
      kv_pool_blocks: device block-pool capacity in pages.  0 (the
        default) sizes it to ``slots x ceil(max_len /
        kv_block_tokens)`` — capacity parity with a slot-reserved
        cache; a smaller pool trades worst-case headroom for more
        co-resident short requests (mixed-length traffic fits far
        more than ``slots`` worth of worst cases), and exhaustion
        sheds typed Overloaded rather than deadlocking: every
        admission reserves its worst-case page count or stays queued.
      prefix_caching: publish/reuse shared prefixes as refcounted
        block aliases (zero-copy; False disables lookup and
        publication, chunked prefill still applies).
      max_queue_depth: bounded admission — a submit arriving with this
        many requests already waiting for slots fails fast with
        Overloaded (HTTP 429 / gRPC RESOURCE_EXHAUSTED) instead of
        queueing unboundedly; 0 = unbounded.  The in-flight cap is
        ``slots`` by construction, so total accepted work is bounded
        by slots + max_queue_depth.
      overload_retry_after_s: the Retry-After hint a shed submission
        carries back to the client.
      speculative_tokens: self-speculative (prompt-lookup / n-gram)
        decoding — the static draft width k of the fourth AOT program
        (``verify_step``): up to k host-drafted candidate tokens per
        slot verify in ONE forward pass, token-identical to greedy
        decode (0 disables).  Requires a greedy export (temperature
        0) — sampling exports silently fall back to plain decode,
        because drafting would perturb the per-request sample
        streams.  Speculation forces a synchronous host loop
        (sync_lag 0): the drafter reads each slot's materialized
        history, and the k-token verify window amortizes dispatch
        the way the read lag otherwise would.
      mesh: a ``jax.sharding.Mesh`` (serving/sharding.py build_mesh)
        to run tensor-parallel over: params and the paged KV block
        pool are placed with NamedShardings at construction (heads /
        MLP hidden / vocab split under ``partition_rules``; the pool
        shards its kv-head dim) and the SAME three AOT programs
        compile SPMD from the argument shardings — the host-owned
        block tables, the step loop, and every admission path are
        untouched.  None (the default) is the single-device engine,
        bit-for-bit the pre-mesh behavior.
      partition_rules: regex partition rules over the param tree
        (default serving/sharding.py LM_PARTITION_RULES); only
        consulted when ``mesh`` is set.
      adapters: a serving/adapters.py ``AdapterRegistry`` to serve
        per-tenant LoRA-style variants from (§5.11).  The stacked
        delta arrays ride INSIDE ``params["adapters"]`` and the
        per-slot row index inside ``state["adapter_ids"]``, so the
        SAME AOT programs serve every variant — mixed-adapter traffic
        co-batches in one continuous batch, ``compiled_programs()``
        never grows a per-adapter entry, and under a mesh the stacked
        axis shards along the ``adapters/...`` partition rules.
        Admission resolves ``inputs["adapter"]`` to a row index (or
        sheds typed 404/429), pins it until release, and seeds the
        request's prefix-digest chain with the adapter's content
        digest so variants never alias each other's KV pages.  None
        (the default) serves the base model exactly as before.
    """

    def __init__(
        self,
        cfg,
        params,
        decode,
        *,
        slots: int = 8,
        prefill_len: int = 256,
        max_len: Optional[int] = None,
        sync_lag: int = 2,
        steps_per_call: int = 1,
        decode_rounds: int = 1,
        admit_width: int = 4,
        prefill_chunk_tokens: int = 64,
        kv_block_tokens: int = 16,
        kv_pool_blocks: int = 0,
        prefix_caching: bool = True,
        host_spill_blocks: int = 0,
        max_queue_depth: int = 0,
        overload_retry_after_s: float = 1.0,
        speculative_tokens: int = 0,
        mesh=None,
        partition_rules=None,
        adapters=None,
        name: str = "engine",
    ):
        from kubeflow_tpu.models.generate import init_paged_state
        from kubeflow_tpu.runtime.prom import REGISTRY

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.cfg = cfg
        self.mesh = mesh
        self._registry = adapters
        self._adapter_version = None
        if adapters is not None:
            # Adapter-array serving (§5.11): the stacked per-tenant
            # delta arrays ride INSIDE the param tree, so every AOT
            # program takes them as ordinary operands (no program-count
            # change) and shard_params below places the stacked axis
            # under the adapters/... partition rules.
            stack, self._adapter_version = adapters.stack_snapshot()
            params = dict(params)
            params["adapters"] = stack
        if mesh is not None:
            # Tensor-parallel placement (serving/sharding.py): a
            # one-time device_put of params + pool; the AOT programs
            # below compile SPMD from these shardings alone.
            from kubeflow_tpu.serving import sharding

            params = sharding.shard_params(
                params, mesh,
                partition_rules or sharding.LM_PARTITION_RULES)
        self.params = params
        self.decode = decode
        self.slots = slots
        self.prefill_len = int(prefill_len)
        if self.prefill_len < 1:
            # A non-positive width silently rejects EVERY prompt via
            # accepts() — all traffic would fall back to the direct
            # path while the engine holds a cache and a thread.  Can
            # arise from the serving entrypoint's derived default when
            # an export config has max_new_tokens >= max_seq_len.
            raise ValueError(
                f"prefill_len must be >= 1, got {self.prefill_len}")
        self.max_len = int(max_len or prefill_len + decode.max_new_tokens)
        if self.max_len <= self.prefill_len:
            raise ValueError(
                f"max_len {self.max_len} leaves no decode room beyond "
                f"prefill_len {self.prefill_len}")
        if getattr(cfg, "max_seq_len", self.max_len) < self.max_len:
            raise ValueError(
                f"max_len {self.max_len} exceeds model max_seq_len "
                f"{cfg.max_seq_len}")
        self.sync_lag = max(0, int(sync_lag))
        self.steps_per_call = max(1, int(steps_per_call))
        # Fused multi-step decode (docs §5.2e): > 1 replaces the
        # per-step dispatch loop with ONE decode_rounds program call
        # advancing every slot up to decode_rounds steps, draining
        # synchronously at each round boundary (sync_lag applies only
        # to the k=1 path — the round's overlap window supersedes the
        # lagged read).  1 keeps the classic loop bit-for-bit and
        # compiles no new program.
        self.decode_rounds = max(1, int(decode_rounds))
        self.admit_width = max(1, min(int(admit_width), slots))
        self.prefill_chunk_tokens = max(1, int(prefill_chunk_tokens))
        self.chunk_w = min(self.prefill_chunk_tokens, self.prefill_len)
        self.kv_block_tokens = max(1, int(kv_block_tokens))
        # Per-slot block-table span: enough logical pages to cover
        # max_len positions (a static program shape).
        self._table_blocks = -(-self.max_len // self.kv_block_tokens)
        self.kv_pool_blocks = int(kv_pool_blocks) \
            or slots * self._table_blocks
        if self.kv_pool_blocks < 1:
            raise ValueError(
                f"kv_pool_blocks must be >= 1, got {self.kv_pool_blocks}")
        self.prefix_caching = bool(prefix_caching)
        # Host-RAM spill tier capacity in pages (§5.10): 0 disables.
        # The tier rides the prefix index (spilled records are looked
        # up by the same chained digests), so it requires caching.
        self.host_spill_blocks = max(0, int(host_spill_blocks)) \
            if self.prefix_caching else 0
        self.max_queue_depth = max(0, int(max_queue_depth))
        self.overload_retry_after_s = overload_retry_after_s
        self._eos = decode.eos_token >= 0
        # Speculative draft width: greedy exports only (verify accepts
        # exact argmax matches; under sampling, drafting would have to
        # perturb the per-request sample streams), capped so a draft
        # can never exceed the largest completion minus its free
        # verify token.
        spec = max(0, int(speculative_tokens))
        spec = min(spec, max(0, int(decode.max_new_tokens) - 1))
        if spec and decode.temperature > 0:
            import logging

            logging.warning(
                "engine %r: speculative_tokens=%d ignored — the export "
                "samples at temperature %g and speculation is greedy-"
                "only", name, spec, decode.temperature)
            spec = 0
        self.speculative_tokens = spec
        if spec:
            # The drafter proposes from each slot's materialized
            # history, so the loop must drain emissions every round;
            # the k-token verify window is what amortizes dispatch
            # instead of the read lag.
            self.sync_lag = 0
        self._state = init_paged_state(cfg, slots, self.kv_pool_blocks,
                                       self.kv_block_tokens,
                                       decode.kv_cache_dtype)
        if mesh is not None:
            from kubeflow_tpu.serving import sharding

            self._state = sharding.shard_paged_state(self._state, mesh)
        # Host-owned per-slot block tables, passed into every program
        # call; the sentinel value (== pool size) parks writes and
        # reads of unallocated logical pages.  Loop-thread-owned.
        self._tables = np.full(
            (slots, self._table_blocks), self.kv_pool_blocks, np.int32)
        # Paged-KV bookkeeping: physical refcounts, admission
        # reservations, and the block-hashed prefix index.  Mutated by
        # the loop thread ONLY, always under self._lock (submit reads
        # available() for shed attribution).
        self._mgr = BlockManager(self.kv_pool_blocks,
                                 self.kv_block_tokens,
                                 caching=self.prefix_caching,
                                 host_blocks=self.host_spill_blocks)
        self._evict_rec_seen = 0
        self._evict_blk_seen = 0
        # AOT executables, built lazily by the loop thread: the step
        # loop calls its programs thousands of times per second, and
        # the jitted wrapper re-hashes the whole params pytree
        # signature per call (~0.4 ms on the smoke config — comparable
        # to the step itself).  lower().compile() once, then call the
        # executable.  This is also the three-program guarantee made
        # literal: these three fields ARE the engine's compiled
        # programs.
        self._chunk_exec = None
        self._step_exec = None
        self._verify_exec = None
        # Disaggregated-serving KV import program (kv_import): built
        # the first time a handoff payload arrives; runs once per
        # imported request, never in the step loop.
        self._import_exec = None
        # Fused decode rounds (decode_rounds > 1): the while_loop
        # executable, the double-buffered device-side block-table
        # snapshot (re-uploaded in the overlap window; any host-table
        # mutation marks it dirty), the table sharding the SPMD
        # executable expects (None = pass the host array per dispatch),
        # the adaptive round width, the realized steps-per-round
        # reservoir, and the per-token pace EMA the deadline clamp
        # reads.  All loop-thread-owned.
        self._rounds_exec = None
        self._tables_dev = None
        self._tables_dirty = True
        self._tables_sharding = None
        self._round_k = self.decode_rounds
        self._round_steps: List[int] = []
        self._step_pace_ema: Optional[float] = None
        # Drafting-scan backoff (loop-thread-owned): consecutive empty
        # scans stretch the scan period toward _SPEC_SCAN_STRIDE_MAX.
        self._spec_stride = 1
        self._spec_tick = 0
        # Measured delivered-rate EMAs of the two step programs (the
        # throughput gate's inputs) and the gated-round probe counter.
        self._rate_step_ema = None
        self._rate_verify_ema = None
        self._spec_probe = 0
        # Per-tenant fair admission (§5.11): last-admitted sequence
        # per adapter key ("" = base traffic).  Mutated only under
        # self._lock by the admission pop.
        self._fair_last: Dict[str, int] = {}
        self._fair_seq = 0

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        # Streaming delivery signal: submit_stream() readers wait here
        # and _drain_one notifies after each materialized emission, so
        # a streamed token reaches its client one drain after the
        # device produced it (no polling the hot loop).
        self._emit = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._stopped = False
        self._drain_deadline: Optional[float] = None
        # Host-side slot table: None = free, else the live request entry.
        self._slot_req: List[Optional[dict]] = [None] * slots
        # Admitted entries whose prompts are still chunk-prefilling
        # (FIFO — the oldest admission finishes first, best TTFT).
        # Loop-thread-owned; the admission pop reads only its length.
        self._prefilling: List[dict] = []
        # (tokens_array, [(slot, entry), ...]) emissions not yet read.
        self._pending: List[tuple] = []
        # Counters (mutated by the loop thread, snapshotted under the
        # lock — the same locked-snapshot discipline MicroBatcher uses).
        self._counters = {
            "requests": 0, "tokens": 0, "steps": 0, "prefills": 0,
            "occupancy_sum": 0, "busy_s": 0.0, "in_flight": 0,
            "shed": 0, "expired": 0,
            "prefix_hits": 0, "prefix_misses": 0, "prefix_evictions": 0,
            "prefill_chunks": 0, "cached_tokens": 0, "prompt_tokens": 0,
            "spec_drafted": 0, "spec_accepted": 0, "spec_steps": 0,
            "kv_evictions": 0, "kv_shed_no_blocks": 0,
            "handoff_pages_out": 0, "handoff_pages_in": 0,
            "fused_rounds": 0, "fused_steps_wasted": 0,
            "spill_pages_out": 0, "spill_pages_in": 0,
            "parked_sessions": 0, "fetches": 0,
        }
        self._step_times: List[float] = []   # bounded reservoirs
        self._chunk_times: List[float] = []
        self._gap_times: List[float] = []
        self._ttft_times: List[float] = []
        self._last_step_end: Optional[float] = None
        self._metric_name = name
        self._occ_gauge = REGISTRY.gauge(
            "kft_engine_active_slots",
            "decode engine live slots, by engine")
        self._queue_gauge = REGISTRY.gauge(
            "kft_engine_queue_depth",
            "decode engine admission queue depth, by engine")
        self._tok_counter = REGISTRY.counter(
            "kft_engine_tokens_total",
            "tokens emitted by the decode engine, by engine")
        self._step_hist = REGISTRY.histogram(
            "kft_engine_step_seconds",
            "decode engine per-step (= per-token) latency, by engine",
            buckets=_STEP_BUCKETS,
        ).declare(engine=name)
        self._hits_ctr = REGISTRY.counter(
            PREFIX_HITS_TOTAL, PREFIX_HITS_HELP)
        self._misses_ctr = REGISTRY.counter(
            PREFIX_MISSES_TOTAL, PREFIX_MISSES_HELP)
        self._evict_ctr = REGISTRY.counter(
            PREFIX_EVICTIONS_TOTAL, PREFIX_EVICTIONS_HELP)
        self._chunks_ctr = REGISTRY.counter(
            PREFILL_CHUNKS_TOTAL, PREFILL_CHUNKS_HELP)
        self._kv_blocks_gauge = REGISTRY.gauge(
            KV_BLOCKS_GAUGE, KV_BLOCKS_HELP)
        self._kv_used_gauge = REGISTRY.gauge(
            KV_BLOCKS_USED_GAUGE, KV_BLOCKS_USED_HELP)
        self._kv_evict_ctr = REGISTRY.counter(
            KV_EVICTIONS_TOTAL, KV_EVICTIONS_HELP)
        self._kv_shed_ctr = REGISTRY.counter(
            KV_SHED_TOTAL, KV_SHED_HELP)
        self._spec_drafted_ctr = REGISTRY.counter(
            SPEC_DRAFTED_TOTAL, SPEC_DRAFTED_HELP)
        self._spec_accepted_ctr = REGISTRY.counter(
            SPEC_ACCEPTED_TOTAL, SPEC_ACCEPTED_HELP)
        self._mesh_gauge = REGISTRY.gauge(
            MESH_DEVICES_GAUGE, MESH_DEVICES_HELP)
        self._handoff_ctr = REGISTRY.counter(
            HANDOFF_PAGES_TOTAL, HANDOFF_PAGES_HELP)
        self._fused_rounds_ctr = REGISTRY.counter(
            FUSED_ROUNDS_TOTAL, FUSED_ROUNDS_HELP)
        self._fused_wasted_ctr = REGISTRY.counter(
            FUSED_WASTED_TOTAL, FUSED_WASTED_HELP)
        self._kv_spilled_gauge = REGISTRY.gauge(
            KV_SPILLED_GAUGE, KV_SPILLED_HELP)
        self._host_tier_gauge = REGISTRY.gauge(
            HOST_TIER_GAUGE, HOST_TIER_HELP)
        self._kv_spill_ctr = REGISTRY.counter(
            KV_SPILL_TOTAL, KV_SPILL_HELP)
        self._adapter_req_ctr = REGISTRY.counter(
            ADAPTER_REQUESTS_TOTAL, ADAPTER_REQUESTS_HELP)
        # Fault-layer series: same names as the static batchers', so
        # shed/expired rates read uniformly across batching planes.
        self._shed_ctr = REGISTRY.counter(SHED_TOTAL, SHED_HELP)
        self._expired_ctr = REGISTRY.counter(EXPIRED_TOTAL, EXPIRED_HELP)
        self._occ_gauge.set(0, engine=name)
        self._queue_gauge.set(0, engine=name)
        self._kv_blocks_gauge.set(self.kv_pool_blocks, engine=name)
        self._kv_used_gauge.set(0, engine=name)
        self._kv_spilled_gauge.set(0, engine=name)
        self._host_tier_gauge.set(self.host_spill_blocks, engine=name)
        from kubeflow_tpu.serving.sharding import mesh_devices

        self._mesh_gauge.set(mesh_devices(mesh), engine=name)
        # Last values pushed to the gauges — the step loop only touches
        # the (locked) registry when a value actually changes.
        self._occ_last = 0
        self._queue_last = 0
        self._kv_used_last = 0
        self._kv_spilled_last = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"decode-engine-{name}")
        self._thread.start()

    # -- client surface ---------------------------------------------------

    def accepts(self, inputs: Dict[str, Any]) -> bool:
        """ModelServer routing hook: prompts whose REAL token count
        (an explicit ``prompt_len``, else the width minus trailing pad
        ids) exceeds the static prefill width fall back to the direct
        generate() path.  A short prompt arriving right-padded — e.g.
        from a client that pads to a fixed wire shape — is admitted at
        its true length, not rejected for its padded width."""
        tokens = np.asarray(inputs.get("tokens", ()))
        if tokens.ndim == 0 or tokens.size == 0:
            return False
        row = tokens.reshape(-1)
        if "prompt_len" in inputs:
            length = int(np.asarray(inputs["prompt_len"]).reshape(()))
            if not 0 < length <= row.shape[0]:
                return False
        else:
            length = _true_token_len(row)
        # A resume's delivered tokens join the context, so they count
        # against the static prefill width too.
        length += int(np.asarray(
            inputs.get("resume_tokens", ())).size)
        return bool(0 < length <= self.prefill_len)

    def submit(self, inputs: Dict[str, Any],
               deadline: Optional[float] = None) -> Dict[str, Any]:
        """One request: tokens [t] or [1, t]; optional per-request
        ``max_new_tokens`` (<= engine headroom), sampling ``seed``, and
        ``prompt_len`` (real token count of a right-padded prompt —
        without it, trailing pad ids (token 0) are trimmed, so a padded
        short prompt is neither rejected nor over-prefilled, and never
        generates with pad tokens in its context).  Blocks until the
        completion is ready; returns {"tokens": [1, true_len + emitted]}.
        With ``return_timing`` truthy the result also carries
        ``ttft_s`` / ``latency_s`` / ``cached_tokens`` (bench surface).

        ``resume_tokens`` (mid-generation failover, the router's
        replay payload): tokens a PRIOR attempt of this request
        already emitted.  They join the prompt as ordinary context —
        the whole resume is one chunked prefill that aliases whatever
        prefix blocks this replica has cached — and the budget shrinks
        by their count, so the engine emits exactly the SUFFIX an
        uninterrupted run would have produced after them (greedy
        decode is prefix-deterministic, which is what makes the
        spliced stream token-identical).  A resume whose tokens
        already exhaust the budget or end at EOS resolves immediately
        as a completed generation.

        ``deadline`` (absolute faults.monotonic() instant) is enforced
        everywhere the request lives: expired-on-arrival raises here,
        an expired queued request is failed before admission, and an
        expired IN-FLIGHT request is retired mid-generation through
        the deterministic-retirement path — its slot frees for the
        next admission while its lagged device emissions are dropped
        on the floor, exactly like a normally-retired slot's."""
        entry = self._admit(inputs, deadline)
        entry["event"].wait()
        if entry["err"] is not None:
            raise entry["err"]
        return entry["out"]

    def prefill_export(self, inputs: Dict[str, Any],
                       deadline: Optional[float] = None
                       ) -> Dict[str, Any]:
        """Disaggregated serving, prefill tier: admit the prompt as an
        ordinary request clamped to ONE generated token (prefill is
        the whole job — the single sampled token proves the pages are
        complete and is recomputed by the decode tier anyway) and
        return the result with its finished full-block pages attached
        under ``kv_handoff`` (see _attach_export).  Prompts too short
        to cover one full page return no payload — the caller falls
        back to the untiered path."""
        fwd = dict(inputs)
        fwd["kv_export"] = True
        fwd["max_new_tokens"] = 1
        return self.submit(fwd, deadline=deadline)

    def submit_stream(self, inputs: Dict[str, Any],
                      deadline: Optional[float] = None):
        """Streaming twin of :meth:`submit`: admits the request (same
        validation, deadlines, resume semantics, typed sheds — all
        raised HERE, before any byte is produced) and returns
        ``(meta, iterator)``.  ``meta`` tells the transport layer what
        failover the request supports — ``resumable`` (greedy export:
        a replay with ``resume_tokens`` is token-identical) and
        ``seeded`` (an explicit sampling seed was recorded: a replay
        FROM SCRATCH reproduces the identical stream, so a proxy can
        skip already-delivered tokens) — plus the admitted context
        width and granted budget.  The iterator yields lists of newly
        emitted token ints as the drain materializes them and raises
        the request's typed error (DeadlineExceeded, BatcherClosed)
        mid-stream if it fails after admission."""
        entry = self._admit(inputs, deadline)
        meta = {
            "resumable": self.decode.temperature <= 0.0,
            "seeded": inputs.get("seed") is not None,
            "prompt_tokens": int(entry["tokens"].shape[1]),
            "max_new_tokens": entry["new"],
        }

        def stream():
            sent = 0
            while True:
                with self._emit:
                    n = len(entry["emitted"])
                    if n <= sent and not entry["event"].is_set():
                        self._emit.wait(timeout=0.02)
                        continue
                if n > sent:
                    chunk = [int(t) for t in entry["emitted"][sent:n]]
                    sent = n
                    yield chunk
                if entry["event"].is_set() \
                        and sent >= len(entry["emitted"]):
                    if entry["err"] is not None:
                        raise entry["err"]
                    return

        return meta, stream()

    def _admit(self, inputs: Dict[str, Any],
               deadline: Optional[float]) -> dict:
        """Validate + enqueue one request (submit/submit_stream share
        this); returns the live entry whose ``event`` resolves it."""
        tokens = np.asarray(inputs["tokens"], np.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        n, width = tokens.shape
        if n != 1:
            raise ValueError(
                f"DecodeEngine.submit takes one prompt per call (got "
                f"batch dim {n}); submit rows separately")
        if "prompt_len" in inputs:
            length = int(np.asarray(inputs["prompt_len"]).reshape(()))
            if not 0 < length <= width:
                raise ValueError(
                    f"prompt_len {length} outside (0, {width}] "
                    f"(the tokens width)")
        else:
            length = _true_token_len(tokens[0])
        if length <= 0:
            raise ValueError(
                f"true prompt length {length} must be positive")
        tokens = np.ascontiguousarray(tokens[:, :length])
        # Mid-generation resume: a prior attempt's delivered tokens
        # join the context (one ordinary chunked prefill — they alias
        # cached prefix blocks where this replica has them) and the
        # budget shrinks by their count, so only the suffix an
        # uninterrupted run would produce is emitted.
        resume = np.asarray(
            inputs.get("resume_tokens", ()), np.int32).reshape(-1)
        resume_len = int(resume.shape[0])
        total_budget = int(np.asarray(inputs.get(
            "max_new_tokens", self.decode.max_new_tokens)).reshape(()))
        if total_budget < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {total_budget}")
        total_budget = min(total_budget, self.decode.max_new_tokens)
        if resume_len:
            # Chaos hook: the resume admission path (sleep = slow
            # failover, raise = resume rejected — the router's replay
            # layer must surface it rather than hang the splice).
            faults.fire("engine.resume")
            if resume_len > total_budget:
                raise ValueError(
                    f"resume_tokens carries {resume_len} tokens but "
                    f"the budget is {total_budget}")
            tokens = np.concatenate([tokens, resume[None]], axis=1)
            length += resume_len
            if resume_len == total_budget or (
                    self._eos
                    and bool(np.any(resume == self.decode.eos_token))):
                # The prior attempt already finished the generation
                # (died between its last token and the done marker):
                # resolve as a completed request, nothing to emit.
                return self._completed_entry(tokens, inputs)
        if not 0 < length <= self.prefill_len:
            raise ValueError(
                f"true context length {length} (prompt + "
                f"{resume_len} resumed) outside "
                f"(0, {self.prefill_len}] (engine prefill width)")
        # Same budget contract as every other serving path: the export
        # config's max_new_tokens is the ceiling (a client cannot buy a
        # bigger completion than the model advertises), and the cache
        # headroom caps it further — both against the TRUE length.
        new = min(total_budget - resume_len, self.max_len - length)
        seed = int(np.asarray(inputs.get("seed", 0)).reshape(()))
        # Disaggregated serving: ``kv_export`` marks a prefill-tier
        # request whose result must carry its finished KV pages
        # (:prefill route); ``kv_handoff`` is the decode-tier import
        # payload those pages arrive as.  Both validated HERE so a
        # malformed payload answers 400 before any device work.
        export = bool(inputs.get("kv_export"))
        handoff = self._parse_handoff(inputs.get("kv_handoff"), length)
        if deadline is not None and faults.monotonic() >= deadline:
            with self._lock:
                self._counters["expired"] += 1
            self._expired_ctr.inc(batcher=self._metric_name)
            raise DeadlineExceeded(
                f"deadline expired before engine "
                f"{self._metric_name!r} admission")
        # Adapter-array resolution (§5.11): name -> stacked row index,
        # PINNED from here to release so LRU eviction can never
        # recycle a row under an in-flight request.  Unknown names
        # shed typed 404, slot exhaustion / an open load breaker 429 —
        # all raised HERE, before any queue state exists.  Every
        # terminal path below must unpin (_unpin_adapter is
        # idempotent), which is what makes "evictable" == "no live
        # request" exact.
        adapter_name = inputs.get("adapter")
        adapter_idx, adapter_salt, adapter_pin = 0, b"", None
        if adapter_name:
            adapter_name = str(adapter_name)
            if self._registry is None:
                raise AdapterNotFound(
                    f"engine {self._metric_name!r} serves no adapters "
                    f"(requested {adapter_name!r})")
            adapter_idx, digest = self._registry.acquire(adapter_name)
            adapter_pin = adapter_idx
            # KV is adapter-SCOPED by the CONTENT digest (stable
            # across replicas, unlike the row index): variants never
            # alias each other's cached pages, while the same adapter
            # on two replicas hashes identically for :fetch_kv.
            adapter_salt = bytes.fromhex(digest)
            self._adapter_req_ctr.inc(
                engine=self._metric_name, adapter=adapter_name)
        else:
            adapter_name = None
        # Trace context captured on the transport thread; the loop
        # thread stamps spans from perf readings at drain time (never
        # per token), so the hot step loop stays untouched and a
        # disabled tracer costs one None check per site.
        # Worst-case paged-KV reservation: every position the request
        # could ever write (prompt + full budget) in whole pages.
        # Reserving it at admission is what makes block exhaustion a
        # typed shed instead of a mid-flight deadlock.
        res_blocks = -(-(length + new) // self.kv_block_tokens)
        trace_ctx = tracing.current_ctx()
        entry = {
            "tokens": tokens, "new": new, "seed": seed,
            "emitted": [], "scheduled": 0, "slot": None,
            "trace": trace_ctx,
            "t_perf": time.perf_counter()
            if trace_ctx is not None else 0.0,
            "t_first_perf": None, "spec_acc": 0,
            "prefilling": False, "pos": 0, "cached": 0,
            "res_blocks": res_blocks, "res_left": 0, "blocks": [],
            "released": False,
            "export": export, "handoff": handoff,
            "park": bool(inputs.get("park_kv")), "spill_in": None,
            "adapter": adapter_idx, "adapter_salt": adapter_salt,
            "adapter_name": adapter_name,
            # Adaptive draft width: grows on full accepts, shrinks on
            # full rejects; 0 = backed off (re-probes after cooldown).
            "spec_k": self.speculative_tokens, "spec_cool": 0,
            # Drafting history (prompt + emitted), maintained
            # incrementally by the drain — rebuilding it per round
            # costs more than the draft search itself at step rates.
            "hist": None, "hist_len": 0,
            "deadline": deadline,
            "want_timing": bool(inputs.get("return_timing")),
            "event": threading.Event(), "out": None, "err": None,
            "t": faults.monotonic(), "t_first": None,
        }
        if adapter_pin is not None:
            entry["adapter_pin"] = adapter_pin
        if self.speculative_tokens:
            hist = np.empty((length + new,), np.int32)
            hist[:length] = tokens[0]
            entry["hist"] = hist
            entry["hist_len"] = length
            # Does the PROMPT alone carry a repeated bigram?  Only
            # then can drafting fire at admission, so only then is an
            # admission worth resetting the scan-stride backoff for.
            if length >= 3:
                pairs = (hist[:length - 1].astype(np.int64) << 32) \
                    | hist[1:length].astype(np.int64)
                entry["spec_seed"] = bool(
                    np.unique(pairs).size < length - 1)
            else:
                entry["spec_seed"] = False
        with self._lock:
            if self._stopped:
                self._unpin_adapter(entry)
                raise BatcherClosed(
                    f"engine {self._metric_name!r} is closed")
            if res_blocks > self.kv_pool_blocks:
                # The request's worst case can NEVER fit this pool —
                # queueing it would wedge the admission head forever,
                # so shed it typed (the client can retry a smaller
                # budget; capacity planning reads the counter).
                self._counters["shed"] += 1
                self._counters["kv_shed_no_blocks"] += 1
                self._shed_ctr.inc(batcher=self._metric_name)
                self._kv_shed_ctr.inc(engine=self._metric_name)
                self._unpin_adapter(entry)
                raise Overloaded(
                    f"request needs {res_blocks} KV blocks but engine "
                    f"{self._metric_name!r}'s pool holds "
                    f"{self.kv_pool_blocks}",
                    retry_after_s=self.overload_retry_after_s)
            if self.max_queue_depth \
                    and len(self._queue) >= self.max_queue_depth:
                # Bounded admission: the wait line is full — fail fast
                # instead of queueing unboundedly (under overload a
                # 429 now beats a 504 later).  Attribute the shed:
                # when the block pool (tokens resident), not the slot
                # count, is what is binding, the kv counter tells the
                # operator to grow --kv_pool_blocks rather than slots.
                self._counters["shed"] += 1
                if self._mgr.available() < res_blocks:
                    self._counters["kv_shed_no_blocks"] += 1
                    self._kv_shed_ctr.inc(engine=self._metric_name)
                self._shed_ctr.inc(batcher=self._metric_name)
                self._unpin_adapter(entry)
                raise Overloaded(
                    f"engine {self._metric_name!r} admission queue "
                    f"full ({len(self._queue)} waiting, "
                    f"{self.slots} slots busy)",
                    retry_after_s=self.overload_retry_after_s)
            self._queue.append(entry)
            self._set_queue_gauge(len(self._queue))
            self._work.notify()
        return entry

    def _completed_entry(self, tokens: np.ndarray,
                         inputs: Dict[str, Any]) -> dict:
        """A resume whose prior attempt already finished (budget spent
        or EOS delivered, only the done marker lost): resolve without
        touching the loop — the full context IS the result."""
        entry = {
            "tokens": tokens, "new": 0, "emitted": [],
            "out": {"tokens": tokens}, "err": None,
            "event": threading.Event(),
        }
        if inputs.get("return_timing"):
            entry["out"]["ttft_s"] = 0.0
            entry["out"]["latency_s"] = 0.0
            entry["out"]["cached_tokens"] = 0
        entry["event"].set()
        return entry

    def compiled_programs(self) -> Dict[str, int]:
        """How many device programs this engine has compiled — by
        construction at most one chunked-prefill, one step, and one
        speculative-verify executable (the build sites are
        None-guarded), so a healthy engine reports at most
        {"chunked_prefill": 1, "step": 1, "verify": 1} for its whole
        lifetime ("verify" stays 0 unless speculation is enabled AND a
        slot actually drafted).  There is no prefix-copy program:
        shared-prefix reuse is host-side block-table aliasing.  A
        decode-tier engine that has imported a disaggregated KV
        handoff additionally reports ``kv_import`` (once compiled) —
        the one-per-request page-scatter program; engines that never
        see a handoff keep the exact three-key shape.  An engine built
        with ``decode_rounds > 1`` reports ``decode_rounds`` once the
        fused while_loop program compiles (ONE executable serves every
        adaptive width — the per-round step cap is a traced operand);
        the k=1 path never compiles it."""
        out = {"chunked_prefill": int(self._chunk_exec is not None),
               "step": int(self._step_exec is not None),
               "verify": int(self._verify_exec is not None)}
        if self._import_exec is not None:
            out["kv_import"] = 1
        if self._rounds_exec is not None:
            out["decode_rounds"] = 1
        return out

    def adapter_info(self) -> List[Dict[str, Any]]:
        """Resident adapters (name/digest/index/pins) for /readyz
        advertisement and the router's digest-affinity pick; empty
        when this engine serves no adapters (§5.11)."""
        return self._registry.loaded() if self._registry is not None \
            else []

    def stats(self) -> Dict[str, Any]:
        """Locked snapshot of the engine counters: occupancy, queue
        depth, throughput, per-token (= per-step) latency, prefix-cache
        effectiveness, and prefill-interference bounds."""
        c, extra = locked_snapshot(
            self._lock, self._counters,
            lambda: {
                "queue_depth": len(self._queue),
                "active_slots": sum(
                    r is not None for r in self._slot_req),
                "kv_used": self._mgr.used_blocks(),
                "host_used": self._mgr.host_used_blocks(),
                "step_times": list(self._step_times),
                "chunk_times": list(self._chunk_times),
                "gap_times": list(self._gap_times),
                "ttft_times": list(self._ttft_times),
                "round_steps": list(self._round_steps),
            })
        steps = c["steps"]

        # Sort each reservoir ONCE, outside the lock: the lock only
        # pays the four list copies, and every percentile below reads
        # the one sorted copy — the old shape re-sorted the same
        # 4096-entry reservoir per pct() call while a hot /stats +
        # /metrics scrape pattern held the decode loop's lock.
        times = sorted(extra["step_times"])
        gaps = sorted(extra["gap_times"])
        chunks = sorted(extra["chunk_times"])
        ttfts = sorted(extra["ttft_times"])
        rounds = sorted(extra["round_steps"])

        def pct(sorted_values, q):
            if not sorted_values:
                return 0.0
            return round(sorted_values[min(len(sorted_values) - 1,
                                           int(len(sorted_values) * q))]
                         * 1e3, 3)

        def pct_raw(sorted_values, q):
            if not sorted_values:
                return 0
            return sorted_values[min(len(sorted_values) - 1,
                                     int(len(sorted_values) * q))]

        prompt_toks = c["prompt_tokens"]
        out = {
            "requests": c["requests"],
            "tokens": c["tokens"],
            "steps": steps,
            "prefills": c["prefills"],
            "slots": self.slots,
            "active_slots": extra["active_slots"],
            "queue_depth": extra["queue_depth"],
            # Admitted but not yet delivered.  THIS is the drain signal:
            # deterministic retirement frees a slot at dispatch (before
            # the lagged emission reaches its client), so active_slots
            # can touch zero while completions are still in flight.
            "in_flight_requests": c["in_flight"],
            # Fault-layer outcomes: admissions refused at the queue cap
            # and requests failed by their deadline (queued or
            # in-flight) — the chaos scenario's primary assertions.
            "shed": c["shed"],
            "deadline_expired": c["expired"],
            # Prefix cache: how much prompt compute block-table
            # aliasing saved.  cached_token_ratio is the one-glance
            # effectiveness number (also exported per-replica to the
            # fleet — see ModelServer.refresh_gauges).
            "prefix_hits": c["prefix_hits"],
            "prefix_misses": c["prefix_misses"],
            "prefix_evictions": c["prefix_evictions"],
            "cached_prompt_tokens": c["cached_tokens"],
            "prompt_tokens": prompt_toks,
            "cached_token_ratio": round(
                c["cached_tokens"] / prompt_toks, 4)
            if prompt_toks else 0.0,
            # Paged KV pool: capacity is tokens RESIDENT, not slots.
            # kv_utilization is the one-glance "how full is this
            # chip's serving memory" number (the fleet CACHE% story
            # extended to capacity); the shed counter attributes
            # overload to the pool rather than the slot count.
            "kv_blocks": self.kv_pool_blocks,
            "kv_blocks_used": extra["kv_used"],
            "kv_block_tokens": self.kv_block_tokens,
            "kv_block_evictions": c["kv_evictions"],
            "kv_shed_no_blocks": c["kv_shed_no_blocks"],
            "tokens_resident": extra["kv_used"] * self.kv_block_tokens,
            "kv_utilization": round(
                extra["kv_used"] / self.kv_pool_blocks, 4)
            if self.kv_pool_blocks else 0.0,
            # Hierarchical KV (§5.10): host spill-tier occupancy and
            # flow.  tokens_addressable is the two-tier capacity story
            # — positions servable without a cold prefill, device pool
            # PLUS host tier; kv_spill_ratio is host-tier occupancy
            # (used / capacity) — the same number the
            # kft_serving_kv_spill_ratio gauge and the fleet-status
            # SPILL% column render.
            "host_spill_blocks": self.host_spill_blocks,
            "host_tier_used": extra["host_used"],
            "kv_spill_pages_out": c["spill_pages_out"],
            "kv_spill_pages_in": c["spill_pages_in"],
            "parked_sessions": c["parked_sessions"],
            "kv_fetches": c["fetches"],
            "tokens_addressable": (self.kv_pool_blocks
                                   + self.host_spill_blocks)
            * self.kv_block_tokens,
            "kv_spill_ratio": round(
                extra["host_used"] / self.host_spill_blocks, 4)
            if self.host_spill_blocks else 0.0,
            # Multi-chip serving: how many devices this engine's mesh
            # spans (1 = single-device) and how many paged-KV pages
            # have crossed the disaggregated prefill/decode boundary
            # in each direction.
            "mesh_devices": self._mesh_devices(),
            "handoff_pages_out": c["handoff_pages_out"],
            "handoff_pages_in": c["handoff_pages_in"],
            # Speculative decoding: drafted vs accepted tokens and the
            # per-verify-call yield.  accepted_per_step is the mean
            # EXTRA tokens a verify call delivered beyond the one a
            # plain decode step would have — the speedup signal to
            # watch (acceptance_rate alone can look high while k is
            # backed off to 1).
            "spec_drafted": c["spec_drafted"],
            "spec_accepted": c["spec_accepted"],
            "spec_steps": c["spec_steps"],
            "spec_acceptance_rate": round(
                c["spec_accepted"] / c["spec_drafted"], 4)
            if c["spec_drafted"] else 0.0,
            "accepted_per_step": round(
                c["spec_accepted"] / c["spec_steps"], 3)
            if c["spec_steps"] else 0.0,
            # Fused decode rounds (docs §5.2e): rounds dispatched,
            # early-exit slot-steps that delivered nothing, and the
            # realized steps-per-round distribution — how much of the
            # configured width the device actually ran before every
            # slot froze.  decode_rounds == 1 is the classic per-step
            # dispatch loop (all three stay at zero).
            "decode_rounds": self.decode_rounds,
            "fused_rounds": c["fused_rounds"],
            "fused_steps_wasted": c["fused_steps_wasted"],
            "steps_per_round_p50": pct_raw(rounds, 0.50),
            "steps_per_round_p99": pct_raw(rounds, 0.99),
            # Which AOT programs exist — the four-program guarantee,
            # observable over the :stats route (the hermetic engine
            # e2e asserts it end to end).
            "compiled_programs": self.compiled_programs(),
            # Chunked prefill: calls made and their latency — one chunk
            # is the most an arriving prompt may stall in-flight decode
            # per scheduling turn.
            "prefill_chunks": c["prefill_chunks"],
            "prefill_chunk_p95_ms": pct(chunks, 0.95),
            "mean_occupancy": round(c["occupancy_sum"] / steps, 2)
            if steps else 0.0,
            "tokens_per_sec": round(c["tokens"] / c["busy_s"], 1)
            if c["busy_s"] else 0.0,
            "token_latency_p50_ms": pct(times, 0.50),
            "token_latency_p95_ms": pct(times, 0.95),
            "token_latency_p99_ms": pct(times, 0.99),
            # Wall time between consecutive step-call completions while
            # slots were live — the client-visible inter-token gap,
            # INCLUDING whatever admission/prefill work ran in between.
            # Bounded by the chunk budget; a full-prefill stall would
            # spike the max.
            "inter_token_gap_p50_ms": pct(gaps, 0.50),
            "inter_token_gap_p99_ms": pct(gaps, 0.99),
            "inter_token_gap_max_ms": round(gaps[-1] * 1e3, 3)
            if gaps else 0.0,
            "ttft_p50_ms": pct(ttfts, 0.50),
            "ttft_p99_ms": pct(ttfts, 0.99),
        }
        if self._registry is not None:
            # Adapter-array serving (§5.11): registry occupancy plus
            # the resident name/digest list the fleet layer advertises.
            out["adapters"] = self._registry.stats()
            out["adapters"]["loaded"] = self._registry.loaded()
        return out

    def close(self, drain_s: float = 10.0) -> None:
        """Deterministic shutdown: refuse new work, give in-flight
        requests ``drain_s`` to finish, fail whatever remains with
        BatcherClosed, and join the loop thread (bounded — mirrors
        ModelServer.stop(); no background-thread leakage across a test
        session)."""
        with self._lock:
            if self._stopped:
                self._work.notify_all()
            else:
                self._stopped = True
                self._drain_deadline = faults.monotonic() \
                    + max(0.0, drain_s)
                self._work.notify_all()
        self._thread.join(timeout=max(5.0, drain_s + 5.0))
        # The prefix index dies with the engine (reload invalidation:
        # the serving layer rebuilds engine + pool per model version);
        # clear it here too so a closed-but-referenced engine can never
        # serve a stale prefix.  After a clean drain every slot has
        # released its pages, so dropping the cached records frees the
        # whole pool.
        with self._lock:
            self._mgr.invalidate()
        # A closed engine exports no live slots, queue, or resident
        # KV: hot-swap retires the metric series at zero instead of
        # freezing a stale occupancy in /metrics forever.
        self._set_occ_gauge(0)
        self._set_queue_gauge(0)
        self._kv_blocks_gauge.set(0, engine=self._metric_name)
        self._set_kv_used_gauge(0)
        self._kv_spilled_gauge.set(0, engine=self._metric_name)
        self._kv_spilled_last = 0
        self._host_tier_gauge.set(0, engine=self._metric_name)
        self._mesh_gauge.set(0, engine=self._metric_name)

    def _mesh_devices(self) -> int:
        from kubeflow_tpu.serving.sharding import mesh_devices

        return mesh_devices(self.mesh)

    # -- step loop --------------------------------------------------------

    def _free_slots_locked(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _fair_pick_locked(self) -> int:
        """Per-tenant fair admission (§5.11): among the queued
        requests, pick the one whose adapter key ("" = base traffic)
        was admitted least recently, oldest-first within a tenant —
        a hot adapter's burst cannot starve co-batched neighbors,
        because every other tenant's queue head outranks the hot
        tenant's next request.  Pure FIFO when nothing queued names an
        adapter, so single-tenant engines keep the exact pre-adapter
        admission order.  The caller still stops on the first
        unplannable pick, which preserves the no-starvation property
        under pool pressure: a waiting request is never jumped
        indefinitely."""
        if self._registry is None or len(self._queue) < 2:
            return 0
        if all(e.get("adapter_name") is None for e in self._queue):
            return 0
        best, best_key = 0, None
        for i, e in enumerate(self._queue):
            key = (self._fair_last.get(
                e.get("adapter_name") or "", -1), i)
            if best_key is None or key < best_key:
                best, best_key = i, key
        return best

    def _apply_adapter_updates(self) -> None:
        """Hot adapter load/evict, device side (loop thread, between
        program calls): swap the registry's current stacked delta
        arrays into the param tree when its version moved.  The stack
        has identical shapes/dtypes on every version (rows mutate,
        geometry never does), so the swap NEVER recompiles a program;
        device_put preserves each leaf's existing placement, so under
        a mesh the stacked axis lands exactly where the compiled SPMD
        programs expect it.  Copy-on-write on the registry side means
        in-flight dispatches keep reading the old leaves — a program
        never observes a torn row."""
        import jax

        stack, version = self._registry.stack_snapshot()
        if version == self._adapter_version:
            return

        def place(new, old):
            sharding = getattr(old, "sharding", None)
            return jax.device_put(np.asarray(new), sharding) \
                if sharding is not None else np.asarray(new)

        params = dict(self.params)
        params["adapters"] = jax.tree_util.tree_map(
            place, stack, dict(self.params["adapters"]))
        self.params = params
        self._adapter_version = version

    def _sweep_expired_locked(self) -> List[dict]:
        """Pull every deadline-expired request out of the queue AND the
        live slot table (caller fails them outside the lock).

        In-flight expiry rides the deterministic-retirement path: the
        slot is freed NOW — the next admission's prefix-copy program
        freezes it on device, which is the device-side abort — and the
        request's lagged emissions still in _pending are dropped by
        _drain_one's event-set check, exactly like a normally-retired
        slot's.  No other slot's state is touched, so co-resident
        generations are unaffected."""
        pnow = faults.monotonic()
        expired: List[dict] = []
        live = []
        for entry in self._queue:
            d = entry["deadline"]
            if d is not None and d <= pnow:
                expired.append(entry)
            else:
                live.append(entry)
        if len(live) != len(self._queue):
            self._queue[:] = live
            self._set_queue_gauge(len(self._queue))
        for i, entry in enumerate(self._slot_req):
            if entry is None:
                continue
            d = entry["deadline"]
            if d is not None and d <= pnow:
                self._slot_req[i] = None
                # Park the dead occupant's table row: its in-flight
                # device state (done may still be False) keeps
                # advancing harmlessly, but every write now drops —
                # its freed pages can be reallocated immediately.
                self._tables[i][:] = self.kv_pool_blocks
                self._tables_dirty = True
                self._release_entry_locked(entry)
                self._counters["in_flight"] -= 1
                expired.append(entry)
        # Deterministically-retired requests live in NEITHER the queue
        # nor the slot table while their lagged emissions sit in
        # _pending — a request is in_flight until delivery, so its
        # deadline is enforced on this tail too (under wedged steps the
        # lag is unbounded; the client must get its 504, not a late
        # 200).  A snapshot entry still slot-resident cannot reach the
        # append: the slot scan above already moved every expired slot
        # entry into `expired`, and the identity dedup skips those (and
        # entries recurring across snapshots).
        for _, snapshot, _ in self._pending:
            for _, entry in snapshot:
                if entry["event"].is_set():
                    continue
                d = entry["deadline"]
                if d is None or d > pnow:
                    continue
                if any(entry is e for e in expired):
                    continue
                # Deterministically retired: the slot (and possibly
                # its table row) already belongs to a successor, but
                # the entry still owns its physical pages until
                # delivery — release them now with the failure.
                self._release_entry_locked(entry)
                self._counters["in_flight"] -= 1
                expired.append(entry)
        if expired:
            self._counters["expired"] += len(expired)
        return expired

    def _fail_expired(self, expired: List[dict]) -> None:
        if not expired:
            return
        self._expired_ctr.inc(len(expired), batcher=self._metric_name)
        for entry in expired:
            # Queue-expired entries never reach _release_entry_locked
            # (they hold no pages) — unpin their adapters here.
            self._unpin_adapter(entry)
        for entry in expired:
            if not entry["event"].is_set():
                if entry["trace"] is not None:
                    tracing.record_span(
                        "engine.request", entry["trace"],
                        entry["t_perf"], time.perf_counter(),
                        status="deadline_expired",
                        attrs={"engine": self._metric_name,
                               "emitted": len(entry["emitted"]),
                               "budget": entry["new"]})
                entry["err"] = DeadlineExceeded(
                    f"deadline expired after {len(entry['emitted'])} "
                    f"of {entry['new']} tokens "
                    f"(engine {self._metric_name!r})")
                entry["event"].set()

    def _unpin_adapter(self, entry: dict) -> None:
        """Drop an entry's adapter pin (idempotent — the pin travels
        as a pop-once key).  Every terminal path calls this: release,
        expiry, queue failure, abort, and the typed admission sheds —
        so an adapter row is LRU-evictable exactly when no live
        request references it."""
        pin = entry.pop("adapter_pin", None)
        if pin is not None and self._registry is not None:
            self._registry.release(pin)

    def _release_entry_locked(self, entry: dict) -> None:
        """Return an entry's physical pages (slot refs) and never-taken
        reservation to the pool.  Pages a published prefix record
        advertises stay resident as evictable cache.  Idempotent —
        retirement, expiry, and drain can each reach a request once.
        Never touches the slot's table row: by release time the row
        may already belong to a successor request."""
        self._unpin_adapter(entry)
        if entry["released"]:
            return
        entry["released"] = True
        self._mgr.release(entry["blocks"], unreserve=entry["res_left"])
        entry["blocks"] = []
        entry["res_left"] = 0

    def _plan_blocks_locked(self, entry: dict):
        """Reserve the entry's worst-case page count (aliasing the
        longest cached prefix for free); None = the pool cannot cover
        it yet, leave the request at the queue head — retirements free
        pages, and FIFO order means a starving big request is never
        jumped into starvation.  A request carrying a KV-handoff
        payload skips the local prefix lookup (limit 0): its pages
        arrive from the prefill tier and land in PRIVATE blocks, so
        the whole worst case reserves.

        Hierarchical KV (§5.10): when the HOST tier covers more of the
        prompt than the device index, the admission plans like a
        handoff import instead — full private reservation, spilled
        pages re-imported through the same ``kv_import`` program — so
        a spilled session resumes without re-prefilling what the tier
        preserved."""
        prompt = entry["tokens"][0]
        salt = entry.get("adapter_salt", b"")
        limit = 0 if entry.get("handoff") else int(prompt.shape[0]) - 1
        spill_in = None
        if limit > 0 and self.host_spill_blocks:
            payload, depth = self._mgr.lookup_spilled(
                prompt, limit, salt=salt)
            if payload is not None and depth * self.kv_block_tokens \
                    > self._mgr.peek(prompt, limit, salt=salt):
                spill_in = (payload, depth)
                limit = 0
        plan = self._mgr.admit(prompt, limit, entry["res_blocks"],
                               salt=salt)
        if plan is not None:
            entry["spill_in"] = spill_in
        return plan

    # -- disaggregated prefill/decode handoff -----------------------------

    def _parse_handoff(self, payload, length: int):
        """Validate + normalize a KV-handoff payload against THIS
        engine's pool geometry; returns {"covered", "k", "v"} (pages
        trimmed to the full blocks covering at most ``length - 1``
        positions — at least one prompt token always recomputes
        locally, which is what arms the slot's scalars through the
        ordinary final prefill chunk), or None when there is nothing
        importable.  Raises ValueError on a geometry/dtype mismatch —
        a payload from a differently-configured prefill replica must
        answer 400, not corrupt the pool."""
        if payload is None:
            return None
        if not isinstance(payload, dict):
            raise ValueError("kv_handoff must be an object")
        bt = int(payload.get("block_tokens", 0))
        if bt != self.kv_block_tokens:
            raise ValueError(
                f"kv_handoff block_tokens {bt} != engine page size "
                f"{self.kv_block_tokens}")
        int8 = self.decode.kv_cache_dtype == "int8"
        page_shape = (self.cfg.n_layers, self.kv_block_tokens,
                      self.cfg.n_kv_heads, self.cfg.head_dim)

        def norm(side, raw):
            if int8:
                if not isinstance(raw, dict) or "values" not in raw \
                        or "scale" not in raw:
                    raise ValueError(
                        f"kv_handoff {side}: engine pool is int8 — "
                        f"payload needs values + scale")
                vals = np.asarray(raw["values"], np.int8)
                scale = np.asarray(raw["scale"], np.float32)
                if scale.shape != vals.shape[:-1]:
                    raise ValueError(
                        f"kv_handoff {side}: scale {scale.shape} "
                        f"must match values {vals.shape} minus the "
                        f"trailing dim")
                return vals, scale
            if isinstance(raw, dict):
                raise ValueError(
                    f"kv_handoff {side}: engine pool is "
                    f"{self.cfg.dtype} — got a quantized payload")
            return np.asarray(raw), None

        k_vals, k_scale = norm("k", payload.get("k"))
        v_vals, v_scale = norm("v", payload.get("v"))
        for side, vals in (("k", k_vals), ("v", v_vals)):
            if vals.ndim != 5 or (vals.shape[0],) + vals.shape[2:] \
                    != page_shape:
                raise ValueError(
                    f"kv_handoff {side} pages {vals.shape} do not "
                    f"match pool pages [layers={page_shape[0]}, n, "
                    f"block_tokens={page_shape[1]}, "
                    f"hkv={page_shape[2]}, d={page_shape[3]}]")
        if k_vals.shape[1] != v_vals.shape[1]:
            raise ValueError("kv_handoff k/v page counts differ")
        n = min(int(k_vals.shape[1]),
                (int(length) - 1) // self.kv_block_tokens)
        if n <= 0:
            return None
        return {
            "covered": n * self.kv_block_tokens,
            "k": (k_vals[:, :n], None if k_scale is None
                  else k_scale[:, :n]),
            "v": (v_vals[:, :n], None if v_scale is None
                  else v_scale[:, :n]),
        }

    def _pad_pages(self, pages, span: int):
        """Page stack [L, n, bt, hkv(, d)] -> the import program's
        static [L, span, ...] shape (zero padding rides sentinel ids
        and drops on device)."""
        from kubeflow_tpu.ops.quantize import QTensor

        vals, scale = pages
        n = vals.shape[1]
        dtype = (self.cfg.dtype if scale is None else np.int8)
        pad = np.zeros(
            (vals.shape[0], span) + vals.shape[2:], dtype)
        pad[:, :n] = vals
        if scale is None:
            return pad
        pad_s = np.zeros(
            (scale.shape[0], span) + scale.shape[2:], np.float32)
        pad_s[:, :n] = scale
        return QTensor(pad, pad_s, (-1,))

    def _import_pages(self, entry: dict, pages: dict) -> int:
        """Shared page-import tail (loop thread, slot claimed): take
        the covered pages from the entry's reservation, scatter the
        page data into them (ONE kv_import program call — the
        transfer unit is a block-page list, never a contiguous slot
        region), and set the chunk-prefill offset past them — from
        there the request is indistinguishable from a local
        prefix-cache resume, which is what makes both handoff import
        (§5.9) and host-tier re-import (§5.10) token-identical to
        local prefill at every chunk boundary.  ``pages`` is the
        normalized {"covered", "k", "v"} form."""
        from kubeflow_tpu.models.generate import import_kv_pages

        self._ensure_cover(entry, pages["covered"] - 1)
        n = pages["covered"] // self.kv_block_tokens
        span = self._table_blocks
        ids = np.full((span,), self.kv_pool_blocks, np.int32)
        ids[:n] = entry["blocks"][:n]
        pages_k = self._pad_pages(pages["k"], span)
        pages_v = self._pad_pages(pages["v"], span)
        if self._import_exec is None:
            self._import_exec = import_kv_pages.lower(
                self._state, pages_k, pages_v, ids).compile()
        self._state = self._import_exec(
            self._state, pages_k, pages_v, ids)
        entry["pos"] = pages["covered"]
        return n

    def _import_handoff(self, entry: dict) -> None:
        """Admission, handoff side: scatter the prefill tier's
        transferred pages into the reserved blocks and start chunked
        prefill at the covered offset."""
        # Chaos hook: the decode-tier import path (sleep = slow
        # cross-replica transfer, raise = import failure — the router
        # surfaces it rather than hanging the tiered dispatch).
        faults.fire("engine.kv_handoff")
        n = self._import_pages(entry, entry["handoff"])
        with self._lock:
            self._counters["handoff_pages_in"] += n
        self._handoff_ctr.inc(n, engine=self._metric_name,
                              direction="import")

    def _import_spill(self, entry: dict) -> None:
        """Admission, host-tier side (§5.10): re-import the spilled
        pages the plan matched, through the same kv_import program a
        disaggregated handoff uses — re-admitting a spilled session
        costs one page scatter plus the uncovered suffix's chunks,
        never a full re-prefill.  A fault here sheds THIS admission
        typed 429 (the caller releases its pages; the host record is
        untouched, so no page leaks in either tier) instead of killing
        the engine: losing one admission to a sick spill tier is
        degradation, not death."""
        payload, depth = entry.pop("spill_in")
        try:
            # Chaos hook: the spill-in import path (raise = spill-tier
            # failure mid-admission -> typed 429; sleep = slow host
            # copy).
            faults.fire("engine.spill")
        except Exception as exc:
            raise _SpillShed(str(exc)) from exc
        (k_vals, k_scale) = payload["k"]
        (v_vals, v_scale) = payload["v"]
        pages = {
            "covered": depth * self.kv_block_tokens,
            "k": (k_vals[:, :depth], None if k_scale is None
                  else k_scale[:, :depth]),
            "v": (v_vals[:, :depth], None if v_scale is None
                  else v_scale[:, :depth]),
        }
        n = self._import_pages(entry, pages)
        with self._lock:
            self._counters["spill_pages_in"] += n
            self._mgr.spills_in += n
        self._kv_spill_ctr.inc(n, engine=self._metric_name,
                               direction="in")

    def _attach_export(self, entry: dict) -> None:
        """Delivery, prefill side (loop thread, pages still held):
        gather the finished full-block prompt pages off the pool into
        the response payload — the same normalized form
        ``kv_handoff`` imports, so prefill and decode tiers stay
        wire-symmetric.  Runs before release: the pages are still
        slot-referenced, so nothing can overwrite them mid-gather."""
        from kubeflow_tpu.models.generate import gather_kv_pages

        true_len = int(entry["tokens"].shape[1])
        n = min((true_len - 1) // self.kv_block_tokens,
                len(entry["blocks"]))
        if n <= 0:
            return
        # Chaos hook: the prefill-tier export path (raise = export
        # failure at delivery; the router's tiered dispatch falls back
        # to the untiered path).
        faults.fire("engine.kv_handoff")
        pages_k, pages_v = gather_kv_pages(
            self._state, entry["blocks"][:n])

        def wire(pages):
            vals, scale = pages
            return vals if scale is None \
                else {"values": vals, "scale": scale}

        entry["out"]["kv_handoff"] = {
            "block_tokens": self.kv_block_tokens,
            "tokens_covered": n * self.kv_block_tokens,
            "k": wire(pages_k),
            "v": wire(pages_v),
        }
        with self._lock:
            self._counters["handoff_pages_out"] += n
        self._handoff_ctr.inc(n, engine=self._metric_name,
                              direction="export")

    def _ensure_cover(self, entry: dict, upto_pos: int) -> None:
        """Grow the slot's block table to cover position ``upto_pos``,
        taking physical pages from the entry's admission reservation
        (capped there — positions past the reservation park on the
        table sentinel and their writes drop; only positions the
        frontier can never reach land there)."""
        target = min(upto_pos // self.kv_block_tokens + 1,
                     entry["res_blocks"])
        if target <= len(entry["blocks"]):
            return
        # Chaos hook: raise = allocation failure (engine death at the
        # growth site — _abort resolves every waiter), sleep = slow
        # allocator under pool pressure.
        faults.fire("engine.alloc_block")
        row = self._tables[entry["slot"]]
        with self._lock:
            while len(entry["blocks"]) < target:
                blk = self._mgr.take()
                row[len(entry["blocks"])] = blk
                entry["blocks"].append(blk)
                entry["res_left"] -= 1
            rec_d, blk_d = self._flush_evictions_locked()
            self._tables_dirty = True
        if rec_d:
            self._evict_ctr.inc(rec_d, engine=self._metric_name)
        if blk_d:
            self._kv_evict_ctr.inc(blk_d, engine=self._metric_name)

    def _trim_cover(self, entry: dict, next_write_pos: int) -> None:
        """Speculative rollback, pool side: pages past the one covering
        ``next_write_pos`` hold only rejected-draft k/v (already behind
        the attention mask) — return them to the pool and restore the
        entry's reservation, so a burst of rejected windows never
        inflates tokens resident."""
        target = max(1, next_write_pos // self.kv_block_tokens + 1)
        n = len(entry["blocks"])
        if n <= target:
            return
        row = self._tables[entry["slot"]]
        row[target:n] = self.kv_pool_blocks
        with self._lock:
            self._tables_dirty = True
            tail = entry["blocks"][target:]
            del entry["blocks"][target:]
            entry["res_left"] += len(tail)
            self._mgr.rollback(tail)

    def _flush_evictions_locked(self):
        """Fold the manager's eviction totals into the engine counters;
        returns the (records, blocks) deltas for the prom counters."""
        rec_d = self._mgr.evictions - self._evict_rec_seen
        blk_d = self._mgr.block_evictions - self._evict_blk_seen
        if rec_d:
            self._evict_rec_seen = self._mgr.evictions
            self._counters["prefix_evictions"] += rec_d
        if blk_d:
            self._evict_blk_seen = self._mgr.block_evictions
            self._counters["kv_evictions"] += blk_d
        return rec_d, blk_d

    def _set_queue_gauge(self, depth: int) -> None:
        if depth != self._queue_last:
            self._queue_last = depth
            self._queue_gauge.set(depth, engine=self._metric_name)

    def _set_occ_gauge(self, active: int) -> None:
        if active != self._occ_last:
            self._occ_last = active
            self._occ_gauge.set(active, engine=self._metric_name)

    def _set_kv_used_gauge(self, used: int) -> None:
        if used != self._kv_used_last:
            self._kv_used_last = used
            self._kv_used_gauge.set(used, engine=self._metric_name)

    def _set_kv_spilled_gauge(self, spilled: int) -> None:
        if spilled != self._kv_spilled_last:
            self._kv_spilled_last = spilled
            self._kv_spilled_gauge.set(spilled, engine=self._metric_name)

    # -- host spill tier (§5.10) ------------------------------------------

    def _spill_tick(self, max_records: int = 4) -> int:
        """Evacuate LRU-cold idle records to the host tier while
        take() pressure would otherwise destroy-evict them (loop
        thread, between program calls — the pool buffers are donated
        to the step programs, so nobody else may gather them).  Each
        spill is select-under-lock, gather-OUTSIDE-the-lock (a device
        read must never run under the engine lock), complete-under-
        lock; spill() revalidates the candidate, so the off-lock
        window is race-free.  A gather fault leaves the record
        resident — destructive LRU eviction remains the fallback and
        correctness is unharmed.  Returns records spilled."""
        from kubeflow_tpu.models.generate import gather_kv_pages

        spilled = 0
        while spilled < max_records and self._mgr.spill_pressure() > 0:
            with self._lock:
                cands = self._mgr.spill_candidates(1)
            if not cands:
                break
            rec = cands[0]
            n = len(rec.blocks)
            with self._lock:
                # Gather-free fast path: a parked session's chain is
                # already host-resident (host_put at delivery), so its
                # device pages can drop without re-copying them.
                freed = self._mgr.spill(rec, None)
                if freed is not None:
                    self._counters["spill_pages_out"] += n
            if freed is not None:
                self._kv_spill_ctr.inc(n, engine=self._metric_name,
                                       direction="out")
                spilled += 1
                continue
            try:
                # Chaos hook: the spill-out gather (raise = gather
                # failure — the record stays resident and eviction
                # falls back to destroying it; sleep = slow host copy).
                faults.fire("engine.spill")
                pages_k, pages_v = gather_kv_pages(
                    self._state, rec.blocks)
            except Exception:
                break
            with self._lock:
                freed = self._mgr.spill(
                    rec, {"k": pages_k, "v": pages_v})
                if freed is None:
                    continue  # went stale off-lock; reselect
                self._counters["spill_pages_out"] += n
            self._kv_spill_ctr.inc(n, engine=self._metric_name,
                                   direction="out")
            spilled += 1
        self._set_kv_spilled_gauge(
            self._mgr.host_used_blocks())
        return spilled

    def _shed_admitted(self, entry: dict, slot: int, why: str) -> None:
        """Shed one ALREADY-CLAIMED admission typed 429 (spill-tier
        fault mid-admission): release its pages and reservation, free
        the slot (no chunk was dispatched, so the previous occupant's
        claim-time freeze still holds), and resolve the waiter.  The
        host tier is untouched — its record still serves the next
        attempt."""
        with self._lock:
            if self._slot_req[slot] is entry:
                self._slot_req[slot] = None
            self._tables[slot][:] = self.kv_pool_blocks
            self._tables_dirty = True
            self._release_entry_locked(entry)
            self._counters["in_flight"] -= 1
            self._counters["shed"] += 1
            self._counters["kv_shed_no_blocks"] += 1
        self._shed_ctr.inc(batcher=self._metric_name)
        self._kv_shed_ctr.inc(engine=self._metric_name)
        entry["err"] = Overloaded(
            f"engine {self._metric_name!r} spill-tier re-import "
            f"failed mid-admission: {why}",
            retry_after_s=self.overload_retry_after_s)
        entry["event"].set()

    def _park_kv(self, entry: dict) -> None:
        """Delivery-side session park (§5.10, loop thread, pages still
        slot-held): publish the FULL context — prompt + emitted; the
        last sampled token has no cache entry — as an ordinary device
        record AND eagerly copy its full-block pages into the host
        tier.  A parked conversation is cold by definition: the next
        turn resumes through the device index while the record is
        warm, through host-tier re-import once pressure spills it,
        and over :fetch_kv from a surviving peer after failover.  A
        gather fault degrades to device-resident-only parking."""
        from kubeflow_tpu.models.generate import gather_kv_pages

        context = np.concatenate(
            [entry["tokens"][0],
             np.asarray(entry["emitted"], np.int32)])
        true_len = int(context.shape[0]) - 1
        n = min(true_len // self.kv_block_tokens, len(entry["blocks"]))
        salt = entry.get("adapter_salt", b"")
        with self._lock:
            self._counters["parked_sessions"] += 1
            if n > 0 and self.prefix_caching:
                self._mgr.publish(context, true_len, entry["blocks"],
                                  salt=salt)
        if n <= 0 or not self.host_spill_blocks:
            return
        try:
            # Chaos hook: the park-side gather — same site and same
            # degradation as the pressure spill above.
            faults.fire("engine.spill")
            pages_k, pages_v = gather_kv_pages(
                self._state, entry["blocks"][:n])
        except Exception:
            return
        with self._lock:
            stored = self._mgr.host_put(
                context, true_len, {"k": pages_k, "v": pages_v},
                salt=salt)
            if stored:
                self._counters["spill_pages_out"] += stored
        if stored:
            self._kv_spill_ctr.inc(stored, engine=self._metric_name,
                                   direction="out")
        self._set_kv_spilled_gauge(
            self._mgr.host_used_blocks())

    def fetch_kv(self, inputs: Dict[str, Any]) -> Dict[str, Any]:
        """Fleet-wide session fetch (§5.10, any thread): serve the
        longest HOST-TIER match of ``tokens`` in the export wire form
        (``{"kv_handoff", "tokens_covered"}`` — encode_kv_handoff
        makes it portable), or a miss with no payload.  Host tier
        ONLY, by design: the device pool's buffers are donated to
        in-flight step programs, so a transport thread must never
        gather them — and parked/spilled sessions, the only state a
        failover survivor needs, are host-resident by construction."""
        tokens = np.asarray(inputs["tokens"], np.int32).reshape(-1)
        # Adapter-scoped lookup: a variant's digest chain is salted
        # with its CONTENT digest, so a fetching peer passes the same
        # digest to address the same pages (base traffic: no salt).
        salt = b""
        digest = inputs.get("adapter_digest")
        if digest:
            salt = bytes.fromhex(str(digest))
        # Chaos hook: the cross-replica fetch path (raise = fetch
        # failure — the router falls back to recompute-resume; sleep =
        # slow fetch).
        faults.fire("engine.fetch")
        with self._lock:
            self._counters["fetches"] += 1
            payload, depth = self._mgr.lookup_spilled(
                tokens, int(tokens.shape[0]), salt=salt)
        if payload is None:
            return {"kv_handoff": None, "tokens_covered": 0}

        def side(pages):
            vals, scale = pages
            if scale is None:
                return vals[:, :depth]
            return {"values": vals[:, :depth],
                    "scale": scale[:, :depth]}

        covered = depth * self.kv_block_tokens
        return {
            "kv_handoff": {
                "block_tokens": self.kv_block_tokens,
                "tokens_covered": covered,
                "k": side(payload["k"]),
                "v": side(payload["v"]),
            },
            "tokens_covered": covered,
        }

    def _begin_prefill(self, entry: dict, slot: int) -> None:
        """Admission, host side.  The admission plan already aliased
        the longest cached prefix into the slot's block table (a
        refcount bump — no device copy exists), so all that remains is
        accounting and the FIRST prefill chunk, dispatched at claim
        time: its unconditional device-side ``done`` freeze is what
        makes reusing a deadline-expired slot safe — without it an
        interleaved decode_step would advance the dead occupant and
        scatter through the NEW request's table."""
        prompt = entry["tokens"][0]
        true_len = int(prompt.shape[0])
        cached = entry["cached"]
        # Chaos hook: sleep = slow admission; raise = device death at
        # admission (propagates to _abort, every waiter resolved).
        faults.fire("engine.admit")
        with self._lock:
            self._counters["prompt_tokens"] += true_len
            if self.prefix_caching:
                # Hit/miss accounting only when caching is ON — with
                # caching disabled a climbing miss counter would read
                # as "cache enabled and failing" on dashboards.
                if cached:
                    self._counters["prefix_hits"] += 1
                    self._counters["cached_tokens"] += cached
                else:
                    self._counters["prefix_misses"] += 1
        if self.prefix_caching:
            (self._hits_ctr if cached else self._misses_ctr).inc(
                engine=self._metric_name)
        if entry["trace"] is not None:
            # Admission span: queue wait (submit -> slot claim) plus
            # the prefix verdict — TTFT debugging's first question
            # ("was it queued or was it prefill?") answered per
            # request.  cached tokens cost zero copies now, so there
            # is no copy_ms to report.
            tracing.record_span(
                "engine.admission", entry["trace"], entry["t_perf"],
                time.perf_counter(),
                attrs={"engine": self._metric_name, "slot": slot,
                       "prompt_tokens": true_len,
                       "cached_tokens": cached,
                       "prefix": "hit" if cached else "miss"})
        if entry.get("handoff"):
            # Disaggregated decode tier: scatter the prefill tier's
            # transferred pages into the reserved blocks, then chunk-
            # prefill only the uncovered suffix (>= 1 token — the
            # final chunk arms the slot exactly as a local prefill
            # would).
            self._import_handoff(entry)
        elif entry.get("spill_in"):
            # Host-tier re-import (§5.10): same mechanics, pages from
            # this replica's own spill tier instead of the wire.
            self._import_spill(entry)
        entry["prefilling"] = True
        self._prefill_chunk(entry)  # claim-time freeze + first chunk
        if entry["prefilling"]:
            self._prefilling.append(entry)

    def _prefill_chunk(self, entry: dict) -> None:
        """One static-width chunk of one entry's prompt into its slot
        (dispatch only — the final chunk's first sampled token joins
        the lagged pending stream)."""
        from kubeflow_tpu.models.generate import prefill_chunk_into_slot

        w = self.chunk_w
        prompt = entry["tokens"][0]
        true_len = int(prompt.shape[0])
        # The chunk's [start, start+w) window may overhang the
        # reserved pages on the final chunk (right-pad columns past
        # the prompt): the paged scatter PARKS those positions on the
        # table sentinel and drops them — they sit beyond every
        # frontier the slot can reach, so no pull-back dance is
        # needed.
        start = entry["pos"]
        chunk = np.zeros((1, w), np.int32)
        seg = prompt[start:start + w]
        chunk[0, :seg.shape[0]] = seg
        self._ensure_cover(entry, start + w - 1)
        if self._chunk_exec is None:
            lower_args = [
                self.cfg, self.params, self._state, self.decode,
                chunk, np.int32(0), np.int32(1), np.int32(1),
                np.int32(0), np.int32(0), self._tables[:1]]
            if self._registry is not None:
                # Adapter-array serving: the row index is a TRACED
                # operand of the ONE chunked-prefill executable (row 0
                # = base), so compiled_programs() never grows a
                # per-adapter entry.
                lower_args.append(np.int32(0))
            self._chunk_exec = prefill_chunk_into_slot.lower(
                *lower_args).compile()
        call_args = [
            self.params, self._state, chunk,
            np.int32(start), np.int32(true_len), np.int32(entry["new"]),
            np.int32(entry["slot"]), np.int32(entry["seed"]),
            self._tables[entry["slot"]:entry["slot"] + 1]]
        if self._registry is not None:
            call_args.append(np.int32(entry.get("adapter", 0)))
        t0 = time.perf_counter()
        self._state, tok = self._chunk_exec(*call_args)
        dt = time.perf_counter() - t0
        entry["pos"] = start + w
        finished = entry["pos"] >= true_len
        if finished:
            entry["prefilling"] = False
            entry["scheduled"] = 1
            self._pending.append((tok, [(0, entry)], None))
            if self.prefix_caching:
                # Publication is free: the full-block prefix pages
                # this prefill just wrote ARE the cache entry — a
                # refcount bump in the index, no donor copy.
                with self._lock:
                    self._mgr.publish(
                        prompt, true_len, entry["blocks"],
                        salt=entry.get("adapter_salt", b""))
        with self._lock:
            self._counters["prefill_chunks"] += 1
            # Prefill compute belongs in busy_s — tokens_per_sec must
            # not count tokens whose cost was never measured (short-
            # completion workloads would otherwise read up to ~2x the
            # real rate).
            self._counters["busy_s"] += dt
            self._chunk_times.append(dt)
            if len(self._chunk_times) > 4096:
                del self._chunk_times[:2048]
            if finished:
                self._counters["prefills"] += 1
        self._chunks_ctr.inc(engine=self._metric_name)
        if entry["trace"] is not None:
            tracing.record_span(
                "engine.prefill_chunk", entry["trace"], t0, t0 + dt,
                attrs={"engine": self._metric_name, "start": start,
                       "width": w,
                       **({"final": True} if finished else {})})

    def _finish(self, entry: dict) -> None:
        """Resolve a completed request: prompt + emitted tokens."""
        out = np.concatenate(
            [entry["tokens"],
             np.asarray(entry["emitted"], np.int32)[None]], axis=1)
        entry["out"] = {"tokens": out}
        if entry.get("export"):
            # Prefill-tier delivery: the finished pages ride the
            # response (gathered before release, while the slot still
            # holds them).
            self._attach_export(entry)
        if entry.get("park"):
            # Multi-turn session park (§5.10): publish + host-copy the
            # full context before release frees its pages.
            self._park_kv(entry)
        if entry["want_timing"]:
            now = faults.monotonic()
            entry["out"]["ttft_s"] = (
                (entry["t_first"] or now) - entry["t"])
            entry["out"]["latency_s"] = now - entry["t"]
            entry["out"]["cached_tokens"] = entry["cached"]
        if entry["trace"] is not None:
            # ONE decode span per request, stamped at delivery: first
            # token -> last token, annotated with the emitted count and
            # the speculative tokens verify_step accepted on its
            # behalf.  Per-step spans would cost the hot loop; this
            # costs one record at drain.
            end = time.perf_counter()
            tracing.record_span(
                "engine.decode", entry["trace"],
                entry["t_first_perf"] or end, end,
                attrs={"engine": self._metric_name,
                       "tokens": len(entry["emitted"]),
                       "spec_accepted": entry["spec_acc"]})
        entry["event"].set()

    def _drain_one(self) -> None:
        """Materialize the oldest pending emission and hand its tokens
        to their requests; retire + resolve the ones that completed.
        Counter merges are batched: one locked update per drained call,
        not per token.

        Three emission shapes ride the one stream: a prefill's [1]
        first token (counts None, col 0), a decode call's
        [steps, slots] grid (counts None — every live slot emitted one
        token per fused step), and a slot-major grid with a per-slot
        ``counts`` vector for the VARIABLE-count programs — a verify
        call's [slots, k+1] accepted prefixes plus free token, and a
        fused decode round's [slots, k] per-step emissions (both cut
        at EOS/budget on device, so row s carries counts[s] real
        tokens)."""
        arr, snapshot, counts = self._pending.pop(0)
        host = np.asarray(arr)
        emitted = 0
        finished = 0
        finished_entries: List[dict] = []
        ttfts: List[float] = []
        if counts is not None:
            counts = np.asarray(counts)
        for col, entry in snapshot:
            if counts is not None:       # verify: row per slot
                toks = host[col, :int(counts[col])]
            elif host.ndim >= 2:         # decode: [steps, slots]
                toks = host[:, col]
            else:                        # prefill first token: [1]
                toks = host
            for tok in toks:
                if entry["event"].is_set() or len(entry["emitted"]) >= \
                        entry["new"]:
                    break
                tok = int(tok)
                if entry["t_first"] is None:
                    entry["t_first"] = faults.monotonic()
                    if entry["trace"] is not None:
                        entry["t_first_perf"] = time.perf_counter()
                entry["emitted"].append(tok)
                if entry["hist"] is not None:
                    entry["hist"][entry["hist_len"]] = tok
                    entry["hist_len"] += 1
                emitted += 1
                complete = len(entry["emitted"]) >= entry["new"] or (
                    self._eos and tok == self.decode.eos_token)
                if complete:
                    # The device `done` flag froze this slot at the
                    # same step, so freeing it here (possibly sync_lag
                    # calls late on the EOS path) never races the cache.
                    if self._slot_req[entry["slot"]] is entry:
                        # Slot table is loop-thread-owned: only _run/
                        # _drain_one rebind entries; stats() reads a
                        # GIL-atomic snapshot under the lock purely
                        # for counter consistency.
                        # kft: allow=lock-guard
                        self._slot_req[entry["slot"]] = None
                    self._finish(entry)
                    finished_entries.append(entry)
                    ttfts.append(entry["t_first"] - entry["t"])
                    finished += 1
                    break
        with self._lock:
            self._counters["tokens"] += emitted
            self._counters["requests"] += finished
            self._counters["in_flight"] -= finished
            # Delivered requests return their private KV pages to the
            # pool; published prefix pages stay resident as evictable
            # cache until LRU eviction needs them.
            for e in finished_entries:
                self._release_entry_locked(e)
            self._ttft_times.extend(ttfts)
            if len(self._ttft_times) > 4096:
                del self._ttft_times[:2048]
            # Wake streaming readers: their tokens materialized above.
            self._emit.notify_all()
        if emitted:
            self._tok_counter.inc(emitted, engine=self._metric_name)

    @staticmethod
    def _blend_rate(ema, rate):
        return rate if ema is None else (
            (1 - _SPEC_RATE_ALPHA) * ema + _SPEC_RATE_ALPHA * rate)

    def _record_step_timing(self, t0, end, norm, steps, occupancy,
                            extra=None, delivered=None, program="step",
                            round_steps=None):
        """Shared per-round accounting for ALL step programs (decode,
        fused decode rounds, and verify): busy time, step/occupancy
        counters, the per-token latency and inter-token-gap
        reservoirs, the step histogram, AND the throughput-gate EMAs —
        one discipline, so the percentiles the bench and e2e assert on
        mean the same thing on every path and the speculation gate
        compares decode and verify in the same currency.  ``norm`` is
        tokens-per-slot-stream this call (fused steps for decode, mean
        emissions of advancing slots for verify); ``extra`` merges
        additional counters under the same lock (a scrape must never
        see spec_steps ahead of steps); ``delivered`` (tokens the
        round actually delivered, post-EOS/budget) feeds the
        ``program``'s rate EMA per ROUND — a fused dispatch of k steps
        is one EMA sample, not k, so the spec gate prices fused decode
        by its delivered rate, not its call rate; ``round_steps``
        appends to the steps-per-round reservoir (fused rounds
        only)."""
        dt = end - t0
        per_tok = dt / norm
        gap = (end - self._last_step_end
               if self._last_step_end is not None else None)
        self._last_step_end = end
        # Pace EMA (loop-thread-owned): the fused-round deadline clamp
        # reads this as its step-latency estimate.
        self._step_pace_ema = per_tok if self._step_pace_ema is None \
            else ((1 - _ROUND_PACE_ALPHA) * self._step_pace_ema
                  + _ROUND_PACE_ALPHA * per_tok)
        with self._lock:
            self._counters["steps"] += steps
            self._counters["occupancy_sum"] += occupancy
            self._counters["busy_s"] += dt
            if extra:
                for key, value in extra.items():
                    self._counters[key] += value
            self._step_times.append(per_tok)
            if len(self._step_times) > 4096:
                del self._step_times[:2048]
            if gap is not None:
                self._gap_times.append(gap / norm)
                if len(self._gap_times) > 4096:
                    del self._gap_times[:2048]
            if round_steps is not None:
                self._round_steps.append(round_steps)
                if len(self._round_steps) > 4096:
                    del self._round_steps[:2048]
        self._step_hist.observe(per_tok, engine=self._metric_name)
        if delivered is not None and delivered > 0 and dt > 0:
            rate = delivered / dt
            if program == "verify":
                self._rate_verify_ema = self._blend_rate(
                    self._rate_verify_ema, rate)
            else:
                self._rate_step_ema = self._blend_rate(
                    self._rate_step_ema, rate)

    def _round_width(self) -> int:
        """Current fused-round step width: the adaptive value, clamped
        so ``width x pace`` stays under the tightest live deadline's
        remaining tolerance.  Deadline expiry granularity is the ROUND
        — the sweep only runs between dispatches — so an unclamped
        width could schedule a whole round past the soonest deadline
        and deliver nothing but a late 504 (docs §5.2e)."""
        width = self._round_k
        pace = self._step_pace_ema
        if width > 1 and pace and pace > 0:
            now = faults.monotonic()
            tightest = None
            for r in self._slot_req:
                if r is None or r["deadline"] is None:
                    continue
                rem = r["deadline"] - now
                tightest = rem if tightest is None \
                    else min(tightest, rem)
            if tightest is not None:
                width = min(width, max(1, int(tightest / pace)))
        return max(1, min(width, self.decode_rounds))

    def _refresh_tables_dev(self) -> None:
        """Upload the host block tables to the device (double buffer).
        Called from the overlap window right after next-round cover
        growth, so the transfer rides alongside the in-flight round's
        compute; a table mutation after that point (admission row
        reset, expiry parking, speculative trim) re-marks dirty and
        the next dispatch re-uploads before launching.  Under a mesh
        whose table placement could not be introspected from the
        compiled executable, keep passing the host array instead — the
        runtime then transfers per dispatch, exactly as the unfused
        ``decode_step`` path always has (correctness first, the
        overlap win is opt-in)."""
        import jax

        with self._lock:
            self._tables_dirty = False
            tables = self._tables.copy()
        if self.mesh is not None and self._tables_sharding is None:
            self._tables_dev = None
            return
        if self._tables_sharding is not None:
            self._tables_dev = jax.device_put(
                tables, self._tables_sharding)
        else:
            self._tables_dev = jax.device_put(tables)

    def _draft_ahead(self, snapshot, width: int) -> None:
        """Overlapped drafting: while the fused round computes, run
        the n-gram scan against each slot's DISPATCH-TIME history and
        stash the proposal on the entry.  The proposal must survive
        the in-flight round, so it is drafted ``width`` tokens deeper
        than the verify window; at the next round boundary
        ``_harvest_ahead_drafts`` checks the round's delivered tokens
        against the proposal's head — a matching prefix means the tail
        is still a valid draft at the new frontier, a divergence drops
        it (the next fused round simply runs undrafted).  Either way a
        verify dispatch never waits on a drafting scan.  Scan-stride
        backoff and the per-slot width cooldown tick here — this IS
        the scan site in fused mode, mirroring ``_collect_drafts``."""
        k = self.speculative_tokens
        self._spec_tick += 1
        if self._spec_tick < self._spec_stride:
            return
        self._spec_tick = 0
        proposed = False
        for i, entry in snapshot:
            if self._slot_req[i] is not entry \
                    or entry["event"].is_set():
                # Deterministically retired at this round's dispatch
                # (or already resolved): it will not verify next round.
                continue
            if entry["spec_k"] <= 0:
                entry["spec_cool"] -= 1
                if entry["spec_cool"] <= 0:
                    entry["spec_k"] = max(1, k // 2)
                continue
            room = entry["new"] - len(entry["emitted"]) - 1
            if room <= 0:
                continue
            depth = width + min(k, entry["spec_k"], room)
            proposal = _ngram_propose(
                entry["hist"][:entry["hist_len"]], depth)
            if proposal.size:
                proposed = True
                entry["draft_ahead"] = (entry["hist_len"], proposal)
        if proposed:
            self._spec_stride = 1
        else:
            self._spec_stride = min(self._spec_stride * 2,
                                    _SPEC_SCAN_STRIDE_MAX)

    def _harvest_ahead_drafts(self):
        """Boundary-side half of overlapped drafting (see
        ``_draft_ahead``): rebuild ``_collect_drafts``'s
        (snapshot, draft, draft_len) contract from the ahead-proposals
        whose heads matched the tokens the fused round actually
        delivered, clipped to the verify window at the NEW frontier.
        Returns None when nothing survived — the loop then runs a
        plain fused round, which re-drafts in its overlap window.
        Greedy token identity is unaffected either way: verify accepts
        exact argmax matches only, so a stale-but-lucky draft and a
        fresh one deliver the same tokens."""
        k = self.speculative_tokens
        draft = draft_len = None
        snapshot: List[tuple] = []
        for i, entry in enumerate(self._slot_req):
            if entry is None or entry["prefilling"]:
                continue
            snapshot.append((i, entry))
            ahead = entry.pop("draft_ahead", None)
            if ahead is None:
                continue
            at_len, proposal = ahead
            grown = entry["hist_len"] - at_len
            if grown < 0 or grown >= proposal.size:
                continue
            if grown and not np.array_equal(
                    entry["hist"][at_len:entry["hist_len"]],
                    proposal[:grown]):
                continue
            room = entry["new"] - len(entry["emitted"]) - 1
            width = min(int(proposal.size) - grown, k,
                        entry["spec_k"], room)
            if width <= 0:
                continue
            if draft is None:
                draft = np.zeros((self.slots, k), np.int32)
                draft_len = np.zeros((self.slots,), np.int32)
            draft[i, :width] = proposal[grown:grown + width]
            draft_len[i] = width
        if draft is None:
            return None
        return snapshot, draft, draft_len

    def _fused_round(self, live: int) -> None:
        """One fused decode round (decode_rounds > 1): a single
        ``decode_rounds`` dispatch advances every live slot up to
        ``width`` steps with device-side early exit the moment all are
        done, and the host work for the NEXT round — cover growth, the
        double-buffered block-table upload, the n-gram drafting scan —
        runs in the overlap window while the device computes.  Drains
        synchronously at the round boundary: admissions and expiries
        join between rounds, and deadline expiry granularity becomes
        the round (``_round_width`` clamps the width under the
        tightest live deadline).  Greedy tokens are bit-identical to
        the k=1 loop: the device math is ``decode_step``'s body and
        slot math is per-row independent, so scheduling granularity
        cannot change any slot's token stream."""
        from kubeflow_tpu.models.generate import decode_rounds

        kmax = self.decode_rounds
        width = self._round_width()
        snapshot = [(i, r) for i, r in enumerate(self._slot_req)
                    if r is not None and not r["prefilling"]]
        # Worst-case cover for the WHOLE round before dispatch: the
        # device may write `width` new positions per slot and the
        # block tables ride in as one host-owned snapshot.  The
        # admission reservation guarantees the pages, so this never
        # blocks.
        for _, r in snapshot:
            self._ensure_cover(
                r, r["tokens"].shape[1] + r["scheduled"] + width - 1)
        if self._rounds_exec is None:
            # One executable serves EVERY adaptive width: the buffer
            # size k is static, the per-round step cap is a traced
            # operand.  Built outside the timed window (compile must
            # not pollute the step percentiles).
            self._rounds_exec = decode_rounds.lower(
                self.cfg, self.params, self._state, self.decode, kmax,
                self._tables, np.int32(kmax)).compile()
            if self.mesh is not None:
                # The double-buffered upload must land the tables
                # exactly where the SPMD executable expects them;
                # when that sharding is not introspectable, fall back
                # to passing the host array per dispatch (see
                # _refresh_tables_dev).
                try:
                    self._tables_sharding = \
                        self._rounds_exec.input_shardings[0][2]
                except Exception:
                    self._tables_sharding = None
        if self._tables_dirty:
            self._refresh_tables_dev()
        tables = (self._tables_dev if self._tables_dev is not None
                  else self._tables)
        # Chaos hook: the same site as the unfused step — injected
        # stalls/deaths hit fused rounds identically (deadlines expire
        # mid-round, _abort resolves waiters).
        faults.fire("engine.step")
        tok_before = self._counters["tokens"]
        t0 = time.perf_counter()
        self._state, toks, counts, steps_run = self._rounds_exec(
            self.params, self._state, tables, np.int32(width))
        # ---- overlap window: the dispatch returned as soon as the
        # round was enqueued; everything until the np.asarray below
        # runs while the device computes.
        # Deterministic retirement at dispatch: with no EOS a slot
        # whose remaining budget fits this round is KNOWN to finish —
        # the loop early-exits only when EVERY slot is done, so it can
        # never stop short of a still-advancing slot's budget.
        for i, r in snapshot:
            r["scheduled"] = min(r["new"], r["scheduled"] + width)
            if not self._eos and r["scheduled"] >= r["new"]:
                # Loop-thread-owned (see _drain_one).
                # kft: allow=lock-guard
                self._slot_req[i] = None
        # Double buffer: grow the NEXT round's covers and start their
        # table upload now, so the next dispatch finds the transfer
        # already done (or at least in flight) instead of paying it on
        # the critical path.
        for i, r in snapshot:
            if self._slot_req[i] is r:
                self._ensure_cover(
                    r, r["tokens"].shape[1] + r["scheduled"] + kmax - 1)
        if self._tables_dirty:
            self._refresh_tables_dev()
        # Overlapped drafting for the next boundary's verify round.
        if self.speculative_tokens:
            self._draft_ahead(snapshot, width)
        # Overlapped spill (§5.10): evacuate one cold record while the
        # round computes — the gather is enqueued behind the in-flight
        # round, so the host blocks at most where it would block on
        # the round's tokens anyway, and pool pressure drains in the
        # window PR 16 opened instead of on the admission path.
        if self.host_spill_blocks:
            self._spill_tick(1)
        # ---- round boundary: materialize ONCE, deliver, account.
        toks_np = np.asarray(toks)
        counts_np = np.asarray(counts)
        steps = int(steps_run)
        self._pending.append((toks_np, snapshot, counts_np))
        while self._pending:
            self._drain_one()
        end = time.perf_counter()
        delivered = self._counters["tokens"] - tok_before
        dispatched = steps * len(snapshot)
        wasted = max(0, dispatched - delivered)
        # Adaptive width (the PR 7 discipline on the round dimension):
        # shrink on early-exit waste or a waiting admission, grow one
        # step per full, waste-free round.
        if dispatched and (self._queue
                           or wasted > _ROUND_WASTE_FRAC * dispatched):
            self._round_k = max(1, self._round_k // 2)
        elif steps >= width and not wasted:
            self._round_k = min(kmax, self._round_k + 1)
        norm = max(1, steps)
        self._record_step_timing(
            t0, end, norm, steps=norm, occupancy=live * norm,
            extra={"fused_rounds": 1, "fused_steps_wasted": wasted},
            delivered=delivered, round_steps=steps)
        self._fused_rounds_ctr.inc(1, engine=self._metric_name)
        if wasted:
            self._fused_wasted_ctr.inc(wasted,
                                       engine=self._metric_name)

    def _collect_drafts(self):
        """Host-side n-gram drafting pass over the live slots.

        Returns (snapshot, draft [slots, k], draft_len [slots]) when at
        least one slot proposed tokens, else None — the loop then runs
        the plain decode program, so traffic the drafter cannot
        predict (and slots whose adaptive width backed off to zero)
        never pays the k+1-wide verify window.  Histories are exact:
        speculation forces sync_lag 0, so every emitted token is
        already materialized when the drafter reads it."""
        k = self.speculative_tokens
        # Draft buffers allocate lazily: most rounds on unrepetitive
        # traffic propose nothing, and this runs once per decode round
        # — its no-draft path must cost microseconds.
        draft = draft_len = None
        snapshot: List[tuple] = []
        for i, entry in enumerate(self._slot_req):
            if entry is None or entry["prefilling"]:
                continue
            snapshot.append((i, entry))
            if entry["spec_k"] <= 0:
                # Backed off: tick the cooldown, then re-probe at a
                # width that can clear the draft-mass floor on its own
                # (a width-1 probe from a lone drafting slot would be
                # mass-gated forever), so a tail that TURNS repetitive
                # recovers.
                entry["spec_cool"] -= 1
                if entry["spec_cool"] <= 0:
                    entry["spec_k"] = max(1, k // 2)
                continue
            # Never draft past the budget: the final budgeted token is
            # the verify call's free token, so a request with <= 1
            # token of room gains nothing from drafting.
            room = entry["new"] - len(entry["emitted"]) - 1
            width = min(k, entry["spec_k"], room)
            if width <= 0:
                continue
            proposal = _ngram_propose(
                entry["hist"][:entry["hist_len"]], width)
            if proposal.size:
                if draft is None:
                    draft = np.zeros((self.slots, k), np.int32)
                    draft_len = np.zeros((self.slots,), np.int32)
                draft[i, :proposal.size] = proposal
                draft_len[i] = proposal.size
        if draft is None:
            return None
        return snapshot, draft, draft_len

    def _spec_gates_pass(self, draft_len) -> bool:
        """Should this round's proposals actually dispatch verify?

        Mass gate: the verify window is STATICALLY k+1 wide — its
        device cost does not shrink with the actual draft mass — so a
        round proposing under half of even ONE window's worth
        (room-capped request tails) cannot win.

        Throughput gate: dispatch verify only while its MEASURED
        delivered rate beats the decode program's (EMAs over real
        calls — break-even is hardware dependent, so it is measured,
        not assumed).  Persistently mediocre acceptance — drafts that
        match often enough to pass the mass gate but not often enough
        to pay for the window — lands here; a probe verify every few
        gated rounds keeps the estimate fresh so traffic that turns
        repetitive re-enables itself.  Together with the per-slot
        width backoff these gates are the no-regression guarantee for
        low-acceptance traffic."""
        if int(draft_len.sum()) < max(1, self.speculative_tokens // 2):
            return False
        if self._rate_step_ema is not None \
                and self._rate_verify_ema is not None:
            if self._rate_verify_ema \
                    < _SPEC_RATE_MARGIN * self._rate_step_ema:
                self._spec_probe += 1
                if self._spec_probe < _SPEC_PROBE_EVERY:
                    return False
            self._spec_probe = 0
        return True

    def _verify_round(self, snapshot, draft, draft_len,
                      live: int) -> None:
        """One speculative round: dispatch verify_step over every live
        slot, drain the variable-count emissions synchronously, and
        fold the outcome into the adaptive widths + counters.

        Rejected drafts need minimal host-side cleanup: the program
        only advanced each slot's cache_len over the accepted prefix,
        so the rejected columns are already behind the attention mask
        (device-side rollback) and the host just trims whole rejected-
        tail BLOCKS back to the pool; prefix publication only ever
        covers full PROMPT blocks written by prefill, so a drafted-
        but-rejected token can never enter a published prefix page."""
        from kubeflow_tpu.models.generate import verify_step

        # Cover every slot's verify window [len, len + k] with pages
        # from its reservation BEFORE dispatch (accepted positions
        # must land in real pages; positions past the reservation can
        # only be rejected/past-budget and park on the sentinel).
        for _, entry in snapshot:
            self._ensure_cover(
                entry, entry["tokens"].shape[1] + len(entry["emitted"])
                + self.speculative_tokens)
        if self._verify_exec is None:
            self._verify_exec = verify_step.lower(
                self.cfg, self.params, self._state, self.decode,
                self.speculative_tokens, draft, draft_len,
                self._tables).compile()
        # Chaos hook: the same site as the decode step — injected
        # stalls/deaths must hit speculative rounds identically
        # (deadlines expire mid-verify, _abort resolves waiters).
        faults.fire("engine.step")
        t0 = time.perf_counter()
        self._state, toks, counts = self._verify_exec(
            self.params, self._state, draft, draft_len, self._tables)
        # Materialize ONCE and share the host copies with the drain —
        # a second device->host transfer per round would show up at
        # this call rate.
        toks_np = np.asarray(toks)
        counts_np = np.asarray(counts)
        self._pending.append((toks_np, snapshot, counts_np))
        while len(self._pending) > self.sync_lag:  # sync: drains all
            self._drain_one()
        end = time.perf_counter()
        drafted = int(draft_len.sum())
        accepted = 0
        for col, entry in snapshot:
            d = int(draft_len[col])
            if not d:
                continue
            lim = min(d, int(counts_np[col]))
            a = 0
            while a < lim and toks_np[col, a] == draft[col, a]:
                a += 1
            accepted += a
            if entry["trace"] is not None:
                # Per-request accepted-token tally for the decode
                # span's annotation (stamped at delivery).
                entry["spec_acc"] += a
            # Adaptive width: additive increase on a full accept,
            # additive decrease on a full reject; at zero the slot
            # stops paying drafting until the cooldown re-probe.
            if a == d:
                entry["spec_k"] = min(self.speculative_tokens,
                                      entry["spec_k"] + 1)
            elif a == 0:
                entry["spec_k"] -= 1
                if entry["spec_k"] <= 0:
                    entry["spec_k"] = 0
                    entry["spec_cool"] = _SPEC_COOLDOWN
        # Speculative rollback, pool side: the drain materialized each
        # slot's true emission count, so pages past the new frontier
        # hold only rejected-draft garbage — trim them back to the
        # pool (a delivered/expired entry already released everything).
        # `scheduled` tracks the delivered count too: the plain decode
        # rounds that follow a backed-off slot size their page cover
        # from it, and a stale value would let a later decode write
        # park on the table sentinel and silently drop its k/v.
        for _, entry in snapshot:
            entry["scheduled"] = max(entry["scheduled"],
                                     len(entry["emitted"]))
            if not entry["released"]:
                self._trim_cover(
                    entry,
                    entry["tokens"].shape[1] + len(entry["emitted"]))
        total = int(counts_np.sum())
        advancing = int(np.count_nonzero(counts_np))
        # Per-TOKEN latency/gap samples: one verify call delivers a
        # variable token count, so normalize by the mean emissions of
        # the slots that advanced — the client-visible stream pace.
        # The verify-rate EMA rides the shared accounting path
        # (delivered tokens per round, same currency as fused decode).
        norm = max(1.0, total / advancing) if advancing else 1.0
        self._record_step_timing(
            t0, end, norm, steps=1, occupancy=live,
            extra={"spec_steps": 1, "spec_drafted": drafted,
                   "spec_accepted": accepted},
            delivered=total, program="verify")
        if drafted:
            self._spec_drafted_ctr.inc(drafted,
                                       engine=self._metric_name)
        if accepted:
            self._spec_accepted_ctr.inc(accepted,
                                        engine=self._metric_name)

    def _run(self) -> None:
        from kubeflow_tpu.models.generate import decode_step

        try:
            while True:
                with self._lock:
                    while (not self._queue
                           and all(r is None for r in self._slot_req)
                           and not self._pending and not self._stopped):
                        self._work.wait()
                    if self._stopped and not self._queue \
                            and all(r is None for r in self._slot_req) \
                            and not self._pending:
                        return
                    stopping = self._stopped
                    past_drain = (stopping and self._drain_deadline
                                  is not None and faults.monotonic()
                                  > self._drain_deadline)
                    expired = self._sweep_expired_locked()
                    admissions = []
                    if not stopping:
                        free = self._free_slots_locked()
                        while (free and self._queue
                               and len(self._prefilling)
                               + len(admissions) < self.admit_width):
                            pick = self._fair_pick_locked()
                            entry = self._queue[pick]
                            plan = self._plan_blocks_locked(entry)
                            if plan is None:
                                # Tokens-resident admission bound: the
                                # pool cannot reserve this request's
                                # worst case yet.  It HOLDS its queue
                                # position (no starvation — the pick
                                # is stable until pages free) until
                                # retirements free pages; submit sheds
                                # new arrivals past the queue cap.
                                break
                            self._queue.pop(pick)
                            self._fair_seq += 1
                            self._fair_last[
                                entry.get("adapter_name") or ""] = \
                                self._fair_seq
                            slot = free.pop(0)
                            shared, cached = plan
                            # Claim the slot and bump in_flight in the
                            # same locked section that pops the queue:
                            # stats() must never see queue_depth==0 AND
                            # in_flight_requests==0 while a request is
                            # live (monitors treat that as "drained"),
                            # and an entry registered here is reachable
                            # by _abort even if its prefill dispatch
                            # dies.
                            entry["slot"] = slot
                            entry["cached"] = cached
                            entry["pos"] = cached
                            entry["blocks"] = list(shared)
                            entry["res_left"] = \
                                entry["res_blocks"] - len(shared)
                            # Zero-copy prefix resume: the cached
                            # blocks slide into the table's leading
                            # entries; prefill starts at the cached
                            # offset.
                            row = self._tables[slot]
                            row[:] = self.kv_pool_blocks
                            row[:len(shared)] = shared
                            self._tables_dirty = True
                            self._slot_req[slot] = entry
                            self._counters["in_flight"] += 1
                            admissions.append((entry, slot))
                        self._set_queue_gauge(len(self._queue))
                self._fail_expired(expired)
                if expired and self._prefilling:
                    # Mid-prefill expiries leave the chunk schedule
                    # (the sweep already released their pages and
                    # parked their table rows); their frozen slots are
                    # safe to reclaim (claim-time first-chunk freeze).
                    self._prefilling = [
                        p for p in self._prefilling
                        if not any(p is e for e in expired)]
                if past_drain:
                    self._abort(RuntimeError(
                        f"engine {self._metric_name!r} drain deadline "
                        "exceeded at close"))
                    return
                if stopping:
                    # Refuse queued work immediately; keep stepping only
                    # to drain in-flight slots.
                    self._fail_queue(BatcherClosed(
                        f"engine {self._metric_name!r} is closed"))
                if self._registry is not None:
                    # Hot adapter load/evict (§5.11): fold any pending
                    # stack version into params between dispatches —
                    # live traffic never waits, in-flight rows are
                    # never torn, and no program recompiles.
                    self._apply_adapter_updates()
                if self.host_spill_blocks:
                    # Spill-then-admit (§5.10): evacuate LRU-cold idle
                    # records to the host tier BEFORE this round's
                    # take() calls (admission prefills below, chunk
                    # budget, decode covers) can destroy-evict them —
                    # pool pressure degrades to a host copy, not to
                    # recompute.
                    self._spill_tick()
                for entry, slot in admissions:
                    try:
                        self._begin_prefill(entry, slot)
                    except _SpillShed as exc:
                        self._shed_admitted(entry, slot, str(exc))
                # Chunked prefill BETWEEN decode steps, under the
                # per-step token budget: the head admission (FIFO —
                # oldest finishes first, best TTFT) gets chunks until
                # the budget is spent, then the loop returns to
                # decoding.  In-flight slots therefore stall at most
                # ~budget prompt-tokens of prefill per step, no matter
                # how long the arriving prompts are.
                budget = self.prefill_chunk_tokens
                while budget > 0 and self._prefilling:
                    entry = self._prefilling[0]
                    self._prefill_chunk(entry)
                    budget -= self.chunk_w
                    if not entry["prefilling"]:
                        self._prefilling.pop(0)
                self._set_occ_gauge(
                    sum(r is not None for r in self._slot_req))
                live = sum(1 for r in self._slot_req
                           if r is not None and not r["prefilling"])
                if live and self.speculative_tokens \
                        and self.decode_rounds > 1:
                    # Fused mode: the drafting scan already ran in the
                    # PREVIOUS round's overlap window (_draft_ahead
                    # owns the stride backoff there); harvest the
                    # proposals that survived the in-flight round and
                    # dispatch verify with no drafting stall on the
                    # critical path.  Nothing harvested => plain fused
                    # round below, which re-drafts while it computes.
                    if any(e.get("spec_seed") for e, _ in admissions):
                        self._spec_stride = 1
                        self._spec_tick = self._spec_stride
                        self._spec_probe = _SPEC_PROBE_EVERY
                    drafts = self._harvest_ahead_drafts()
                    if drafts is not None \
                            and self._spec_gates_pass(drafts[2]):
                        self._verify_round(*drafts, live)
                        self._set_occ_gauge(sum(
                            r is not None for r in self._slot_req))
                        continue
                elif live and self.speculative_tokens:
                    # Speculation: draft host-side; when at least one
                    # slot proposed, one verify call replaces this
                    # round's decode step (undrafted slots ride along
                    # at draft_len 0 and still net their one token).
                    # No drafts => fall through to the plain decode
                    # program — the adaptive backoff's no-regression
                    # guarantee for low-acceptance traffic — and
                    # stretch the scan stride so persistent
                    # unrepetitive traffic stops paying even the scan.
                    self._spec_tick += 1
                    if any(e.get("spec_seed") for e, _ in admissions):
                        # A draftable prompt arrived: scan next round
                        # and let the first drafted round probe even
                        # if earlier traffic measured speculation
                        # unprofitable — a new request is a new
                        # regime.
                        self._spec_stride = 1
                        self._spec_tick = self._spec_stride
                        self._spec_probe = _SPEC_PROBE_EVERY
                    if self._spec_tick >= self._spec_stride:
                        self._spec_tick = 0
                        drafts = self._collect_drafts()
                        if drafts is None:
                            # Truly EMPTY scan (nothing proposed):
                            # stretch the scan period.  Gate-blocked
                            # rounds below do NOT — proposals exist,
                            # so the scan stays productive and the
                            # probe cadence stays honest.
                            self._spec_stride = min(
                                self._spec_stride * 2,
                                _SPEC_SCAN_STRIDE_MAX)
                        else:
                            self._spec_stride = 1
                            if self._spec_gates_pass(drafts[2]):
                                self._verify_round(*drafts, live)
                                self._set_occ_gauge(sum(
                                    r is not None
                                    for r in self._slot_req))
                                continue
                if live and self.decode_rounds > 1:
                    self._fused_round(live)
                elif live:
                    k = self.steps_per_call
                    # Cover every advancing slot's next k write
                    # positions with pages from its admission
                    # reservation BEFORE dispatch (the reservation
                    # guarantees them, so this can never block); slots
                    # already done on device write nothing, and the
                    # cover cap at res_blocks bounds what an EOS-lagged
                    # slot can take to pages it had reserved anyway.
                    for r in self._slot_req:
                        if r is None or r["prefilling"]:
                            continue
                        self._ensure_cover(
                            r, r["tokens"].shape[1]
                            + r["scheduled"] + k - 1)
                    # Build (one-time) OUTSIDE the timed window: the
                    # first per-token latency sample must not carry
                    # seconds of XLA compile into the p50/p95 stats and
                    # the step histogram.
                    if self._step_exec is None:
                        self._step_exec = decode_step.lower(
                            self.cfg, self.params, self._state,
                            self.decode, k, self._tables).compile()
                    # Chaos hook: sleep = slow/wedged step (deadlines
                    # expire mid-generation); raise = device death.
                    # Outside the timed window so the injected stall
                    # does not masquerade as device latency in the
                    # step histogram.
                    faults.fire("engine.step")
                    # Counter read is loop-thread-local (the sync
                    # drain below merges into it on this same thread):
                    # the delta across the drain is the tokens this
                    # round actually DELIVERED — post-EOS/post-budget
                    # fused steps emit nothing, so live*k would
                    # overstate the decode rate and the throughput
                    # gate would suppress profitable speculation.
                    tok_before = (self._counters["tokens"]
                                  if self.speculative_tokens else 0)
                    t0 = time.perf_counter()
                    self._state, sampled = self._step_exec(
                        self.params, self._state, self._tables)
                    self._pending.append((sampled, [
                        (i, r) for i, r in enumerate(self._slot_req)
                        if r is not None and not r["prefilling"]], None))
                    # Deterministic retirement: with no EOS in play a
                    # request's completion step is known at dispatch —
                    # free the slot NOW so the next admission overlaps
                    # the lagged read instead of waiting for it.  The
                    # request stays visible in in_flight until its
                    # lagged emission is delivered.
                    for i, r in enumerate(self._slot_req):
                        if r is None or r["prefilling"]:
                            continue
                        r["scheduled"] = min(r["new"],
                                             r["scheduled"] + k)
                        if not self._eos and r["scheduled"] >= r["new"]:
                            # Loop-thread-owned (see _drain_one).
                            # kft: allow=lock-guard
                            self._slot_req[i] = None
                    while len(self._pending) > self.sync_lag:
                        self._drain_one()
                    end = time.perf_counter()
                    # Per-call latency and gap normalized by fused
                    # steps: what a client streaming tokens would see
                    # between tokens, including interleaved
                    # admission/prefill work.  The delivered-token
                    # delta feeds the speculation throughput gate its
                    # decode-side comparison rate (same currency as
                    # the verify side's counts sum).
                    self._record_step_timing(
                        t0, end, k, steps=k, occupancy=live * k,
                        delivered=(self._counters["tokens"] - tok_before
                                   if self.speculative_tokens else None))
                else:
                    self._last_step_end = None
                    if not self._prefilling:
                        while self._pending:
                            self._drain_one()
                self._set_occ_gauge(
                    sum(r is not None for r in self._slot_req))
                # Pages resident (loop thread is the pool's only
                # mutator; the guarded setter only touches the locked
                # registry on change).
                self._set_kv_used_gauge(self._mgr.used_blocks())
                if self.host_spill_blocks:
                    self._set_kv_spilled_gauge(
                        self._mgr.host_used_blocks())
        except BaseException as exc:  # noqa: BLE001 — fail loudly to waiters
            self._abort(exc)

    def _fail_queue(self, exc: Exception) -> None:
        with self._lock:
            queued, self._queue = self._queue, []
            self._set_queue_gauge(0)
        for entry in queued:
            self._unpin_adapter(entry)
            entry["err"] = exc
            entry["event"].set()

    def _abort(self, exc: BaseException) -> None:
        """Engine death: every waiter gets the error, nobody hangs."""
        with self._lock:
            self._stopped = True
            self._counters["in_flight"] = 0
        err = exc if isinstance(exc, Exception) else \
            RuntimeError(f"engine loop died: {exc!r}")
        self._fail_queue(err)
        # Fail live slots AND requests whose slots were already
        # deterministically retired but whose lagged emissions still sit
        # in _pending — those entries are in neither the queue nor the
        # slot table, and clearing _pending without resolving them would
        # leave their clients parked in submit() forever.
        for i, entry in enumerate(self._slot_req):
            if entry is not None and not entry["event"].is_set():
                self._unpin_adapter(entry)
                entry["err"] = err
                entry["event"].set()
            # Loop thread is dead or dying here; no concurrent writer
            # exists (see _drain_one).
            # kft: allow=lock-guard
            self._slot_req[i] = None
        for _, snapshot, _ in self._pending:
            for _, entry in snapshot:
                if not entry["event"].is_set():
                    self._unpin_adapter(entry)
                    entry["err"] = err
                    entry["event"].set()
        self._pending.clear()
        self._prefilling.clear()
        self._set_occ_gauge(0)
