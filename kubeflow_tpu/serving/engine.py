"""Continuous-batching LM decode engine: slot-based serving loop.

The static batchers (MicroBatcher / BucketedLMBatcher) dispatch whole
``generate()`` programs: a batch is assembled, padded, and OWNED by one
device program from prefill to the last token.  Two structural costs
follow — a request that arrives mid-generation waits for the entire
program, and every row pays the batch bucket's padded KV span on every
decode step (models/generate.py's docstring measures ~6x wasted decode
compute on wide length distributions).

This engine runs the slot entry points instead (models/generate.py)
over ONE persistent KV cache of ``slots`` rows:

  - a dedicated step loop advances all live slots one token per
    ``decode_step`` call;
  - new requests are admitted into free slots BETWEEN steps, and their
    prompts prefill in **static-width chunks scheduled between decode
    steps** under a per-step token budget (``prefill_chunk_tokens``) —
    a long arriving prompt can never stall in-flight decode for longer
    than one chunk's compute, where a one-shot full-width prefill
    stalls every active slot for the whole prompt;
  - admission first resumes from the **longest cached shared prefix**:
    a host-side block-hashed index (serving/prefix_cache.py) over a
    small pinned pool of donor KV rows finds the longest token-block
    prefix a previous prompt already computed, ``copy_prefix_into_slot``
    copies those columns on device, and chunked prefill continues from
    there — TTFT scales with the *uncached suffix* length, not the full
    prompt (the win for fleets of chat requests sharing a system
    prompt);
  - finished rows retire immediately (device-side ``done`` flag) and
    their slots are reused — no request ever waits for the batch to
    drain, and per-request ``max_new_tokens`` is data, not a compiled
    constant;
  - every shape is static, so the engine's whole lifetime compiles
    exactly three programs (chunked prefill, prefix copy, step).

The host loop reads sampled tokens with a small LAG (``sync_lag``
steps): step N+lag is dispatched before step N's tokens are
materialized, so host bookkeeping overlaps device compute instead of
serializing on it.  Completion is detected deterministically from the
per-request budget (and, when EOS is configured, from the lagged token
stream — the device flag has already frozen the slot by then, so the
lag costs at most ``sync_lag`` idle slot-steps).

Interface-compatible with the batchers (submit/accepts/stats/close), so
ModelServer.enable_batching wires it behind the REST and gRPC surfaces
unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from kubeflow_tpu.serving.errors import (
    BatcherClosed,
    DeadlineExceeded,
    Overloaded,
)
from kubeflow_tpu.serving.model_server import (
    EXPIRED_HELP,
    EXPIRED_TOTAL,
    SHED_HELP,
    SHED_TOTAL,
    locked_snapshot,
)
from kubeflow_tpu.serving.prefix_cache import PrefixIndex
from kubeflow_tpu.testing import faults

# Step-duration histogram buckets: decode steps run ~0.1 ms (tiny CPU
# smoke models) to ~100 ms (big models over a slow tunnel).
_STEP_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                 1.0, 2.5)

PREFIX_HITS_TOTAL = "kft_engine_prefix_hits_total"
PREFIX_HITS_HELP = "admissions resumed from a cached prefix, by engine"
PREFIX_MISSES_TOTAL = "kft_engine_prefix_misses_total"
PREFIX_MISSES_HELP = "admissions with no cached prefix, by engine"
PREFIX_EVICTIONS_TOTAL = "kft_engine_prefix_evictions_total"
PREFIX_EVICTIONS_HELP = "donor prefix-pool rows evicted (LRU), by engine"
PREFILL_CHUNKS_TOTAL = "kft_engine_prefill_chunks_total"
PREFILL_CHUNKS_HELP = "prefill chunk program calls, by engine"


def _true_token_len(row: np.ndarray) -> int:
    """Real prompt length of a 1-D token row: trailing pad ids (token
    0, the framework-wide pad convention) do not count.  An all-pad row
    keeps its full width — there is no basis to trim it."""
    nz = np.flatnonzero(row)
    return int(nz[-1]) + 1 if nz.size else int(row.shape[0])


class DecodeEngine:
    """Continuous-batching decode over a persistent slot-based KV cache.

    Args:
      cfg/params/decode: the loaded model (loaders.lm_generate exposes
        them as ``predict.engine_spec`` — params already staged to HBM).
      slots: concurrent sequences (the persistent cache's row count).
      prefill_len: static prompt width bound; prompts with more REAL
        tokens (trailing pad ids don't count) fall back to the direct
        generate() path.
      max_len: cache columns per slot (default prefill_len +
        decode.max_new_tokens).
      sync_lag: how many step calls the host may run ahead of token
        materialization (0 = fully synchronous loop).
      steps_per_call: decode steps fused into one step-program call
        (models/generate.py decode_step's static ``steps``): per-call
        dispatch overhead amortizes over k tokens, admission waits at
        most k steps.  One engine uses one value, so the three-program
        guarantee holds either way.
      admit_width: how many admissions may be MID-PREFILL concurrently
        — further queued requests wait even when slots are free, so a
        burst of long prompts cannot hoard every slot in a half-filled
        state.  Chunk scheduling among the admitted set is FIFO (the
        oldest admission takes the whole budget until it finishes —
        best TTFT for the head of the line).
      prefill_chunk_tokens: per-step prefill token budget AND the
        static chunk program width (clamped to prefill_len): between
        two decode steps the loop spends at most this many prompt
        tokens on chunked prefill, which bounds the inter-token latency
        of in-flight slots regardless of arriving prompt length.
      prefix_pool_blocks: donor rows in the shared-prefix KV pool
        (0 disables prefix caching; chunked prefill still applies).
        Each row holds up to prefill_len cached columns and is filled
        as a free side effect of a cache-miss admission's chunked
        prefill, then reused by later admissions sharing the prefix.
      prefix_block_tokens: prefix hash/publish granularity — prefixes
        are cached and matched in multiples of this many tokens.
      max_queue_depth: bounded admission — a submit arriving with this
        many requests already waiting for slots fails fast with
        Overloaded (HTTP 429 / gRPC RESOURCE_EXHAUSTED) instead of
        queueing unboundedly; 0 = unbounded.  The in-flight cap is
        ``slots`` by construction, so total accepted work is bounded
        by slots + max_queue_depth.
      overload_retry_after_s: the Retry-After hint a shed submission
        carries back to the client.
    """

    def __init__(
        self,
        cfg,
        params,
        decode,
        *,
        slots: int = 8,
        prefill_len: int = 256,
        max_len: Optional[int] = None,
        sync_lag: int = 2,
        steps_per_call: int = 1,
        admit_width: int = 4,
        prefill_chunk_tokens: int = 64,
        prefix_pool_blocks: int = 4,
        prefix_block_tokens: int = 16,
        max_queue_depth: int = 0,
        overload_retry_after_s: float = 1.0,
        name: str = "engine",
    ):
        from kubeflow_tpu.models.generate import (
            init_prefix_pool,
            init_slot_state,
        )
        from kubeflow_tpu.runtime.prom import REGISTRY

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.cfg = cfg
        self.params = params
        self.decode = decode
        self.slots = slots
        self.prefill_len = int(prefill_len)
        if self.prefill_len < 1:
            # A non-positive width silently rejects EVERY prompt via
            # accepts() — all traffic would fall back to the direct
            # path while the engine holds a cache and a thread.  Can
            # arise from the serving entrypoint's derived default when
            # an export config has max_new_tokens >= max_seq_len.
            raise ValueError(
                f"prefill_len must be >= 1, got {self.prefill_len}")
        self.max_len = int(max_len or prefill_len + decode.max_new_tokens)
        if self.max_len <= self.prefill_len:
            raise ValueError(
                f"max_len {self.max_len} leaves no decode room beyond "
                f"prefill_len {self.prefill_len}")
        if getattr(cfg, "max_seq_len", self.max_len) < self.max_len:
            raise ValueError(
                f"max_len {self.max_len} exceeds model max_seq_len "
                f"{cfg.max_seq_len}")
        self.sync_lag = max(0, int(sync_lag))
        self.steps_per_call = max(1, int(steps_per_call))
        self.admit_width = max(1, min(int(admit_width), slots))
        self.prefill_chunk_tokens = max(1, int(prefill_chunk_tokens))
        self.chunk_w = min(self.prefill_chunk_tokens, self.prefill_len)
        self.prefix_pool_blocks = max(0, int(prefix_pool_blocks))
        self.prefix_block_tokens = max(1, int(prefix_block_tokens))
        self.max_queue_depth = max(0, int(max_queue_depth))
        self.overload_retry_after_s = overload_retry_after_s
        self._eos = decode.eos_token >= 0
        self._state = init_slot_state(cfg, slots, self.max_len,
                                      decode.kv_cache_dtype)
        # Donor prefix pool: allocated even when caching is off (one
        # row) so the chunk/copy programs keep one static shape — the
        # copy program's slot FREEZE is load-bearing for admission
        # safety regardless of caching (see copy_prefix_into_slot).
        self._pool_rows = max(1, self.prefix_pool_blocks)
        self._pool = init_prefix_pool(cfg, self._pool_rows,
                                      self.prefill_len,
                                      decode.kv_cache_dtype)
        self._index = (
            PrefixIndex(self.prefix_pool_blocks,
                        self.prefix_block_tokens, self.prefill_len)
            if self.prefix_pool_blocks > 0 else None)
        # AOT executables, built lazily by the loop thread: the step
        # loop calls its programs thousands of times per second, and
        # the jitted wrapper re-hashes the whole params pytree
        # signature per call (~0.4 ms on the smoke config — comparable
        # to the step itself).  lower().compile() once, then call the
        # executable.  This is also the three-program guarantee made
        # literal: these three fields ARE the engine's compiled
        # programs.
        self._chunk_exec = None
        self._copy_exec = None
        self._step_exec = None

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._stopped = False
        self._drain_deadline: Optional[float] = None
        # Host-side slot table: None = free, else the live request entry.
        self._slot_req: List[Optional[dict]] = [None] * slots
        # Admitted entries whose prompts are still chunk-prefilling
        # (FIFO — the oldest admission finishes first, best TTFT).
        # Loop-thread-owned; the admission pop reads only its length.
        self._prefilling: List[dict] = []
        # (tokens_array, [(slot, entry), ...]) emissions not yet read.
        self._pending: List[tuple] = []
        # Counters (mutated by the loop thread, snapshotted under the
        # lock — the same locked-snapshot discipline MicroBatcher uses).
        self._counters = {
            "requests": 0, "tokens": 0, "steps": 0, "prefills": 0,
            "occupancy_sum": 0, "busy_s": 0.0, "in_flight": 0,
            "shed": 0, "expired": 0,
            "prefix_hits": 0, "prefix_misses": 0, "prefix_evictions": 0,
            "prefill_chunks": 0, "cached_tokens": 0, "prompt_tokens": 0,
        }
        self._step_times: List[float] = []   # bounded reservoirs
        self._chunk_times: List[float] = []
        self._gap_times: List[float] = []
        self._ttft_times: List[float] = []
        self._last_step_end: Optional[float] = None
        self._metric_name = name
        self._occ_gauge = REGISTRY.gauge(
            "kft_engine_active_slots",
            "decode engine live slots, by engine")
        self._queue_gauge = REGISTRY.gauge(
            "kft_engine_queue_depth",
            "decode engine admission queue depth, by engine")
        self._tok_counter = REGISTRY.counter(
            "kft_engine_tokens_total",
            "tokens emitted by the decode engine, by engine")
        self._step_hist = REGISTRY.histogram(
            "kft_engine_step_seconds",
            "decode engine per-step (= per-token) latency, by engine",
            buckets=_STEP_BUCKETS,
        ).declare(engine=name)
        self._hits_ctr = REGISTRY.counter(
            PREFIX_HITS_TOTAL, PREFIX_HITS_HELP)
        self._misses_ctr = REGISTRY.counter(
            PREFIX_MISSES_TOTAL, PREFIX_MISSES_HELP)
        self._evict_ctr = REGISTRY.counter(
            PREFIX_EVICTIONS_TOTAL, PREFIX_EVICTIONS_HELP)
        self._chunks_ctr = REGISTRY.counter(
            PREFILL_CHUNKS_TOTAL, PREFILL_CHUNKS_HELP)
        # Fault-layer series: same names as the static batchers', so
        # shed/expired rates read uniformly across batching planes.
        self._shed_ctr = REGISTRY.counter(SHED_TOTAL, SHED_HELP)
        self._expired_ctr = REGISTRY.counter(EXPIRED_TOTAL, EXPIRED_HELP)
        self._occ_gauge.set(0, engine=name)
        self._queue_gauge.set(0, engine=name)
        # Last values pushed to the gauges — the step loop only touches
        # the (locked) registry when a value actually changes.
        self._occ_last = 0
        self._queue_last = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"decode-engine-{name}")
        self._thread.start()

    # -- client surface ---------------------------------------------------

    def accepts(self, inputs: Dict[str, Any]) -> bool:
        """ModelServer routing hook: prompts whose REAL token count
        (an explicit ``prompt_len``, else the width minus trailing pad
        ids) exceeds the static prefill width fall back to the direct
        generate() path.  A short prompt arriving right-padded — e.g.
        from a client that pads to a fixed wire shape — is admitted at
        its true length, not rejected for its padded width."""
        tokens = np.asarray(inputs.get("tokens", ()))
        if tokens.ndim == 0 or tokens.size == 0:
            return False
        row = tokens.reshape(-1)
        if "prompt_len" in inputs:
            length = int(np.asarray(inputs["prompt_len"]).reshape(()))
            if not 0 < length <= row.shape[0]:
                return False
        else:
            length = _true_token_len(row)
        return bool(0 < length <= self.prefill_len)

    def submit(self, inputs: Dict[str, Any],
               deadline: Optional[float] = None) -> Dict[str, Any]:
        """One request: tokens [t] or [1, t]; optional per-request
        ``max_new_tokens`` (<= engine headroom), sampling ``seed``, and
        ``prompt_len`` (real token count of a right-padded prompt —
        without it, trailing pad ids (token 0) are trimmed, so a padded
        short prompt is neither rejected nor over-prefilled, and never
        generates with pad tokens in its context).  Blocks until the
        completion is ready; returns {"tokens": [1, true_len + emitted]}.
        With ``return_timing`` truthy the result also carries
        ``ttft_s`` / ``latency_s`` / ``cached_tokens`` (bench surface).

        ``deadline`` (absolute faults.monotonic() instant) is enforced
        everywhere the request lives: expired-on-arrival raises here,
        an expired queued request is failed before admission, and an
        expired IN-FLIGHT request is retired mid-generation through
        the deterministic-retirement path — its slot frees for the
        next admission while its lagged device emissions are dropped
        on the floor, exactly like a normally-retired slot's."""
        tokens = np.asarray(inputs["tokens"], np.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        n, width = tokens.shape
        if n != 1:
            raise ValueError(
                f"DecodeEngine.submit takes one prompt per call (got "
                f"batch dim {n}); submit rows separately")
        if "prompt_len" in inputs:
            length = int(np.asarray(inputs["prompt_len"]).reshape(()))
            if not 0 < length <= width:
                raise ValueError(
                    f"prompt_len {length} outside (0, {width}] "
                    f"(the tokens width)")
        else:
            length = _true_token_len(tokens[0])
        if not 0 < length <= self.prefill_len:
            raise ValueError(
                f"true prompt length {length} outside "
                f"(0, {self.prefill_len}] (engine prefill width)")
        tokens = np.ascontiguousarray(tokens[:, :length])
        new = int(np.asarray(inputs.get(
            "max_new_tokens", self.decode.max_new_tokens)).reshape(()))
        if new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {new}")
        # Same budget contract as every other serving path: the export
        # config's max_new_tokens is the ceiling (a client cannot buy a
        # bigger completion than the model advertises), and the cache
        # headroom caps it further — both against the TRUE length.
        new = min(new, self.decode.max_new_tokens, self.max_len - length)
        seed = int(np.asarray(inputs.get("seed", 0)).reshape(()))
        if deadline is not None and faults.monotonic() >= deadline:
            with self._lock:
                self._counters["expired"] += 1
            self._expired_ctr.inc(batcher=self._metric_name)
            raise DeadlineExceeded(
                f"deadline expired before engine "
                f"{self._metric_name!r} admission")
        entry = {
            "tokens": tokens, "new": new, "seed": seed,
            "emitted": [], "scheduled": 0, "slot": None,
            "prefilling": False, "pos": 0, "cached": 0, "pool_row": None,
            "deadline": deadline,
            "want_timing": bool(inputs.get("return_timing")),
            "event": threading.Event(), "out": None, "err": None,
            "t": time.monotonic(), "t_first": None,
        }
        with self._lock:
            if self._stopped:
                raise BatcherClosed(
                    f"engine {self._metric_name!r} is closed")
            if self.max_queue_depth \
                    and len(self._queue) >= self.max_queue_depth:
                # Bounded admission: all slots busy and the wait line
                # is full — fail fast instead of queueing unboundedly
                # (under overload a 429 now beats a 504 later).
                self._counters["shed"] += 1
                self._shed_ctr.inc(batcher=self._metric_name)
                raise Overloaded(
                    f"engine {self._metric_name!r} admission queue "
                    f"full ({len(self._queue)} waiting, "
                    f"{self.slots} slots busy)",
                    retry_after_s=self.overload_retry_after_s)
            self._queue.append(entry)
            self._set_queue_gauge(len(self._queue))
            self._work.notify()
        entry["event"].wait()
        if entry["err"] is not None:
            raise entry["err"]
        return entry["out"]

    def compiled_programs(self) -> Dict[str, int]:
        """How many device programs this engine has compiled — by
        construction at most one chunked-prefill, one prefix-copy, and
        one step executable (the build sites are None-guarded), so a
        healthy engine reports {"chunked_prefill": 1, "copy_prefix": 1,
        "step": 1} for its whole lifetime."""
        return {"chunked_prefill": int(self._chunk_exec is not None),
                "copy_prefix": int(self._copy_exec is not None),
                "step": int(self._step_exec is not None)}

    def stats(self) -> Dict[str, Any]:
        """Locked snapshot of the engine counters: occupancy, queue
        depth, throughput, per-token (= per-step) latency, prefix-cache
        effectiveness, and prefill-interference bounds."""
        c, extra = locked_snapshot(
            self._lock, self._counters,
            lambda: {
                "queue_depth": len(self._queue),
                "active_slots": sum(
                    r is not None for r in self._slot_req),
                "step_times": list(self._step_times),
                "chunk_times": list(self._chunk_times),
                "gap_times": list(self._gap_times),
                "ttft_times": list(self._ttft_times),
            })
        steps = c["steps"]

        def pct(values, q):
            if not values:
                return 0.0
            values = sorted(values)
            return round(values[min(len(values) - 1,
                                    int(len(values) * q))] * 1e3, 3)

        times = extra["step_times"]
        gaps = extra["gap_times"]
        prompt_toks = c["prompt_tokens"]
        return {
            "requests": c["requests"],
            "tokens": c["tokens"],
            "steps": steps,
            "prefills": c["prefills"],
            "slots": self.slots,
            "active_slots": extra["active_slots"],
            "queue_depth": extra["queue_depth"],
            # Admitted but not yet delivered.  THIS is the drain signal:
            # deterministic retirement frees a slot at dispatch (before
            # the lagged emission reaches its client), so active_slots
            # can touch zero while completions are still in flight.
            "in_flight_requests": c["in_flight"],
            # Fault-layer outcomes: admissions refused at the queue cap
            # and requests failed by their deadline (queued or
            # in-flight) — the chaos scenario's primary assertions.
            "shed": c["shed"],
            "deadline_expired": c["expired"],
            # Prefix cache: how much prompt compute the donor pool
            # saved.  cached_token_ratio is the operator's one-glance
            # effectiveness number (also exported per-replica to the
            # fleet — see ModelServer.refresh_gauges).
            "prefix_hits": c["prefix_hits"],
            "prefix_misses": c["prefix_misses"],
            "prefix_evictions": c["prefix_evictions"],
            "cached_prompt_tokens": c["cached_tokens"],
            "prompt_tokens": prompt_toks,
            "cached_token_ratio": round(
                c["cached_tokens"] / prompt_toks, 4)
            if prompt_toks else 0.0,
            # Chunked prefill: calls made and their latency — one chunk
            # is the most an arriving prompt may stall in-flight decode
            # per scheduling turn.
            "prefill_chunks": c["prefill_chunks"],
            "prefill_chunk_p95_ms": pct(extra["chunk_times"], 0.95),
            "mean_occupancy": round(c["occupancy_sum"] / steps, 2)
            if steps else 0.0,
            "tokens_per_sec": round(c["tokens"] / c["busy_s"], 1)
            if c["busy_s"] else 0.0,
            "token_latency_p50_ms": pct(times, 0.50),
            "token_latency_p95_ms": pct(times, 0.95),
            "token_latency_p99_ms": pct(times, 0.99),
            # Wall time between consecutive step-call completions while
            # slots were live — the client-visible inter-token gap,
            # INCLUDING whatever admission/prefill work ran in between.
            # Bounded by the chunk budget; a full-prefill stall would
            # spike the max.
            "inter_token_gap_p50_ms": pct(gaps, 0.50),
            "inter_token_gap_p99_ms": pct(gaps, 0.99),
            "inter_token_gap_max_ms": round(max(gaps) * 1e3, 3)
            if gaps else 0.0,
            "ttft_p50_ms": pct(extra["ttft_times"], 0.50),
            "ttft_p99_ms": pct(extra["ttft_times"], 0.99),
        }

    def close(self, drain_s: float = 10.0) -> None:
        """Deterministic shutdown: refuse new work, give in-flight
        requests ``drain_s`` to finish, fail whatever remains with
        BatcherClosed, and join the loop thread (bounded — mirrors
        ModelServer.stop(); no background-thread leakage across a test
        session)."""
        with self._lock:
            if self._stopped:
                self._work.notify_all()
            else:
                self._stopped = True
                self._drain_deadline = time.monotonic() + max(0.0, drain_s)
                self._work.notify_all()
        self._thread.join(timeout=max(5.0, drain_s + 5.0))
        # The prefix index dies with the engine (reload invalidation:
        # the serving layer rebuilds engine + pool per model version);
        # clear it here too so a closed-but-referenced engine can never
        # serve a stale prefix.
        if self._index is not None:
            self._index.invalidate()
        # A closed engine exports no live slots or queue: hot-swap
        # retires the metric series at zero instead of freezing a
        # stale occupancy in /metrics forever.
        self._set_occ_gauge(0)
        self._set_queue_gauge(0)

    # -- step loop --------------------------------------------------------

    def _free_slots_locked(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _sweep_expired_locked(self) -> List[dict]:
        """Pull every deadline-expired request out of the queue AND the
        live slot table (caller fails them outside the lock).

        In-flight expiry rides the deterministic-retirement path: the
        slot is freed NOW — the next admission's prefix-copy program
        freezes it on device, which is the device-side abort — and the
        request's lagged emissions still in _pending are dropped by
        _drain_one's event-set check, exactly like a normally-retired
        slot's.  No other slot's state is touched, so co-resident
        generations are unaffected."""
        pnow = faults.monotonic()
        expired: List[dict] = []
        live = []
        for entry in self._queue:
            d = entry["deadline"]
            if d is not None and d <= pnow:
                expired.append(entry)
            else:
                live.append(entry)
        if len(live) != len(self._queue):
            self._queue[:] = live
            self._set_queue_gauge(len(self._queue))
        for i, entry in enumerate(self._slot_req):
            if entry is None:
                continue
            d = entry["deadline"]
            if d is not None and d <= pnow:
                self._slot_req[i] = None
                self._counters["in_flight"] -= 1
                expired.append(entry)
        # Deterministically-retired requests live in NEITHER the queue
        # nor the slot table while their lagged emissions sit in
        # _pending — a request is in_flight until delivery, so its
        # deadline is enforced on this tail too (under wedged steps the
        # lag is unbounded; the client must get its 504, not a late
        # 200).  A snapshot entry still slot-resident cannot reach the
        # append: the slot scan above already moved every expired slot
        # entry into `expired`, and the identity dedup skips those (and
        # entries recurring across snapshots).
        for _, snapshot in self._pending:
            for _, entry in snapshot:
                if entry["event"].is_set():
                    continue
                d = entry["deadline"]
                if d is None or d > pnow:
                    continue
                if any(entry is e for e in expired):
                    continue
                self._counters["in_flight"] -= 1
                expired.append(entry)
        if expired:
            self._counters["expired"] += len(expired)
        return expired

    def _fail_expired(self, expired: List[dict]) -> None:
        if not expired:
            return
        self._expired_ctr.inc(len(expired), batcher=self._metric_name)
        for entry in expired:
            if not entry["event"].is_set():
                entry["err"] = DeadlineExceeded(
                    f"deadline expired after {len(entry['emitted'])} "
                    f"of {entry['new']} tokens "
                    f"(engine {self._metric_name!r})")
                entry["event"].set()

    def _release_capture(self, entry: dict) -> None:
        """Abandon an entry's donor capture (expired mid-prefill): the
        pool row's partial writes are unreachable and the row unpins."""
        row = entry.get("pool_row")
        entry["pool_row"] = None
        if row is not None and self._index is not None:
            self._index.abort_capture(row)

    def _set_queue_gauge(self, depth: int) -> None:
        if depth != self._queue_last:
            self._queue_last = depth
            self._queue_gauge.set(depth, engine=self._metric_name)

    def _set_occ_gauge(self, active: int) -> None:
        if active != self._occ_last:
            self._occ_last = active
            self._occ_gauge.set(active, engine=self._metric_name)

    def _begin_prefill(self, entry: dict, slot: int) -> None:
        """Admission, host side: find the longest cached prefix, copy
        it into (and FREEZE) the slot in one device call, claim a donor
        row for capture on a miss, and queue the entry for chunked
        prefill.  The copy program runs for EVERY admission — at k = 0
        it is the claim-time freeze that makes reusing a deadline-
        expired slot safe (see copy_prefix_into_slot)."""
        from kubeflow_tpu.models.generate import copy_prefix_into_slot

        prompt = entry["tokens"][0]
        true_len = int(prompt.shape[0])
        row, cached = (None, 0)
        if self._index is not None:
            row, cached = self._index.lookup(prompt, true_len - 1)
        # Chaos hook: sleep = slow admission; raise = device death at
        # admission (propagates to _abort, every waiter resolved).
        faults.fire("engine.admit")
        if self._copy_exec is None:
            self._copy_exec = copy_prefix_into_slot.lower(
                self._state, self._pool, np.int32(0), np.int32(0),
                np.int32(0)).compile()
        t0 = time.perf_counter()
        self._state = self._copy_exec(
            self._state, self._pool, np.int32(row or 0), np.int32(slot),
            np.int32(cached))
        dt = time.perf_counter() - t0
        evicted = False
        if (self._index is not None and cached == 0
                and true_len >= self.prefix_block_tokens):
            # Full miss with at least one publishable block: capture
            # this prompt's prefix as a new donor while prefilling it.
            # Partial hits don't extend the donor (a donor must be
            # self-contained from column 0); the pool stays small, so
            # the common shared-system-prompt case — one miss, then
            # hits — is the one that matters.
            pool_row, evicted = self._index.begin_capture()
            entry["pool_row"] = pool_row
        entry["pos"] = cached
        entry["cached"] = cached
        entry["prefilling"] = True
        self._prefilling.append(entry)
        with self._lock:
            self._counters["prompt_tokens"] += true_len
            self._counters["busy_s"] += dt
            if self._index is not None:
                # Hit/miss accounting only when caching is ON — with
                # --prefix_pool_blocks 0 a climbing miss counter would
                # read as "cache enabled and failing" on dashboards.
                if cached:
                    self._counters["prefix_hits"] += 1
                    self._counters["cached_tokens"] += cached
                else:
                    self._counters["prefix_misses"] += 1
                if evicted:
                    self._counters["prefix_evictions"] += 1
        if self._index is not None:
            (self._hits_ctr if cached else self._misses_ctr).inc(
                engine=self._metric_name)
            if evicted:
                self._evict_ctr.inc(engine=self._metric_name)

    def _prefill_chunk(self, entry: dict) -> None:
        """One static-width chunk of one entry's prompt into its slot
        (dispatch only — the final chunk's first sampled token joins
        the lagged pending stream)."""
        from kubeflow_tpu.models.generate import prefill_chunk_into_slot

        w = self.chunk_w
        prompt = entry["tokens"][0]
        true_len = int(prompt.shape[0])
        # The final chunk's [start, start+w) write window must fit the
        # slot's max_len columns — XLA's dynamic_update_slice CLAMPS an
        # out-of-bounds start (it does not drop), which would shift the
        # whole chunk onto earlier valid columns.  Pulling start back
        # recomputes a few already-written columns instead: same
        # tokens, same positions, same prefix KV => identical k/v, so
        # the overlap is a no-op rewrite.  Only the final chunk can
        # overflow (intermediate chunks end before prompt_len <=
        # prefill_len < max_len), so this never slows steady prefill.
        start = min(entry["pos"], self.max_len - w)
        chunk = np.zeros((1, w), np.int32)
        seg = prompt[start:start + w]
        chunk[0, :seg.shape[0]] = seg
        pool_row = entry["pool_row"]
        if pool_row is None:
            pool_row = self._pool_rows  # OOB = capture writes dropped
        if self._chunk_exec is None:
            self._chunk_exec = prefill_chunk_into_slot.lower(
                self.cfg, self.params, self._state, self.decode,
                self._pool, chunk, np.int32(0), np.int32(1),
                np.int32(1), np.int32(0), np.int32(0),
                np.int32(0)).compile()
        t0 = time.perf_counter()
        self._state, self._pool, tok = self._chunk_exec(
            self.params, self._state, self._pool, chunk,
            np.int32(start), np.int32(true_len), np.int32(entry["new"]),
            np.int32(entry["slot"]), np.int32(pool_row),
            np.int32(entry["seed"]))
        dt = time.perf_counter() - t0
        entry["pos"] = start + w
        finished = entry["pos"] >= true_len
        if finished:
            entry["prefilling"] = False
            entry["scheduled"] = 1
            self._pending.append((tok, [(0, entry)]))
            if entry["pool_row"] is not None and self._index is not None:
                self._index.commit_capture(
                    entry["pool_row"], prompt, true_len)
                entry["pool_row"] = None
        with self._lock:
            self._counters["prefill_chunks"] += 1
            # Prefill compute belongs in busy_s — tokens_per_sec must
            # not count tokens whose cost was never measured (short-
            # completion workloads would otherwise read up to ~2x the
            # real rate).
            self._counters["busy_s"] += dt
            self._chunk_times.append(dt)
            if len(self._chunk_times) > 4096:
                del self._chunk_times[:2048]
            if finished:
                self._counters["prefills"] += 1
        self._chunks_ctr.inc(engine=self._metric_name)

    def _finish(self, entry: dict) -> None:
        """Resolve a completed request: prompt + emitted tokens."""
        out = np.concatenate(
            [entry["tokens"],
             np.asarray(entry["emitted"], np.int32)[None]], axis=1)
        entry["out"] = {"tokens": out}
        if entry["want_timing"]:
            now = time.monotonic()
            entry["out"]["ttft_s"] = (
                (entry["t_first"] or now) - entry["t"])
            entry["out"]["latency_s"] = now - entry["t"]
            entry["out"]["cached_tokens"] = entry["cached"]
        entry["event"].set()

    def _drain_one(self) -> None:
        """Materialize the oldest pending emission and hand its tokens
        to their requests; retire + resolve the ones that completed.
        Counter merges are batched: one locked update per drained call,
        not per token."""
        arr, snapshot = self._pending.pop(0)
        host = np.asarray(arr)
        if host.ndim < 2:   # prefill emission: [1] first token, the
            host = host[None]   # snapshot's col is 0
        emitted = 0
        finished = 0
        ttfts: List[float] = []
        for row in host:           # fused calls carry [steps, slots]
            for col, entry in snapshot:
                if entry["event"].is_set() or len(entry["emitted"]) >= \
                        entry["new"]:
                    continue
                tok = int(row[col])
                if entry["t_first"] is None:
                    entry["t_first"] = time.monotonic()
                entry["emitted"].append(tok)
                emitted += 1
                complete = len(entry["emitted"]) >= entry["new"] or (
                    self._eos and tok == self.decode.eos_token)
                if complete:
                    # The device `done` flag froze this slot at the
                    # same step, so freeing it here (possibly sync_lag
                    # calls late on the EOS path) never races the cache.
                    if self._slot_req[entry["slot"]] is entry:
                        self._slot_req[entry["slot"]] = None
                    self._finish(entry)
                    ttfts.append(entry["t_first"] - entry["t"])
                    finished += 1
        with self._lock:
            self._counters["tokens"] += emitted
            self._counters["requests"] += finished
            self._counters["in_flight"] -= finished
            self._ttft_times.extend(ttfts)
            if len(self._ttft_times) > 4096:
                del self._ttft_times[:2048]
        if emitted:
            self._tok_counter.inc(emitted, engine=self._metric_name)

    def _run(self) -> None:
        from kubeflow_tpu.models.generate import decode_step

        try:
            while True:
                with self._lock:
                    while (not self._queue
                           and all(r is None for r in self._slot_req)
                           and not self._pending and not self._stopped):
                        self._work.wait()
                    if self._stopped and not self._queue \
                            and all(r is None for r in self._slot_req) \
                            and not self._pending:
                        return
                    stopping = self._stopped
                    past_drain = (stopping and self._drain_deadline
                                  is not None and time.monotonic()
                                  > self._drain_deadline)
                    expired = self._sweep_expired_locked()
                    admissions = []
                    if not stopping:
                        free = self._free_slots_locked()
                        while (free and self._queue
                               and len(self._prefilling)
                               + len(admissions) < self.admit_width):
                            entry = self._queue.pop(0)
                            slot = free.pop(0)
                            # Claim the slot and bump in_flight in the
                            # same locked section that pops the queue:
                            # stats() must never see queue_depth==0 AND
                            # in_flight_requests==0 while a request is
                            # live (monitors treat that as "drained"),
                            # and an entry registered here is reachable
                            # by _abort even if its prefill dispatch
                            # dies.
                            entry["slot"] = slot
                            self._slot_req[slot] = entry
                            self._counters["in_flight"] += 1
                            admissions.append((entry, slot))
                        self._set_queue_gauge(len(self._queue))
                self._fail_expired(expired)
                if expired and self._prefilling:
                    # Mid-prefill expiries leave the chunk schedule and
                    # release their donor captures; their frozen slots
                    # are safe to reclaim (claim-time freeze).
                    keep = []
                    for p in self._prefilling:
                        if any(p is e for e in expired):
                            self._release_capture(p)
                        else:
                            keep.append(p)
                    self._prefilling = keep
                if past_drain:
                    self._abort(RuntimeError(
                        f"engine {self._metric_name!r} drain deadline "
                        "exceeded at close"))
                    return
                if stopping:
                    # Refuse queued work immediately; keep stepping only
                    # to drain in-flight slots.
                    self._fail_queue(BatcherClosed(
                        f"engine {self._metric_name!r} is closed"))
                for entry, slot in admissions:
                    self._begin_prefill(entry, slot)
                # Chunked prefill BETWEEN decode steps, under the
                # per-step token budget: the head admission (FIFO —
                # oldest finishes first, best TTFT) gets chunks until
                # the budget is spent, then the loop returns to
                # decoding.  In-flight slots therefore stall at most
                # ~budget prompt-tokens of prefill per step, no matter
                # how long the arriving prompts are.
                budget = self.prefill_chunk_tokens
                while budget > 0 and self._prefilling:
                    entry = self._prefilling[0]
                    self._prefill_chunk(entry)
                    budget -= self.chunk_w
                    if not entry["prefilling"]:
                        self._prefilling.pop(0)
                self._set_occ_gauge(
                    sum(r is not None for r in self._slot_req))
                live = sum(1 for r in self._slot_req
                           if r is not None and not r["prefilling"])
                if live:
                    k = self.steps_per_call
                    # Build (one-time) OUTSIDE the timed window: the
                    # first per-token latency sample must not carry
                    # seconds of XLA compile into the p50/p95 stats and
                    # the step histogram.
                    if self._step_exec is None:
                        self._step_exec = decode_step.lower(
                            self.cfg, self.params, self._state,
                            self.decode, k).compile()
                    # Chaos hook: sleep = slow/wedged step (deadlines
                    # expire mid-generation); raise = device death.
                    # Outside the timed window so the injected stall
                    # does not masquerade as device latency in the
                    # step histogram.
                    faults.fire("engine.step")
                    t0 = time.perf_counter()
                    self._state, sampled = self._step_exec(
                        self.params, self._state)
                    self._pending.append((sampled, [
                        (i, r) for i, r in enumerate(self._slot_req)
                        if r is not None and not r["prefilling"]]))
                    # Deterministic retirement: with no EOS in play a
                    # request's completion step is known at dispatch —
                    # free the slot NOW so the next admission overlaps
                    # the lagged read instead of waiting for it.  The
                    # request stays visible in in_flight until its
                    # lagged emission is delivered.
                    for i, r in enumerate(self._slot_req):
                        if r is None or r["prefilling"]:
                            continue
                        r["scheduled"] = min(r["new"],
                                             r["scheduled"] + k)
                        if not self._eos and r["scheduled"] >= r["new"]:
                            self._slot_req[i] = None
                    while len(self._pending) > self.sync_lag:
                        self._drain_one()
                    end = time.perf_counter()
                    dt = end - t0
                    per_step = dt / k
                    gap = (end - self._last_step_end
                           if self._last_step_end is not None else None)
                    self._last_step_end = end
                    with self._lock:
                        self._counters["steps"] += k
                        self._counters["occupancy_sum"] += live * k
                        self._counters["busy_s"] += dt
                        self._step_times.append(per_step)
                        if len(self._step_times) > 4096:
                            del self._step_times[:2048]
                        if gap is not None:
                            # Per-call gap normalized by fused steps:
                            # what a client streaming tokens would see
                            # between tokens, including interleaved
                            # admission/prefill work.
                            self._gap_times.append(gap / k)
                            if len(self._gap_times) > 4096:
                                del self._gap_times[:2048]
                    self._step_hist.observe(per_step,
                                            engine=self._metric_name)
                else:
                    self._last_step_end = None
                    if not self._prefilling:
                        while self._pending:
                            self._drain_one()
                self._set_occ_gauge(
                    sum(r is not None for r in self._slot_req))
        except BaseException as exc:  # noqa: BLE001 — fail loudly to waiters
            self._abort(exc)

    def _fail_queue(self, exc: Exception) -> None:
        with self._lock:
            queued, self._queue = self._queue, []
            self._set_queue_gauge(0)
        for entry in queued:
            entry["err"] = exc
            entry["event"].set()

    def _abort(self, exc: BaseException) -> None:
        """Engine death: every waiter gets the error, nobody hangs."""
        with self._lock:
            self._stopped = True
            self._counters["in_flight"] = 0
        err = exc if isinstance(exc, Exception) else \
            RuntimeError(f"engine loop died: {exc!r}")
        self._fail_queue(err)
        # Fail live slots AND requests whose slots were already
        # deterministically retired but whose lagged emissions still sit
        # in _pending — those entries are in neither the queue nor the
        # slot table, and clearing _pending without resolving them would
        # leave their clients parked in submit() forever.
        for i, entry in enumerate(self._slot_req):
            if entry is not None and not entry["event"].is_set():
                entry["err"] = err
                entry["event"].set()
            self._slot_req[i] = None
        for _, snapshot in self._pending:
            for _, entry in snapshot:
                if not entry["event"].is_set():
                    entry["err"] = err
                    entry["event"].set()
        self._pending.clear()
        self._prefilling.clear()
        self._set_occ_gauge(0)
