"""Continuous-batching LM decode engine: slot-based serving loop.

The static batchers (MicroBatcher / BucketedLMBatcher) dispatch whole
``generate()`` programs: a batch is assembled, padded, and OWNED by one
device program from prefill to the last token.  Two structural costs
follow — a request that arrives mid-generation waits for the entire
program, and every row pays the batch bucket's padded KV span on every
decode step (models/generate.py's docstring measures ~6x wasted decode
compute on wide length distributions).

This engine runs the slot entry points instead (models/generate.py:
``prefill_into_slot`` / ``decode_step``) over ONE persistent KV cache of
``slots`` rows:

  - a dedicated step loop advances all live slots one token per
    ``decode_step`` call;
  - new requests are admitted into free slots BETWEEN steps (prefill
    interleaved with decode) — admission latency is one step, not one
    generation;
  - finished rows retire immediately (device-side ``done`` flag) and
    their slots are reused — no request ever waits for the batch to
    drain, and per-request ``max_new_tokens`` is data, not a compiled
    constant;
  - every shape is static, so the engine's whole lifetime compiles
    exactly two programs (prefill, step).

The host loop reads sampled tokens with a small LAG (``sync_lag``
steps): step N+lag is dispatched before step N's tokens are
materialized, so host bookkeeping overlaps device compute instead of
serializing on it.  Completion is detected deterministically from the
per-request budget (and, when EOS is configured, from the lagged token
stream — the device flag has already frozen the slot by then, so the
lag costs at most ``sync_lag`` idle slot-steps).

Interface-compatible with the batchers (submit/accepts/stats/close), so
ModelServer.enable_batching wires it behind the REST and gRPC surfaces
unchanged.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from kubeflow_tpu.serving.errors import (
    BatcherClosed,
    DeadlineExceeded,
    Overloaded,
)
from kubeflow_tpu.serving.model_server import (
    EXPIRED_HELP,
    EXPIRED_TOTAL,
    SHED_HELP,
    SHED_TOTAL,
    locked_snapshot,
)
from kubeflow_tpu.testing import faults

# Step-duration histogram buckets: decode steps run ~0.1 ms (tiny CPU
# smoke models) to ~100 ms (big models over a slow tunnel).
_STEP_BUCKETS = (.0005, .001, .0025, .005, .01, .025, .05, .1, .25, .5,
                 1.0, 2.5)


class DecodeEngine:
    """Continuous-batching decode over a persistent slot-based KV cache.

    Args:
      cfg/params/decode: the loaded model (loaders.lm_generate exposes
        them as ``predict.engine_spec`` — params already staged to HBM).
      slots: concurrent sequences (the persistent cache's row count).
      prefill_len: static prompt width; prompts are right-padded to it.
      max_len: cache columns per slot (default prefill_len +
        decode.max_new_tokens).
      sync_lag: how many step calls the host may run ahead of token
        materialization (0 = fully synchronous loop).
      steps_per_call: decode steps fused into one step-program call
        (models/generate.py decode_step's static ``steps``): per-call
        dispatch overhead amortizes over k tokens, admission waits at
        most k steps.  One engine uses one value, so the two-program
        guarantee holds either way.
      admit_width: prefill program admission rows (static) — up to this
        many queued requests prefill in ONE call; a burst of arrivals
        amortizes per-call overhead instead of paying one serialized
        prefill per request.  Unused rows are dropped on device.
      max_queue_depth: bounded admission — a submit arriving with this
        many requests already waiting for slots fails fast with
        Overloaded (HTTP 429 / gRPC RESOURCE_EXHAUSTED) instead of
        queueing unboundedly; 0 = unbounded.  The in-flight cap is
        ``slots`` by construction, so total accepted work is bounded
        by slots + max_queue_depth.
      overload_retry_after_s: the Retry-After hint a shed submission
        carries back to the client.
    """

    def __init__(
        self,
        cfg,
        params,
        decode,
        *,
        slots: int = 8,
        prefill_len: int = 256,
        max_len: Optional[int] = None,
        sync_lag: int = 2,
        steps_per_call: int = 1,
        admit_width: int = 4,
        max_queue_depth: int = 0,
        overload_retry_after_s: float = 1.0,
        name: str = "engine",
    ):
        from kubeflow_tpu.models.generate import init_slot_state
        from kubeflow_tpu.runtime.prom import REGISTRY

        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.cfg = cfg
        self.params = params
        self.decode = decode
        self.slots = slots
        self.prefill_len = int(prefill_len)
        if self.prefill_len < 1:
            # A non-positive width silently rejects EVERY prompt via
            # accepts() — all traffic would fall back to the direct
            # path while the engine holds a cache and a thread.  Can
            # arise from the serving entrypoint's derived default when
            # an export config has max_new_tokens >= max_seq_len.
            raise ValueError(
                f"prefill_len must be >= 1, got {self.prefill_len}")
        self.max_len = int(max_len or prefill_len + decode.max_new_tokens)
        if self.max_len <= self.prefill_len:
            raise ValueError(
                f"max_len {self.max_len} leaves no decode room beyond "
                f"prefill_len {self.prefill_len}")
        if getattr(cfg, "max_seq_len", self.max_len) < self.max_len:
            raise ValueError(
                f"max_len {self.max_len} exceeds model max_seq_len "
                f"{cfg.max_seq_len}")
        self.sync_lag = max(0, int(sync_lag))
        self.steps_per_call = max(1, int(steps_per_call))
        self.admit_width = max(1, min(int(admit_width), slots))
        self.max_queue_depth = max(0, int(max_queue_depth))
        self.overload_retry_after_s = overload_retry_after_s
        self._eos = decode.eos_token >= 0
        self._state = init_slot_state(cfg, slots, self.max_len,
                                      decode.kv_cache_dtype)
        # AOT executables, built lazily by the loop thread: the step
        # loop calls its two programs thousands of times per second,
        # and the jitted wrapper re-hashes the whole params pytree
        # signature per call (~0.4 ms on the smoke config — comparable
        # to the step itself).  lower().compile() once, then call the
        # executable.  This is also the two-program guarantee made
        # literal: these two fields ARE the engine's compiled programs.
        self._prefill_exec = None
        self._step_exec = None

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue: List[dict] = []
        self._stopped = False
        self._drain_deadline: Optional[float] = None
        # Host-side slot table: None = free, else the live request entry.
        self._slot_req: List[Optional[dict]] = [None] * slots
        # (tokens_array, [(slot, entry), ...]) emissions not yet read.
        self._pending: List[tuple] = []
        # Counters (mutated by the loop thread, snapshotted under the
        # lock — the same locked-snapshot discipline MicroBatcher uses).
        self._counters = {
            "requests": 0, "tokens": 0, "steps": 0, "prefills": 0,
            "occupancy_sum": 0, "busy_s": 0.0, "in_flight": 0,
            "shed": 0, "expired": 0,
        }
        self._step_times: List[float] = []   # bounded reservoir
        self._metric_name = name
        self._occ_gauge = REGISTRY.gauge(
            "kft_engine_active_slots",
            "decode engine live slots, by engine")
        self._queue_gauge = REGISTRY.gauge(
            "kft_engine_queue_depth",
            "decode engine admission queue depth, by engine")
        self._tok_counter = REGISTRY.counter(
            "kft_engine_tokens_total",
            "tokens emitted by the decode engine, by engine")
        self._step_hist = REGISTRY.histogram(
            "kft_engine_step_seconds",
            "decode engine per-step (= per-token) latency, by engine",
            buckets=_STEP_BUCKETS,
        ).declare(engine=name)
        # Fault-layer series: same names as the static batchers', so
        # shed/expired rates read uniformly across batching planes.
        self._shed_ctr = REGISTRY.counter(SHED_TOTAL, SHED_HELP)
        self._expired_ctr = REGISTRY.counter(EXPIRED_TOTAL, EXPIRED_HELP)
        self._occ_gauge.set(0, engine=name)
        self._queue_gauge.set(0, engine=name)
        # Last values pushed to the gauges — the step loop only touches
        # the (locked) registry when a value actually changes.
        self._occ_last = 0
        self._queue_last = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"decode-engine-{name}")
        self._thread.start()

    # -- client surface ---------------------------------------------------

    def accepts(self, inputs: Dict[str, Any]) -> bool:
        """ModelServer routing hook: prompts beyond the static prefill
        width fall back to the direct generate() path."""
        tokens = np.asarray(inputs.get("tokens", ()))
        length = tokens.shape[-1] if tokens.ndim else 0
        return bool(0 < length <= self.prefill_len)

    def submit(self, inputs: Dict[str, Any],
               deadline: Optional[float] = None) -> Dict[str, Any]:
        """One request: tokens [t] or [1, t]; optional per-request
        ``max_new_tokens`` (<= engine headroom) and sampling ``seed``.
        Blocks until the completion is ready; returns
        {"tokens": [1, t + emitted]}.

        ``deadline`` (absolute faults.monotonic() instant) is enforced
        everywhere the request lives: expired-on-arrival raises here,
        an expired queued request is failed before admission, and an
        expired IN-FLIGHT request is retired mid-generation through
        the deterministic-retirement path — its slot frees for the
        next admission while its lagged device emissions are dropped
        on the floor, exactly like a normally-retired slot's."""
        tokens = np.asarray(inputs["tokens"], np.int32)
        if tokens.ndim == 1:
            tokens = tokens[None]
        n, length = tokens.shape
        if n != 1:
            raise ValueError(
                f"DecodeEngine.submit takes one prompt per call (got "
                f"batch dim {n}); submit rows separately")
        if not 0 < length <= self.prefill_len:
            raise ValueError(
                f"prompt length {length} outside (0, {self.prefill_len}]"
                f" (engine prefill width)")
        new = int(np.asarray(inputs.get(
            "max_new_tokens", self.decode.max_new_tokens)).reshape(()))
        if new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got {new}")
        # Same budget contract as every other serving path: the export
        # config's max_new_tokens is the ceiling (a client cannot buy a
        # bigger completion than the model advertises), and the cache
        # headroom caps it further.
        new = min(new, self.decode.max_new_tokens, self.max_len - length)
        seed = int(np.asarray(inputs.get("seed", 0)).reshape(()))
        if deadline is not None and faults.monotonic() >= deadline:
            with self._lock:
                self._counters["expired"] += 1
            self._expired_ctr.inc(batcher=self._metric_name)
            raise DeadlineExceeded(
                f"deadline expired before engine "
                f"{self._metric_name!r} admission")
        entry = {
            "tokens": tokens, "new": new, "seed": seed,
            "emitted": [], "scheduled": 0, "slot": None,
            "deadline": deadline,
            "event": threading.Event(), "out": None, "err": None,
            "t": time.monotonic(),
        }
        with self._lock:
            if self._stopped:
                raise BatcherClosed(
                    f"engine {self._metric_name!r} is closed")
            if self.max_queue_depth \
                    and len(self._queue) >= self.max_queue_depth:
                # Bounded admission: all slots busy and the wait line
                # is full — fail fast instead of queueing unboundedly
                # (under overload a 429 now beats a 504 later).
                self._counters["shed"] += 1
                self._shed_ctr.inc(batcher=self._metric_name)
                raise Overloaded(
                    f"engine {self._metric_name!r} admission queue "
                    f"full ({len(self._queue)} waiting, "
                    f"{self.slots} slots busy)",
                    retry_after_s=self.overload_retry_after_s)
            self._queue.append(entry)
            self._set_queue_gauge(len(self._queue))
            self._work.notify()
        entry["event"].wait()
        if entry["err"] is not None:
            raise entry["err"]
        return entry["out"]

    def compiled_programs(self) -> Dict[str, int]:
        """How many device programs this engine has compiled — by
        construction at most one prefill and one step executable (the
        build sites are None-guarded), so a healthy engine reports
        {"prefill": 1, "step": 1} for its whole lifetime."""
        return {"prefill": int(self._prefill_exec is not None),
                "step": int(self._step_exec is not None)}

    def stats(self) -> Dict[str, Any]:
        """Locked snapshot of the engine counters: occupancy, queue
        depth, throughput, and per-token (= per-step) latency."""
        c, extra = locked_snapshot(
            self._lock, self._counters,
            lambda: {
                "queue_depth": len(self._queue),
                "active_slots": sum(
                    r is not None for r in self._slot_req),
                "step_times": list(self._step_times),
            })
        steps = c["steps"]
        times = sorted(extra["step_times"])

        def pct(q):
            if not times:
                return 0.0
            return round(times[min(len(times) - 1,
                                   int(len(times) * q))] * 1e3, 3)

        return {
            "requests": c["requests"],
            "tokens": c["tokens"],
            "steps": steps,
            "prefills": c["prefills"],
            "slots": self.slots,
            "active_slots": extra["active_slots"],
            "queue_depth": extra["queue_depth"],
            # Admitted but not yet delivered.  THIS is the drain signal:
            # deterministic retirement frees a slot at dispatch (before
            # the lagged emission reaches its client), so active_slots
            # can touch zero while completions are still in flight.
            "in_flight_requests": c["in_flight"],
            # Fault-layer outcomes: admissions refused at the queue cap
            # and requests failed by their deadline (queued or
            # in-flight) — the chaos scenario's primary assertions.
            "shed": c["shed"],
            "deadline_expired": c["expired"],
            "mean_occupancy": round(c["occupancy_sum"] / steps, 2)
            if steps else 0.0,
            "tokens_per_sec": round(c["tokens"] / c["busy_s"], 1)
            if c["busy_s"] else 0.0,
            "token_latency_p50_ms": pct(0.50),
            "token_latency_p95_ms": pct(0.95),
        }

    def close(self, drain_s: float = 10.0) -> None:
        """Deterministic shutdown: refuse new work, give in-flight
        requests ``drain_s`` to finish, fail whatever remains with
        BatcherClosed, and join the loop thread (bounded — mirrors
        ModelServer.stop(); no background-thread leakage across a test
        session)."""
        with self._lock:
            if self._stopped:
                self._work.notify_all()
            else:
                self._stopped = True
                self._drain_deadline = time.monotonic() + max(0.0, drain_s)
                self._work.notify_all()
        self._thread.join(timeout=max(5.0, drain_s + 5.0))
        # A closed engine exports no live slots or queue: hot-swap
        # retires the metric series at zero instead of freezing a
        # stale occupancy in /metrics forever.
        self._set_occ_gauge(0)
        self._set_queue_gauge(0)

    # -- step loop --------------------------------------------------------

    def _free_slots_locked(self) -> List[int]:
        return [i for i, r in enumerate(self._slot_req) if r is None]

    def _sweep_expired_locked(self) -> List[dict]:
        """Pull every deadline-expired request out of the queue AND the
        live slot table (caller fails them outside the lock).

        In-flight expiry rides the deterministic-retirement path: the
        slot is freed NOW — the next admission prefills over it, which
        is the device-side abort — and the request's lagged emissions
        still in _pending are dropped by _drain_one's event-set check,
        exactly like a normally-retired slot's.  No other slot's state
        is touched, so co-resident generations are unaffected."""
        pnow = faults.monotonic()
        expired: List[dict] = []
        live = []
        for entry in self._queue:
            d = entry["deadline"]
            if d is not None and d <= pnow:
                expired.append(entry)
            else:
                live.append(entry)
        if len(live) != len(self._queue):
            self._queue[:] = live
            self._set_queue_gauge(len(self._queue))
        for i, entry in enumerate(self._slot_req):
            if entry is None:
                continue
            d = entry["deadline"]
            if d is not None and d <= pnow:
                self._slot_req[i] = None
                self._counters["in_flight"] -= 1
                expired.append(entry)
        # Deterministically-retired requests live in NEITHER the queue
        # nor the slot table while their lagged emissions sit in
        # _pending — a request is in_flight until delivery, so its
        # deadline is enforced on this tail too (under wedged steps the
        # lag is unbounded; the client must get its 504, not a late
        # 200).  A snapshot entry still slot-resident cannot reach the
        # append: the slot scan above already moved every expired slot
        # entry into `expired`, and the identity dedup skips those (and
        # entries recurring across snapshots).
        for _, snapshot in self._pending:
            for _, entry in snapshot:
                if entry["event"].is_set():
                    continue
                d = entry["deadline"]
                if d is None or d > pnow:
                    continue
                if any(entry is e for e in expired):
                    continue
                self._counters["in_flight"] -= 1
                expired.append(entry)
        if expired:
            self._counters["expired"] += len(expired)
        return expired

    def _fail_expired(self, expired: List[dict]) -> None:
        if not expired:
            return
        self._expired_ctr.inc(len(expired), batcher=self._metric_name)
        for entry in expired:
            if not entry["event"].is_set():
                entry["err"] = DeadlineExceeded(
                    f"deadline expired after {len(entry['emitted'])} "
                    f"of {entry['new']} tokens "
                    f"(engine {self._metric_name!r})")
                entry["event"].set()

    def _set_queue_gauge(self, depth: int) -> None:
        if depth != self._queue_last:
            self._queue_last = depth
            self._queue_gauge.set(depth, engine=self._metric_name)

    def _set_occ_gauge(self, active: int) -> None:
        if active != self._occ_last:
            self._occ_last = active
            self._occ_gauge.set(active, engine=self._metric_name)

    def _admit(self, batch: List[tuple]) -> None:
        """Prefill up to admit_width requests into their slots in ONE
        program call (dispatch only — the first sampled tokens join the
        lagged pending stream).  Unused admission rows point at an
        out-of-range slot; the device drops their writes."""
        from kubeflow_tpu.models.generate import prefill_into_slot

        a = self.admit_width
        tokens = np.zeros((a, self.prefill_len), np.int32)
        plen = np.ones((a,), np.int32)
        new = np.ones((a,), np.int32)
        slots = np.full((a,), self.slots, np.int32)  # OOB = dropped
        seeds = np.zeros((a,), np.int32)
        snapshot = []
        for row, (entry, slot) in enumerate(batch):
            t = entry["tokens"]
            tokens[row, :t.shape[1]] = t[0]
            plen[row] = t.shape[1]
            new[row] = entry["new"]
            slots[row] = slot
            seeds[row] = entry["seed"]
            entry["scheduled"] = 1  # slot claimed at queue pop, locked
            snapshot.append((row, entry))
        # Chaos hook: sleep = slow admission; raise = device death at
        # prefill (propagates to _abort, every waiter resolved).
        faults.fire("engine.admit")
        if self._prefill_exec is None:
            self._prefill_exec = prefill_into_slot.lower(
                self.cfg, self.params, self._state, self.decode, tokens,
                plen, new, slots, seeds).compile()
        t0 = time.perf_counter()
        self._state, first = self._prefill_exec(
            self.params, self._state, tokens, plen, new, slots, seeds)
        dt = time.perf_counter() - t0
        self._pending.append((first, snapshot))
        with self._lock:
            self._counters["prefills"] += len(batch)
            # Prefill emits each request's first token, so its compute
            # belongs in busy_s — tokens_per_sec must not count tokens
            # whose cost was never measured (short-completion workloads
            # would otherwise read up to ~2x the real rate).
            self._counters["busy_s"] += dt

    def _finish(self, entry: dict) -> None:
        """Resolve a completed request: prompt + emitted tokens."""
        out = np.concatenate(
            [entry["tokens"],
             np.asarray(entry["emitted"], np.int32)[None]], axis=1)
        entry["out"] = {"tokens": out}
        entry["event"].set()

    def _drain_one(self) -> None:
        """Materialize the oldest pending emission and hand its tokens
        to their requests; retire + resolve the ones that completed.
        Counter merges are batched: one locked update per drained call,
        not per token."""
        arr, snapshot = self._pending.pop(0)
        host = np.asarray(arr)
        if host.ndim < 2:   # prefill emission: [A] first tokens, the
            host = host[None]   # snapshot's cols are admission rows
        emitted = 0
        finished = 0
        for row in host:           # fused calls carry [steps, slots]
            for col, entry in snapshot:
                if entry["event"].is_set() or len(entry["emitted"]) >= \
                        entry["new"]:
                    continue
                tok = int(row[col])
                entry["emitted"].append(tok)
                emitted += 1
                complete = len(entry["emitted"]) >= entry["new"] or (
                    self._eos and tok == self.decode.eos_token)
                if complete:
                    # The device `done` flag froze this slot at the
                    # same step, so freeing it here (possibly sync_lag
                    # calls late on the EOS path) never races the cache.
                    if self._slot_req[entry["slot"]] is entry:
                        self._slot_req[entry["slot"]] = None
                    self._finish(entry)
                    finished += 1
        with self._lock:
            self._counters["tokens"] += emitted
            self._counters["requests"] += finished
            self._counters["in_flight"] -= finished
        if emitted:
            self._tok_counter.inc(emitted, engine=self._metric_name)

    def _run(self) -> None:
        from kubeflow_tpu.models.generate import decode_step

        try:
            while True:
                with self._lock:
                    while (not self._queue
                           and all(r is None for r in self._slot_req)
                           and not self._pending and not self._stopped):
                        self._work.wait()
                    if self._stopped and not self._queue \
                            and all(r is None for r in self._slot_req) \
                            and not self._pending:
                        return
                    stopping = self._stopped
                    past_drain = (stopping and self._drain_deadline
                                  is not None and time.monotonic()
                                  > self._drain_deadline)
                    expired = self._sweep_expired_locked()
                    admissions = []
                    if not stopping:
                        free = self._free_slots_locked()
                        while free and self._queue:
                            entry = self._queue.pop(0)
                            slot = free.pop(0)
                            # Claim the slot and bump in_flight in the
                            # same locked section that pops the queue:
                            # stats() must never see queue_depth==0 AND
                            # in_flight_requests==0 while a request is
                            # live (monitors treat that as "drained"),
                            # and an entry registered here is reachable
                            # by _abort even if its prefill dispatch
                            # dies.
                            entry["slot"] = slot
                            self._slot_req[slot] = entry
                            self._counters["in_flight"] += 1
                            admissions.append((entry, slot))
                        self._set_queue_gauge(len(self._queue))
                self._fail_expired(expired)
                if past_drain:
                    self._abort(RuntimeError(
                        f"engine {self._metric_name!r} drain deadline "
                        "exceeded at close"))
                    return
                if stopping:
                    # Refuse queued work immediately; keep stepping only
                    # to drain in-flight slots.
                    self._fail_queue(BatcherClosed(
                        f"engine {self._metric_name!r} is closed"))
                for i in range(0, len(admissions), self.admit_width):
                    self._admit(admissions[i:i + self.admit_width])
                active = sum(r is not None for r in self._slot_req)
                self._set_occ_gauge(active)
                if active:
                    k = self.steps_per_call
                    # Build (one-time) OUTSIDE the timed window: the
                    # first per-token latency sample must not carry
                    # seconds of XLA compile into the p50/p95 stats and
                    # the step histogram.
                    if self._step_exec is None:
                        self._step_exec = decode_step.lower(
                            self.cfg, self.params, self._state,
                            self.decode, k).compile()
                    # Chaos hook: sleep = slow/wedged step (deadlines
                    # expire mid-generation); raise = device death.
                    # Outside the timed window so the injected stall
                    # does not masquerade as device latency in the
                    # step histogram.
                    faults.fire("engine.step")
                    t0 = time.perf_counter()
                    self._state, sampled = self._step_exec(
                        self.params, self._state)
                    self._pending.append((sampled, [
                        (i, r) for i, r in enumerate(self._slot_req)
                        if r is not None]))
                    # Deterministic retirement: with no EOS in play a
                    # request's completion step is known at dispatch —
                    # free the slot NOW so the next admission overlaps
                    # the lagged read instead of waiting for it.  The
                    # request stays visible in in_flight until its
                    # lagged emission is delivered.
                    for i, r in enumerate(self._slot_req):
                        if r is None:
                            continue
                        r["scheduled"] = min(r["new"],
                                             r["scheduled"] + k)
                        if not self._eos and r["scheduled"] >= r["new"]:
                            self._slot_req[i] = None
                    while len(self._pending) > self.sync_lag:
                        self._drain_one()
                    dt = time.perf_counter() - t0
                    per_step = dt / k
                    with self._lock:
                        self._counters["steps"] += k
                        self._counters["occupancy_sum"] += active * k
                        self._counters["busy_s"] += dt
                        self._step_times.append(per_step)
                        if len(self._step_times) > 4096:
                            del self._step_times[:2048]
                    self._step_hist.observe(per_step,
                                            engine=self._metric_name)
                else:
                    while self._pending:
                        self._drain_one()
                self._set_occ_gauge(
                    sum(r is not None for r in self._slot_req))
        except BaseException as exc:  # noqa: BLE001 — fail loudly to waiters
            self._abort(exc)

    def _fail_queue(self, exc: Exception) -> None:
        with self._lock:
            queued, self._queue = self._queue, []
            self._set_queue_gauge(0)
        for entry in queued:
            entry["err"] = exc
            entry["event"].set()

    def _abort(self, exc: BaseException) -> None:
        """Engine death: every waiter gets the error, nobody hangs."""
        with self._lock:
            self._stopped = True
            self._counters["in_flight"] = 0
        err = exc if isinstance(exc, Exception) else \
            RuntimeError(f"engine loop died: {exc!r}")
        self._fail_queue(err)
        # Fail live slots AND requests whose slots were already
        # deterministically retired but whose lagged emissions still sit
        # in _pending — those entries are in neither the queue nor the
        # slot table, and clearing _pending without resolving them would
        # leave their clients parked in submit() forever.
        for i, entry in enumerate(self._slot_req):
            if entry is not None and not entry["event"].is_set():
                entry["err"] = err
                entry["event"].set()
            self._slot_req[i] = None
        for _, snapshot in self._pending:
            for _, entry in snapshot:
                if not entry["event"].is_set():
                    entry["err"] = err
                    entry["event"].set()
        self._pending.clear()
        self._set_occ_gauge(0)
