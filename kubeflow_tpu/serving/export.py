"""Versioned model export — the SavedModel-equivalent for JAX models.

The reference served C++ ``tensorflow_model_server`` pointed at a
``--model_base_path`` of numbered SavedModel versions
(kubeflow/tf-serving/tf-serving.libsonnet:118-132); new versions dropped
into the directory are picked up live.  This module defines the TPU
framework's on-disk contract with the same shape:

    {base_path}/{version}/
        model.json       — loader spec: how to rebuild the predict fn
        params.msgpack   — flax-serialized variables

``model.json`` names a *loader* (an importable ``module:function``) plus a
config dict; the loader returns a callable ``predict(variables, inputs
dict) -> outputs dict``.  The framework ships loaders for its model
families (serving/loaders.py); user models register by exporting their own
loader path.  This replaces TF's graph serialization with the JAX-native
equivalent: code + weights, with jit/AOT compilation at load time.
"""

from __future__ import annotations

import importlib
import json
import os
import re
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

from flax import serialization

MODEL_FILE = "model.json"
PARAMS_FILE = "params.msgpack"
_VERSION_RE = re.compile(r"^\d+$")

# Loader resolution is allowlisted: model.json lives in the (possibly
# remote, writable-by-producers) model base path, so letting it name an
# arbitrary importable would hand code execution in the serving process
# to anyone who can write a model directory.  Only modules registered
# here — the framework's own loaders by default, plus explicit opt-ins
# via allow_loader_module() or the KFT_SERVING_LOADER_MODULES env var
# (comma-separated) — may be imported.
_ALLOWED_LOADER_MODULES = {"kubeflow_tpu.serving.loaders"}
_LOADER_REGISTRY: Dict[str, Callable] = {}


def register_loader(name: str, fn: Callable) -> None:
    """Register a loader callable under a plain name (no import at all)."""
    _LOADER_REGISTRY[name] = fn


def allow_loader_module(module: str) -> None:
    """Opt a module into 'module:function' loader resolution."""
    _ALLOWED_LOADER_MODULES.add(module)


def export(
    base_path: str | Path,
    version: int,
    variables: Any,
    loader: str,
    config: Optional[Dict[str, Any]] = None,
    signature: Optional[Dict[str, Any]] = None,
) -> Path:
    """Write one model version.  Atomic: builds in a temp dir then renames,
    so a half-written version is never visible to the watcher (the same
    guarantee SavedModel writers provide)."""
    base = Path(base_path)
    final = base / str(version)
    tmp = base / f".tmp-{version}"
    if final.exists():
        raise FileExistsError(f"version {version} already exists at {final}")
    tmp.mkdir(parents=True, exist_ok=True)
    # Unbox partitioning metadata (nn.Partitioned wrappers from
    # with_logical_partitioning): serialized boxes restore as plain
    # dicts, which loaders would then have to special-case.  Sharding at
    # serve time is the server's decision, not the artifact's.
    from flax import linen as nn

    (tmp / PARAMS_FILE).write_bytes(
        serialization.to_bytes(nn.unbox(variables)))
    (tmp / MODEL_FILE).write_text(json.dumps({
        "format": "kubeflow-tpu/1",
        "loader": loader,
        "config": config or {},
        "signature": signature or {},
    }, indent=2))
    tmp.rename(final)
    return final


def list_versions(base_path: str | Path) -> List[int]:
    base = Path(base_path)
    if not base.is_dir():
        return []
    out = []
    for child in base.iterdir():
        if child.is_dir() and _VERSION_RE.match(child.name) \
                and (child / MODEL_FILE).exists():
            out.append(int(child.name))
    return sorted(out)


def resolve_loader(path: str) -> Callable:
    """Registered name or allowlisted 'pkg.mod:fn' -> callable.

    model.json is producer-controlled data; resolution refuses modules
    outside the allowlist so a writable model path is not an arbitrary
    code-execution vector into the serving process.
    """
    if path in _LOADER_REGISTRY:
        return _LOADER_REGISTRY[path]
    mod_name, _, fn_name = path.partition(":")
    if not fn_name:
        raise ValueError(f"loader {path!r} must be 'module:function'")
    allowed = _ALLOWED_LOADER_MODULES | {
        m.strip() for m in os.environ.get(
            "KFT_SERVING_LOADER_MODULES", "").split(",") if m.strip()
    }
    if mod_name not in allowed:
        raise PermissionError(
            f"loader module {mod_name!r} is not allowlisted; register it "
            f"via register_loader()/allow_loader_module() or the "
            f"KFT_SERVING_LOADER_MODULES env var (allowed: {sorted(allowed)})"
        )
    return getattr(importlib.import_module(mod_name), fn_name)


def load_version(
    base_path: str | Path, version: int
) -> Tuple[Callable[[Dict[str, Any]], Dict[str, Any]], Dict[str, Any]]:
    """Rebuild (predict_fn, metadata) for one exported version.

    predict_fn takes/returns dicts of arrays — the serving server's only
    interface to the model.
    """
    vdir = Path(base_path) / str(version)
    spec = json.loads((vdir / MODEL_FILE).read_text())
    if spec.get("format") != "kubeflow-tpu/1":
        raise ValueError(f"unknown model format in {vdir}: {spec.get('format')}")
    loader = resolve_loader(spec["loader"])
    make_predict = loader(spec["config"])
    variables = serialization.msgpack_restore(
        (vdir / PARAMS_FILE).read_bytes()
    )
    predict = make_predict(variables)
    meta = {
        "loader": spec["loader"],
        "config": spec["config"],
        "signature": spec["signature"],
        "version": version,
    }
    return predict, meta
