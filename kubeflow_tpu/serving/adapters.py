"""Adapter-array multi-model serving: stacked per-tenant deltas.

One base model, thousands of per-tenant fine-tuned variants is the
millions-of-users reality — and one-model-per-ModelServer fragments the
fleet into per-model deployments that each under-fill a chip.  This
module applies HFTA's model-array trick (PAPERS.md, arXiv 2102.02344)
to INFERENCE: every variant is a LoRA-style low-rank delta over the
attention/MLP projections named by the PR 15 partition rules, and all
variants live in ONE stacked ``[n_adapters, layers, ...]`` array
resident beside the base params.  The step programs gather each slot's
delta by a per-slot int32 index (``state["adapter_ids"]``, armed at
prefill) — so requests for different variants ride ONE continuous
batch and ONE SPMD executable, and ``compiled_programs()`` never grows
a per-adapter entry.  Row 0 of the stack is the all-zero base delta:
base traffic co-batches with tenant traffic at identical math.

Device-side application lives in models/generate.py (``_lora`` and the
``_forward_with_cache`` gather); sharding of the stacked axis rides the
existing ``match_partition_rules`` machinery via the ``adapters/...``
rules in serving/sharding.py.  This module is the HOST side:

  AdapterRegistry   bounded slots, digest-verified load from disk, hot
                    load/evict behind the ``_ReloadBreaker`` discipline
                    (a corrupt adapter can't hot-loop; the last-good
                    revision keeps serving), LRU eviction of IDLE
                    adapters only — in-flight requests pin their
                    adapter's slot, so evict-under-pressure never
                    corrupts a running generation.

Wire form: clients address a variant as ``model@adapter`` (the HTTP
route name charset already admits ``@``); ModelServer splits the name,
the engine resolves it to an array index at admission — or sheds typed
404 (unknown adapter) / 429 (slots exhausted, breaker open).  KV is
adapter-SCOPED: the engine seeds each request's prefix-digest chain
with its adapter digest, so variants never alias each other's cached
pages (user_guide §5.11).
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kubeflow_tpu.serving.errors import Overloaded
from kubeflow_tpu.testing import faults

log = logging.getLogger(__name__)

# Metric constants (kft_engine_adapter_*): module-level names shared by
# the registry and the e2e assertions — divergent literals would mint a
# silent second series.
ADAPTER_LOADS_TOTAL = "kft_engine_adapter_loads_total"
ADAPTER_LOADS_HELP = "adapter (re)loads installed into the stack, by engine/adapter"
ADAPTER_LOAD_FAILURES_TOTAL = "kft_engine_adapter_load_failures_total"
ADAPTER_LOAD_FAILURES_HELP = "adapter load attempts that raised, by engine/adapter"
ADAPTER_EVICTIONS_TOTAL = "kft_engine_adapter_evictions_total"
ADAPTER_EVICTIONS_HELP = "idle adapters LRU-evicted from the stack, by engine"
ADAPTER_RESIDENT_GAUGE = "kft_engine_adapter_resident"
ADAPTER_RESIDENT_HELP = "adapters currently resident in the stack, by engine"


class AdapterNotFound(KeyError):
    """Unknown ``model@adapter`` name: no resident slot and no loadable
    artifact on disk.  Subclasses KeyError so both transports map it to
    the same 404 an unknown model name gets."""


def split_model_adapter(name: str) -> Tuple[str, Optional[str]]:
    """``"lm@tenant1"`` -> ``("lm", "tenant1")``; plain names pass
    through with adapter None.  The single parse site for the wire
    form — ModelServer and the fleet router both call this."""
    if "@" in name:
        base, _, adapter = name.partition("@")
        return base, (adapter or None)
    return name, None


def _factor_shapes(cfg, rank: int) -> Dict[str, Dict[str, tuple]]:
    """Per-projection low-rank factor shapes (without the adapter row
    axis), mirroring the base param tree: delta(W) = a @ b per
    projection, so the stacked arrays prepend [rows, layers] to
    these."""
    e, h, hkv = cfg.d_model, cfg.n_heads, cfg.n_kv_heads
    d, f, r = cfg.head_dim, cfg.d_ff, int(rank)
    return {
        "attn": {
            "wq_a": (e, r), "wq_b": (r, h, d),
            "wkv_a": (2, e, r), "wkv_b": (2, r, hkv, d),
            "wo_a": (h, d, r), "wo_b": (r, e),
        },
        "mlp": {
            "wi_a": (2, e, r), "wi_b": (2, r, f),
            "wo_a": (f, r), "wo_b": (r, e),
        },
    }


def init_adapter_stack(cfg, rows: int, rank: int, dtype=None):
    """Zeroed stacked delta arrays: ``[rows, layers, ...]`` per factor.
    Row 0 is the permanent base (zero-delta) row; rows 1..slots hold
    loaded tenants.  Shapes are fixed at construction, which is what
    lets hot load/evict mutate rows without recompiling any program."""
    if dtype is None:
        dtype = cfg.dtype
    L = cfg.n_layers
    return {
        grp: {k: np.zeros((rows, L) + shape, dtype)
              for k, shape in leaves.items()}
        for grp, leaves in _factor_shapes(cfg, rank).items()
    }


def random_adapter_factors(cfg, rank: int, seed: int,
                           scale: float = 0.05):
    """Deterministic per-layer random factors for one adapter (tests,
    benches, and the hermetic e2e fabricate tenants with these — a
    distinct seed is a distinct tenant)."""
    rng = np.random.default_rng(seed)
    L = cfg.n_layers
    return {
        grp: {k: (rng.standard_normal((L,) + shape) * scale
                  ).astype(np.float32)
              for k, shape in leaves.items()}
        for grp, leaves in _factor_shapes(cfg, rank).items()
    }


def _flatten(factors) -> Dict[str, np.ndarray]:
    return {f"{grp}/{k}": np.asarray(v, np.float32)
            for grp, leaves in factors.items()
            for k, v in leaves.items()}


def factors_digest(factors) -> str:
    """Content digest of a factor tree (stable across save/load):
    sha256 over the sorted flattened float32 leaves."""
    h = hashlib.sha256()
    for key, arr in sorted(_flatten(factors).items()):
        h.update(key.encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def save_adapter(path: str, factors) -> str:
    """Write one adapter artifact: ``<path>`` (npz of float32 factor
    leaves, '/'-joined keys) plus a ``<path>.json`` sidecar carrying
    the content digest the loader verifies.  Returns the digest."""
    flat = _flatten(factors)
    digest = factors_digest(factors)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **flat)
    os.replace(tmp, path)  # atomic-write discipline: never half a file
    with open(path + ".json", "w") as f:
        json.dump({"digest": digest}, f)
    return digest


def load_adapter(path: str, cfg, rank: int):
    """Digest-verified load: returns ``(factors, digest)`` or raises
    ValueError on a digest mismatch / wrong-shape artifact (the
    registry's breaker turns that into a bounded-backoff open, not a
    hot loop)."""
    with np.load(path) as data:
        flat = {k: np.asarray(data[k]) for k in data.files}
    factors: Dict[str, Dict[str, np.ndarray]] = {}
    for key, arr in flat.items():
        grp, _, leaf = key.partition("/")
        factors.setdefault(grp, {})[leaf] = arr
    want = _factor_shapes(cfg, rank)
    for grp, leaves in want.items():
        for k, shape in leaves.items():
            got = factors.get(grp, {}).get(k)
            if got is None or got.shape != (cfg.n_layers,) + shape:
                raise ValueError(
                    f"adapter artifact {path!r} missing/misshaped "
                    f"factor {grp}/{k} (want "
                    f"{(cfg.n_layers,) + shape}, got "
                    f"{None if got is None else got.shape})")
    digest = factors_digest(factors)
    sidecar = path + ".json"
    if os.path.exists(sidecar):
        with open(sidecar) as f:
            expect = json.load(f).get("digest")
        if expect and expect != digest:
            raise ValueError(
                f"adapter artifact {path!r} digest mismatch: sidecar "
                f"{expect[:12]} != content {digest[:12]} (corrupt or "
                f"torn write)")
    return factors, digest


class AdapterRegistry:
    """Bounded-slot host registry over the stacked delta arrays.

    ``slots`` tenants max beside the permanent base row 0.  Resolution
    is load-on-demand: the first admission naming an adapter loads it
    from ``directory/<name>.npz`` (digest-verified) into a free slot —
    or LRU-evicts an IDLE one (pins == 0; in-flight requests pin their
    slot from admission to release).  A changed on-disk digest
    hot-reloads in place behind a per-adapter ``_ReloadBreaker``: a
    corrupt artifact opens the breaker for a jittered exponential
    backoff during which the last-good revision keeps serving (or, for
    a never-loaded name, admissions shed typed 429 until it expires).

    Mutations are copy-on-write (a load/evict replaces whole leaf
    arrays) and bump ``version``; the engine loop applies pending
    versions between program dispatches via ``stack_snapshot()``, so a
    program never reads a torn row.  Thread-safe; the engine calls
    ``acquire``/``release`` from transport threads and
    ``stack_snapshot`` from its loop thread.
    """

    def __init__(self, cfg, *, slots: int = 8, rank: int = 4,
                 directory: Optional[str] = None, dtype=None,
                 name: str = "engine",
                 breaker_base_s: float = 0.5,
                 breaker_cap_s: float = 60.0,
                 overload_retry_after_s: float = 1.0):
        if slots < 1:
            raise ValueError(f"slots must be >= 1, got {slots}")
        self.cfg = cfg
        self.slots = int(slots)
        self.rank = int(rank)
        self.directory = directory
        self.name = name
        self._dtype = dtype if dtype is not None else cfg.dtype
        self._retry_after_s = float(overload_retry_after_s)
        self._breaker_base_s = breaker_base_s
        self._breaker_cap_s = breaker_cap_s
        self._stack = init_adapter_stack(cfg, self.slots + 1, self.rank,
                                         self._dtype)
        self._lock = threading.Lock()
        self._residents: Dict[str, Dict[str, Any]] = {}
        self._by_index: Dict[int, Dict[str, Any]] = {}
        self._free: List[int] = list(range(1, self.slots + 1))
        self._breakers: Dict[str, Any] = {}
        self._digest_cache: Dict[str, Tuple[Tuple[float, int], str]] = {}
        self._seq = 0
        self.version = 0

    # -- stack access (engine loop) ---------------------------------------

    def stack_snapshot(self):
        """(stack tree, version) — leaves are never mutated in place,
        so the engine may device_put these refs without copying."""
        with self._lock:
            return self._stack, self.version

    # -- resolution (transport threads) -----------------------------------

    def acquire(self, name: str) -> Tuple[int, str]:
        """Resolve ``name`` to ``(row index, content digest)`` and PIN
        the slot until ``release(index)``.  Loads/reloads from disk as
        needed; sheds AdapterNotFound (404) for unknown names and
        Overloaded (429) when every slot is pinned or the load breaker
        is open with no last-good revision."""
        with self._lock:
            res = self._residents.get(name)
            path = self._path(name)
            want: Optional[str] = None
            if path is not None and os.path.exists(path):
                try:
                    want = self._file_digest_locked(name, path)
                except OSError:
                    want = None
            if res is not None and (want is None
                                    or want == res["digest"]):
                return self._pin_locked(res)
            if want is None:
                if res is not None:
                    # Artifact vanished: the resident revision keeps
                    # serving (eviction under live pins would be worse).
                    return self._pin_locked(res)
                raise AdapterNotFound(
                    f"adapter {name!r} is not resident and has no "
                    f"artifact under {self.directory!r}")
            breaker = self._breaker_locked(name)
            if not breaker.allow(want):
                if res is not None:
                    return self._pin_locked(res)  # last-good serves
                raise Overloaded(
                    f"adapter {name!r} load breaker open "
                    f"(artifact {want[:12]} failed "
                    f"{breaker.failures}x)",
                    retry_after_s=max(
                        self._retry_after_s,
                        breaker.open_until - faults.monotonic()))
            try:
                faults.fire("adapter.load")
                factors, digest = load_adapter(path, self.cfg,
                                               self.rank)
            except Exception as exc:
                breaker.record_failure(want)
                self._counter(
                    ADAPTER_LOAD_FAILURES_TOTAL,
                    ADAPTER_LOAD_FAILURES_HELP).inc(
                        engine=self.name, adapter=name)
                if res is not None:
                    log.warning(
                        "adapter %r reload failed (%s); breaker open, "
                        "last-good %s keeps serving", name, exc,
                        res["digest"][:12])
                    return self._pin_locked(res)
                raise Overloaded(
                    f"adapter {name!r} failed to load: {exc}",
                    retry_after_s=self._retry_after_s)
            breaker.record_success()
            self._install_locked(name, factors, digest, reuse=res)
            return self._pin_locked(self._residents[name])

    def release(self, index: int) -> None:
        with self._lock:
            res = self._by_index.get(index)
            if res is not None and res["pins"] > 0:
                res["pins"] -= 1

    def put(self, name: str, factors, digest: Optional[str] = None
            ) -> int:
        """Install ``factors`` for ``name`` directly (no disk) — the
        in-memory load path tests and benches use.  Returns the row
        index."""
        with self._lock:
            if digest is None:
                digest = factors_digest(factors)
            self._install_locked(name, factors, digest,
                                 reuse=self._residents.get(name))
            return self._residents[name]["index"]

    def salt(self, index: int) -> bytes:
        """Prefix-digest chain salt for a resolved adapter row: the
        content digest's bytes (stable across replicas, unlike the row
        index), empty for the base row — KV pages are adapter-scoped
        so variants never alias each other's cache (§5.11)."""
        if index == 0:
            return b""
        with self._lock:
            res = self._by_index.get(index)
            return bytes.fromhex(res["digest"]) if res else b""

    def loaded(self) -> List[Dict[str, Any]]:
        """Resident adapters for /readyz advertisement and stats."""
        with self._lock:
            return [{"name": r["name"], "digest": r["digest"],
                     "index": r["index"], "pins": r["pins"]}
                    for r in sorted(self._by_index.values(),
                                    key=lambda r: r["index"])]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "adapter_slots": self.slots,
                "adapter_rank": self.rank,
                "adapters_resident": len(self._residents),
                "adapters_pinned": sum(
                    1 for r in self._residents.values()
                    if r["pins"] > 0),
            }

    # -- internals (all under self._lock) ---------------------------------

    def _path(self, name: str) -> Optional[str]:
        if self.directory is None:
            return None
        # Tenant names come off the wire: refuse separators so a name
        # can never path-traverse out of the adapter directory.
        if not name or "/" in name or "\\" in name or ".." in name:
            raise AdapterNotFound(f"invalid adapter name {name!r}")
        return os.path.join(self.directory, name + ".npz")

    def _file_digest_locked(self, name: str, path: str) -> str:
        """Sidecar digest when present (cheap), else content hash of
        the npz cached by (mtime, size) — acquire() runs per admission
        and must not re-hash an unchanged artifact every request."""
        sidecar = path + ".json"
        if os.path.exists(sidecar):
            with open(sidecar) as f:
                digest = json.load(f).get("digest")
            if digest:
                return str(digest)
        st = os.stat(path)
        key = (st.st_mtime, st.st_size)
        cached = self._digest_cache.get(name)
        if cached is not None and cached[0] == key:
            return cached[1]
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        digest = h.hexdigest()
        self._digest_cache[name] = (key, digest)
        return digest

    def _breaker_locked(self, name: str):
        breaker = self._breakers.get(name)
        if breaker is None:
            from kubeflow_tpu.serving.model_server import _ReloadBreaker

            breaker = self._breakers[name] = _ReloadBreaker(
                self._breaker_base_s, self._breaker_cap_s)
        return breaker

    def _pin_locked(self, res) -> Tuple[int, str]:
        res["pins"] += 1
        res["last_used"] = self._seq
        self._seq += 1
        return res["index"], res["digest"]

    def _install_locked(self, name, factors, digest, reuse=None):
        if reuse is not None:
            index = reuse["index"]
        elif self._free:
            index = self._free.pop(0)
        else:
            index = self._evict_lru_locked()
        self._write_row_locked(index, factors)
        res = {"name": name, "index": index, "digest": digest,
               "pins": reuse["pins"] if reuse is not None else 0,
               "last_used": self._seq}
        self._seq += 1
        self._residents[name] = res
        self._by_index[index] = res
        self._counter(ADAPTER_LOADS_TOTAL, ADAPTER_LOADS_HELP).inc(
            engine=self.name, adapter=name)
        self._gauge().set(len(self._residents), engine=self.name)
        log.info("adapter %r -> slot %d (digest %s)", name, index,
                 digest[:12])

    def _evict_lru_locked(self) -> int:
        """Free the least-recently-used IDLE slot; every pinned slot
        belongs to an in-flight request and is untouchable — all
        pinned means the stack is genuinely full (typed 429)."""
        idle = [r for r in self._residents.values() if r["pins"] == 0]
        if not idle:
            raise Overloaded(
                f"all {self.slots} adapter slots pinned by in-flight "
                f"requests", retry_after_s=self._retry_after_s)
        victim = min(idle, key=lambda r: r["last_used"])
        faults.fire("adapter.evict")
        index = victim["index"]
        self._zero_row_locked(index)
        del self._residents[victim["name"]]
        del self._by_index[index]
        self._counter(ADAPTER_EVICTIONS_TOTAL,
                      ADAPTER_EVICTIONS_HELP).inc(engine=self.name)
        self._gauge().set(len(self._residents), engine=self.name)
        log.info("adapter %r LRU-evicted from slot %d",
                 victim["name"], index)
        return index

    def _write_row_locked(self, index: int, factors) -> None:
        # Copy-on-write: programs in flight keep reading the old leaf
        # arrays; the engine loop picks the new tree up at the next
        # version check, between dispatches.
        new_stack = {}
        for grp, leaves in self._stack.items():
            new_stack[grp] = {}
            for k, arr in leaves.items():
                arr = np.array(arr)
                arr[index] = np.asarray(factors[grp][k]).astype(
                    arr.dtype)
                new_stack[grp][k] = arr
        self._stack = new_stack
        self.version += 1

    def _zero_row_locked(self, index: int) -> None:
        new_stack = {}
        for grp, leaves in self._stack.items():
            new_stack[grp] = {}
            for k, arr in leaves.items():
                arr = np.array(arr)
                arr[index] = 0
                new_stack[grp][k] = arr
        self._stack = new_stack
        self.version += 1

    @staticmethod
    def _counter(name, help_):
        from kubeflow_tpu.runtime.prom import REGISTRY

        return REGISTRY.counter(name, help_)

    @staticmethod
    def _gauge():
        from kubeflow_tpu.runtime.prom import REGISTRY

        return REGISTRY.gauge(ADAPTER_RESIDENT_GAUGE,
                              ADAPTER_RESIDENT_HELP)
