"""Built-in loaders: rebuild predict functions from exported configs.

A loader is ``fn(config) -> (variables -> predict)`` where predict maps
{input_name: array} -> {output_name: array}.  Loader paths are recorded in
model.json at export time (serving/export.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp


def classifier(config: Dict[str, Any]) -> Callable:
    """Image classifier over models/resnet.py or models/inception.py.

    config: {"family": "resnet50"|"inception_v3"|..., "num_classes": int}
    Signature: {"image": [b, h, w, 3] float32 or uint8} ->
               {"scores": [b, classes], "classes": [b, top_k]}

    Wire dtype is preserved on the host->device hop and converted on
    device: uint8 images (the reference's raw-image-bytes contract,
    components/k8s-model-server/inception-client/label.py) are scaled to
    [0, 1] inside the jitted forward — a quarter of the transfer bytes
    of a host-side float32 cast, which matters when the host link, not
    the MXU, bounds serving throughput.  float64/int64 (numpy's default
    from JSON lists) are narrowed host-side for the same reason.
    """
    family = config.get("family", "resnet50")
    num_classes = int(config.get("num_classes", 1000))
    top_k = min(int(config.get("top_k", 5)), num_classes)
    if family.startswith("resnet"):
        from kubeflow_tpu.models.resnet import ResNetConfig

        factory = ResNetConfig._FACTORIES.get(family)
        if factory is None:
            raise ValueError(f"unknown resnet family {family!r}")
        model = factory(
            num_classes=num_classes,
            num_filters=int(config.get("num_filters", 64)),
        )
    elif family == "inception_v3":
        from kubeflow_tpu.models.inception import InceptionV3

        model = InceptionV3(num_classes=num_classes)
    else:
        raise ValueError(f"unknown classifier family {family!r}")

    def make_predict(variables):
        @jax.jit
        def fwd(image):
            # dtype is trace-static: one compile per wire dtype.
            if image.dtype == jnp.uint8:
                image = image.astype(jnp.float32) / 255.0
            else:
                image = image.astype(jnp.float32)
            logits = model.apply(variables, image, train=False)
            probs = jax.nn.softmax(logits, axis=-1)
            top = jax.lax.top_k(probs, top_k)
            return probs, top

        def predict(inputs: Dict[str, Any]) -> Dict[str, Any]:
            import numpy as np

            image = inputs["image"]
            if isinstance(image, jax.Array):
                # Already device-resident (pipelined in-process callers):
                # never round-trip it through host numpy.
                pass
            else:
                image = np.asarray(image)
                if image.dtype == np.float64:
                    image = image.astype(np.float32)
                elif image.dtype.kind in "iu" and image.dtype != np.uint8:
                    # JSON integer pixels: ship as uint8 when they fit
                    # the 0..255 image range, else as float32.
                    if (image.size and 0 <= image.min()
                            and image.max() <= 255):
                        image = image.astype(np.uint8)
                    else:
                        image = image.astype(np.float32)
            if image.ndim == 3:
                image = image[None]
            probs, (top_p, top_i) = fwd(image)
            return {
                "scores": probs,
                "top_k_scores": top_p,
                "top_k_classes": top_i,
            }

        return predict

    return make_predict


def _model_config(overrides: Dict[str, Any]):
    """TransformerConfig from JSON-safe overrides (model.json carries
    dtype as a string, e.g. "float32"/"bfloat16")."""
    from kubeflow_tpu.models.transformer import TransformerConfig

    overrides = dict(overrides)
    if isinstance(overrides.get("dtype"), str):
        overrides["dtype"] = jnp.dtype(overrides["dtype"])
    return TransformerConfig(**overrides)


def lm_generate(config: Dict[str, Any]) -> Callable:
    """Autoregressive generation loader.

    config: {"model": TransformerConfig overrides,
             "max_new_tokens": int, "temperature": float,
             "top_k": int (0 = off), "top_p": float (1.0 = off),
             "quantize": "int8" (optional, weight-only),
             "kv_cache": "int8" (optional, quantized decode cache)}

    Sampling is deterministic per request (fixed seed): identical
    prompts return identical completions, the reproducibility contract
    a versioned model server wants.
    Signature: {"tokens": [b, t] int32} -> {"tokens": [b, t+new] int32}
    """
    from kubeflow_tpu.models.generate import DecodeConfig, generate

    cfg = _model_config(config.get("model", {}))
    kv_cache = config.get("kv_cache")
    if kv_cache not in (None, "int8"):
        raise ValueError(f"unknown kv_cache mode {kv_cache!r}")
    decode = DecodeConfig(
        max_new_tokens=int(config.get("max_new_tokens", 64)),
        temperature=float(config.get("temperature", 0.0)),
        top_k=int(config.get("top_k", 0)),
        top_p=float(config.get("top_p", 1.0)),
        eos_token=int(config.get("eos_token", -1)),
        kv_cache_dtype=kv_cache or "model",
    )
    quantize = config.get("quantize")
    if quantize not in (None, "int8"):
        raise ValueError(f"unknown quantize mode {quantize!r}")

    def make_predict(variables):
        # Stage weights into HBM ONCE at load.  They are an argument to
        # the jitted generate (not a closure constant), and jit
        # re-transfers host-numpy arguments on every call — measured as
        # ~40 s/request for a 188M model through the bench harness's
        # slow host link vs ~0.1 ms/token with resident params.
        # Weight-only int8 quantization happens host-side BEFORE the
        # staging transfer (fewer bytes over the link, fewer HBM reads
        # per decoded token; ops/quantize.py).  Without it, matmul
        # weights are narrowed to the model compute dtype at staging:
        # checkpoints carry float32 masters, and serving float32 would
        # double every per-token weight read just to feed casts the
        # matmuls do anyway.  1D params (norm scales) stay float32 —
        # byte-free and precision-relevant.
        params = variables["params"]
        if quantize == "int8":
            from kubeflow_tpu.ops.quantize import quantize_params

            params = quantize_params(params)
        else:
            from kubeflow_tpu.ops.quantize import narrow_params

            params = narrow_params(params, cfg.dtype)
        params = jax.device_put(params)

        def predict(inputs: Dict[str, Any]) -> Dict[str, Any]:
            tokens = jnp.asarray(inputs["tokens"], jnp.int32)
            sd = inputs.get("seed")
            # Same sampling-seed contract as the DecodeEngine: a seeded
            # request falling back to this path (prompt too wide for
            # the engine, or engine disabled) must not silently sample
            # from the fixed default stream.  One seed per CALL — the
            # BucketedLMBatcher declines seeded requests so they arrive
            # here unbatched.
            rng = None
            if sd is not None:
                rng = jax.random.PRNGKey(
                    int(jnp.asarray(sd).reshape(-1)[0]))
            plen = inputs.get("prompt_len")
            if plen is not None:
                # Left-padded bucketed batch (BucketedLMBatcher): rows
                # decode at their real lengths; pad keys are masked.
                plen = jnp.asarray(plen, jnp.int32).reshape(-1)
                out, _ = generate(cfg, params, tokens, decode,
                                  rng=rng, prompt_len=plen)
            else:
                out, _ = generate(cfg, params, tokens, decode, rng=rng)
            req = inputs.get("max_new_tokens")
            if req is not None:
                # Per-request completion budget, same contract as the
                # DecodeEngine: a prompt that falls back to this path
                # (too wide for the engine's prefill width, or the
                # engine disabled) must not silently get the config's
                # full budget instead.  generate() still decodes the
                # full program; only the surplus is trimmed.  The
                # output array is rectangular, so a MULTI-row direct
                # call trims every row to the batch's LARGEST budget
                # (rows asking for less still get at least what they
                # asked); per-row budgets need per-row calls or the
                # engine/batcher paths.
                lim = int(jnp.max(jnp.asarray(req)))
                lim = max(1, min(lim, decode.max_new_tokens))
                out = out[:, : tokens.shape[1] + lim]
            return {"tokens": out}

        # Continuous-batching hook: the DecodeEngine (serving/engine.py)
        # needs the model itself — config, HBM-staged params, decode
        # settings — not a predict closure.  Exposing them here lets the
        # serving entrypoint build the engine around every hot-swapped
        # version exactly as it rebuilds batchers.
        predict.engine_spec = {"cfg": cfg, "params": params,
                               "decode": decode}
        return predict

    return make_predict


def lm(config: Dict[str, Any]) -> Callable:
    """Transformer LM loader: next-token logits for a token batch.

    config: TransformerConfig field overrides.
    Signature: {"tokens": [b, s] int32} -> {"logits": [b, s, vocab]}
    """
    from kubeflow_tpu.models.transformer import Transformer

    cfg = _model_config(config)
    model = Transformer(cfg)

    def make_predict(variables):
        from kubeflow_tpu.ops.quantize import narrow_params

        # Stage weights to HBM once, narrowed to the compute dtype —
        # the same treatment lm_generate got: raw orbax-restored numpy
        # leaves passed into jit are re-uploaded per call, and numpy
        # embedding tables cannot be fancy-indexed by a tracer at all
        # (the bf16 path crashed before any perf question arose).
        params = jax.device_put(
            narrow_params(variables["params"], cfg.dtype))

        @jax.jit
        def fwd(params, tokens):
            # Params are a jit ARGUMENT (not a closure constant —
            # closed-over arrays can be baked into the executable as a
            # second resident copy; lm_generate passes them the same
            # way).  Full-precision logits on the wire regardless of
            # the model's ce_dtype (a training-loss knob that changes
            # the forward's output dtype; irrelevant to serving).
            return model.apply(
                {"params": params}, tokens).astype(jnp.float32)

        def predict(inputs: Dict[str, Any]) -> Dict[str, Any]:
            tokens = jnp.asarray(inputs["tokens"], jnp.int32)
            return {"logits": fwd(params, tokens)}

        return predict

    return make_predict
