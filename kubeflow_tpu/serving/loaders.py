"""Built-in loaders: rebuild predict functions from exported configs.

A loader is ``fn(config) -> (variables -> predict)`` where predict maps
{input_name: array} -> {output_name: array}.  Loader paths are recorded in
model.json at export time (serving/export.py).
"""

from __future__ import annotations

from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp


def classifier(config: Dict[str, Any]) -> Callable:
    """Image classifier over models/resnet.py or models/inception.py.

    config: {"family": "resnet50"|"inception_v3"|..., "num_classes": int}
    Signature: {"image": [b, h, w, 3] float32} ->
               {"scores": [b, classes], "classes": [b, top_k]}
    """
    family = config.get("family", "resnet50")
    num_classes = int(config.get("num_classes", 1000))
    top_k = min(int(config.get("top_k", 5)), num_classes)
    if family.startswith("resnet"):
        from kubeflow_tpu.models.resnet import ResNetConfig

        factory = ResNetConfig._FACTORIES.get(family)
        if factory is None:
            raise ValueError(f"unknown resnet family {family!r}")
        model = factory(
            num_classes=num_classes,
            num_filters=int(config.get("num_filters", 64)),
        )
    elif family == "inception_v3":
        from kubeflow_tpu.models.inception import InceptionV3

        model = InceptionV3(num_classes=num_classes)
    else:
        raise ValueError(f"unknown classifier family {family!r}")

    def make_predict(variables):
        @jax.jit
        def fwd(image):
            logits = model.apply(variables, image, train=False)
            probs = jax.nn.softmax(logits, axis=-1)
            top = jax.lax.top_k(probs, top_k)
            return probs, top

        def predict(inputs: Dict[str, Any]) -> Dict[str, Any]:
            image = jnp.asarray(inputs["image"], jnp.float32)
            if image.ndim == 3:
                image = image[None]
            probs, (top_p, top_i) = fwd(image)
            return {
                "scores": probs,
                "top_k_scores": top_p,
                "top_k_classes": top_i,
            }

        return predict

    return make_predict


def lm_generate(config: Dict[str, Any]) -> Callable:
    """Autoregressive generation loader.

    config: {"model": TransformerConfig overrides,
             "max_new_tokens": int, "temperature": float}
    Signature: {"tokens": [b, t] int32} -> {"tokens": [b, t+new] int32}
    """
    from kubeflow_tpu.models.generate import DecodeConfig, generate
    from kubeflow_tpu.models.transformer import TransformerConfig

    cfg = TransformerConfig(**config.get("model", {}))
    decode = DecodeConfig(
        max_new_tokens=int(config.get("max_new_tokens", 64)),
        temperature=float(config.get("temperature", 0.0)),
        eos_token=int(config.get("eos_token", -1)),
    )

    def make_predict(variables):
        params = variables["params"]

        def predict(inputs: Dict[str, Any]) -> Dict[str, Any]:
            tokens = jnp.asarray(inputs["tokens"], jnp.int32)
            out, _ = generate(cfg, params, tokens, decode)
            return {"tokens": out}

        return predict

    return make_predict


def lm(config: Dict[str, Any]) -> Callable:
    """Transformer LM loader: next-token logits for a token batch.

    config: TransformerConfig field overrides.
    Signature: {"tokens": [b, s] int32} -> {"logits": [b, s, vocab]}
    """
    from kubeflow_tpu.models.transformer import Transformer, TransformerConfig

    cfg = TransformerConfig(**config)
    model = Transformer(cfg)

    def make_predict(variables):
        @jax.jit
        def fwd(tokens):
            return model.apply(variables, tokens)

        def predict(inputs: Dict[str, Any]) -> Dict[str, Any]:
            tokens = jnp.asarray(inputs["tokens"], jnp.int32)
            return {"logits": fwd(tokens)}

        return predict

    return make_predict
