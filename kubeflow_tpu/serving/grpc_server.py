"""gRPC PredictionService — the reference's primary serving wire contract.

The reference exposed C++ TF-Serving's gRPC PredictionService on :9000
(kubeflow/tf-serving/tf-serving.libsonnet:118-132) with the REST proxy in
front; here the same split: serving/http.py is the REST face, this module
the gRPC face, both over one ModelServer.

Service stubs are hand-rolled with grpc's generic-handler API (the image
has protoc for messages but no grpc codegen plugin); the method table
mirrors protos/prediction.proto.
"""

from __future__ import annotations

import json
import logging
from concurrent import futures
from typing import Optional

import grpc
import numpy as np

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.serving.errors import DeadlineExceeded, Overloaded
from kubeflow_tpu.serving.model_server import ModelServer
from kubeflow_tpu.serving.protos import prediction_pb2 as pb
from kubeflow_tpu.testing import faults

log = logging.getLogger(__name__)


def _deadline_from(context: grpc.ServicerContext):
    """Client-supplied gRPC deadline -> the absolute policy-clock
    instant the batching planes enforce.  gRPC carries deadlines in the
    transport (grpc-timeout header), so unlike REST no body field is
    needed — whatever deadline the client set on the call propagates
    into queues and the engine's mid-generation sweep."""
    remaining = context.time_remaining()
    if remaining is None:
        return None
    return faults.monotonic() + remaining

SERVICE = "kft.serving.PredictionService"
GRPC_PORT = 9000  # same port the reference's model server bound

# Idempotency key metadata (the gRPC analogue of the REST header):
# retried calls carrying the same key are answered from the model
# server's dedup cache instead of re-executing (docs §5.6).
IDEMPOTENCY_METADATA = "x-kft-idempotency-key"


def _idem_key_from(context: grpc.ServicerContext):
    for key, value in (context.invocation_metadata() or ()):
        if key == IDEMPOTENCY_METADATA:
            return value
    return None

# grpc.health.v1 readiness parity (the standard Health service wire
# contract, hand-rolled like the rest of this module — the image has no
# grpc_health codegen).  Check mirrors /readyz: SERVING while models
# are loaded and the server is not draining, NOT_SERVING during a
# SIGTERM drain — so the fleet router can probe gRPC-only replicas with
# any stock health checker.  The answer is server-wide (one serving
# process = one readiness), whatever ``service`` name the request asks
# about; mirroring /readyz exactly is the point.
HEALTH_SERVICE = "grpc.health.v1.Health"
HEALTH_SERVING = 1      # HealthCheckResponse.ServingStatus.SERVING
HEALTH_NOT_SERVING = 2  # HealthCheckResponse.ServingStatus.NOT_SERVING


def _health_response(status: int) -> bytes:
    """Serialize HealthCheckResponse{status}: field 1 varint (single
    byte for the two statuses this server emits)."""
    return bytes([0x08, status])


def _health_status(data: bytes) -> int:
    """Parse HealthCheckResponse bytes back to the status enum (client
    side of the same hand-rolled contract)."""
    if len(data) >= 2 and data[0] == 0x08:
        return data[1]
    return 0  # UNKNOWN (empty message = all defaults)


def tensor_to_numpy(t: pb.Tensor) -> np.ndarray:
    return np.frombuffer(t.data, dtype=np.dtype(t.dtype)).reshape(
        tuple(t.shape))


def numpy_to_tensor(arr: np.ndarray) -> pb.Tensor:
    arr = np.ascontiguousarray(arr)
    return pb.Tensor(dtype=str(arr.dtype), shape=list(arr.shape),
                     data=arr.tobytes())


class PredictionServicer:
    def __init__(self, server: ModelServer):
        self.server = server

    def _resolve(self, spec: pb.ModelSpec):
        version = spec.version if spec.version > 0 else None
        return self.server.get(spec.name, version)

    def Predict(self, request: pb.PredictRequest,
                context: grpc.ServicerContext) -> pb.PredictResponse:
        model = self._resolve(request.model_spec)
        inputs = {k: tensor_to_numpy(t) for k, t in request.inputs.items()}
        # Through ModelServer.predict (not model.predict) so request
        # batching (enable_batching) applies to gRPC traffic exactly as
        # it does to REST.
        version = request.model_spec.version \
            if request.model_spec.version > 0 else None
        outputs = self.server.predict(model.name, inputs, version,
                                      deadline=_deadline_from(context),
                                      idem_key=_idem_key_from(context))
        resp = pb.PredictResponse()
        resp.model_spec.name = model.name
        resp.model_spec.version = model.version
        for key, value in outputs.items():
            resp.outputs[key].CopyFrom(numpy_to_tensor(np.asarray(value)))
        return resp

    def Classify(self, request: pb.ClassifyRequest,
                 context: grpc.ServicerContext) -> pb.ClassifyResponse:
        model = self._resolve(request.model_spec)
        inputs = {k: tensor_to_numpy(t) for k, t in request.inputs.items()}
        version = request.model_spec.version \
            if request.model_spec.version > 0 else None
        outputs = {k: np.asarray(v) for k, v in
                   self.server.predict(
                       model.name, inputs, version,
                       deadline=_deadline_from(context),
                       idem_key=_idem_key_from(context)).items()}
        resp = pb.ClassifyResponse()
        resp.model_spec.name = model.name
        resp.model_spec.version = model.version
        if "top_k_classes" in outputs:
            classes, scores = outputs["top_k_classes"], outputs["top_k_scores"]
        else:
            scores = outputs["scores"]
            k = request.top_k or scores.shape[-1]
            idx = np.argsort(-scores, axis=-1)[:, :k]
            classes = idx
            scores = np.take_along_axis(scores, idx, axis=-1)
        for row_c, row_s in zip(classes, scores):
            result = resp.results.add()
            result.classes.extend(str(c) for c in row_c)
            result.scores.extend(float(s) for s in row_s)
        return resp

    def GetModelMetadata(
        self, request: pb.GetModelMetadataRequest,
        context: grpc.ServicerContext,
    ) -> pb.GetModelMetadataResponse:
        model = self._resolve(request.model_spec)
        resp = pb.GetModelMetadataResponse()
        resp.model_spec.name = model.name
        resp.model_spec.version = model.version
        meta = dict(model.meta)
        # Live batching-plane stats ride the metadata face (the REST
        # side serves the same snapshot on /model/<name>:stats) — gRPC
        # clients monitoring engine occupancy need no extra RPC.
        batcher_stats = self.server.batcher_stats(model.name)
        if batcher_stats is not None:
            meta["batcher_stats"] = batcher_stats
        resp.metadata_json = json.dumps(meta)
        return resp


_METHODS = {
    "Predict": (pb.PredictRequest, pb.PredictResponse),
    "Classify": (pb.ClassifyRequest, pb.ClassifyResponse),
    "GetModelMetadata": (pb.GetModelMetadataRequest,
                         pb.GetModelMetadataResponse),
}


def _wrap(servicer: PredictionServicer, name: str):
    method = getattr(servicer, name)
    route = f"grpc_{name.lower()}"

    def handler(request, context):
        # Every method counted + timed centrally (the REST face records
        # the same series); only KNOWN model names become label values —
        # client-supplied names must not grow /metrics series.
        import time as _time

        from kubeflow_tpu.runtime.prom import REGISTRY
        from kubeflow_tpu.serving.model_server import (
            LATENCY_HELP,
            LATENCY_SECONDS,
            REQUESTS_HELP,
            REQUESTS_TOTAL,
        )

        spec_name = request.model_spec.name
        model_label = spec_name \
            if servicer.server.has_model(spec_name) else "_unknown_"
        # gRPC carries trace context in invocation metadata (the
        # transport's header analogue); the server span mirrors the
        # REST face's and feeds the same tail-sampled store.
        parent = None
        if tracing.enabled():
            parent = tracing.extract(
                dict(context.invocation_metadata() or ()))
        span = tracing.start_span(
            f"server.{route}", parent=parent,
            attrs={"model": model_label, "transport": "grpc"})
        # `outcome` keeps the metric vocabulary; `span_status` names
        # client faults so a 404/400 answer samples like ok traffic
        # instead of riding tail sampling's always-keep error tier.
        outcome = span_status = "error"
        t0 = _time.perf_counter()
        try:
            with tracing.use_span(span):
                resp = method(request, context)
            outcome = span_status = "ok"
            return resp
        except KeyError as e:
            span_status = "not_found"
            context.abort(grpc.StatusCode.NOT_FOUND, str(e))
        except ValueError as e:
            span_status = "invalid_argument"
            context.abort(grpc.StatusCode.INVALID_ARGUMENT, str(e))
        except Overloaded as e:
            # Same status pair as the REST face's 429/504: one failure
            # semantics across transports.  The Retry-After hint rides
            # STRUCTURED trailing metadata (the gRPC analogue of the
            # REST header) — clients must not parse prose.
            outcome = span_status = "shed"
            context.set_trailing_metadata(
                (("retry-after", f"{e.retry_after_s}"),))
            context.abort(
                grpc.StatusCode.RESOURCE_EXHAUSTED,
                f"{e} (retry after {e.retry_after_s:.1f}s)")
        except DeadlineExceeded as e:
            outcome = span_status = "deadline_exceeded"
            context.abort(grpc.StatusCode.DEADLINE_EXCEEDED, str(e))
        finally:
            REGISTRY.counter(REQUESTS_TOTAL, REQUESTS_HELP).inc(
                model=model_label, route=route, outcome=outcome)
            REGISTRY.histogram(
                LATENCY_SECONDS, LATENCY_HELP,
            ).observe(_time.perf_counter() - t0, route=route)
            span.end(status=span_status)

    return handler


def make_grpc_server(
    model_server: ModelServer,
    port: int = GRPC_PORT,
    host: str = "0.0.0.0",
    max_workers: int = 8,
) -> grpc.Server:
    """Build + start the gRPC server; returns it (call .stop() to halt).
    Pass port=0 for an ephemeral port (read it from .bound_port)."""
    servicer = PredictionServicer(model_server)
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            _wrap(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        for name, (req, resp) in _METHODS.items()
    }
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(SERVICE, handlers),))
    # Standard health face (readiness parity with /readyz): raw-bytes
    # serializers — the request's optional ``service`` field is
    # irrelevant to a server-wide answer, so no message parse at all.
    def health_check(request: bytes, context) -> bytes:
        return _health_response(
            HEALTH_SERVING if model_server.is_ready()
            else HEALTH_NOT_SERVING)

    health_handlers = {
        "Check": grpc.unary_unary_rpc_method_handler(
            health_check,
            request_deserializer=bytes,
            response_serializer=bytes,
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(HEALTH_SERVICE,
                                              health_handlers),))
    # TF-Serving compat face on the SAME port: reference-era clients
    # address /tensorflow.serving.PredictionService/Predict with TF
    # TensorProto payloads and run unchanged (serving/tf_compat.py).
    from kubeflow_tpu.serving import tf_compat
    from kubeflow_tpu.serving.protos import tf_compat_pb2

    tf_servicer = tf_compat.TFPredictServicer(model_server)
    tf_handlers = {
        "Predict": grpc.unary_unary_rpc_method_handler(
            _wrap(tf_servicer, "Predict"),
            request_deserializer=tf_compat_pb2.PredictRequest.FromString,
            response_serializer=(
                tf_compat_pb2.PredictResponse.SerializeToString),
        ),
    }
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(
            tf_compat.TF_SERVICE, tf_handlers),))
    bound = server.add_insecure_port(f"{host}:{port}")
    server.bound_port = bound
    server.start()
    log.info("gRPC PredictionService on :%d (+ tf-serving compat)", bound)
    return server


def retry_call(fn, *, retries: int = 2, backoff_s: float = 0.05,
               backoff_cap_s: float = 2.0, rng=None,
               sleep=None):
    """Bounded client-side retry for idempotent calls to a serving
    replica (``fn`` is a zero-arg closure over one PredictionClient
    method call).

    Backoff honors the SERVER's hint first: an ``Overloaded`` carries
    the Retry-After the server attached (trailing metadata -> the typed
    ``retry_after_s`` field), and that number — the server's own
    estimate of when it will have room — overrides the local jittered
    exponential schedule, capped at ``backoff_cap_s`` so a confused
    server cannot park the client.  Transport UNAVAILABLE (replica
    restarting) falls back to the local schedule.  DeadlineExceeded and
    semantic errors never retry: the deadline is spent, and answers are
    answers."""
    import random as _random
    import time as _time

    rng = rng or _random.Random()
    sleep = sleep or _time.sleep
    attempt = 0
    while True:
        try:
            return fn()
        except Overloaded as e:
            if attempt >= retries:
                raise
            hint = getattr(e, "retry_after_s", None)
            if hint is not None:
                delay = min(backoff_cap_s, max(0.0, float(hint)))
                delay *= 1.0 + 0.1 * rng.random()
            else:
                delay = min(backoff_cap_s, backoff_s * (2 ** attempt))
                delay *= 0.5 + 0.5 * rng.random()
            sleep(delay)
            attempt += 1
        except grpc.RpcError as e:
            code = e.code() if callable(getattr(e, "code", None)) else None
            if code != grpc.StatusCode.UNAVAILABLE or attempt >= retries:
                raise
            delay = min(backoff_cap_s, backoff_s * (2 ** attempt))
            sleep(delay * (0.5 + 0.5 * rng.random()))
            attempt += 1


class PredictionClient:
    """Minimal client — heir of inception-client/label.py:40-57.

    ``timeout`` is the CLIENT-SUPPLIED deadline, in seconds, propagated
    on the wire (gRPC grpc-timeout): the server enforces it in its
    queues and — for the decode engine — mid-generation, so the default
    is None (no deadline) rather than an arbitrary hard-coded number;
    pass what your caller can actually afford.  Transport-level
    deadline/overload statuses come back as the typed serving errors
    (DeadlineExceeded / Overloaded), matching what in-process callers
    of ModelServer.predict see."""

    def __init__(self, target: str):
        self._channel = grpc.insecure_channel(target)
        self._methods = {
            name: self._channel.unary_unary(
                f"/{SERVICE}/{name}",
                request_serializer=req.SerializeToString,
                response_deserializer=resp.FromString,
            )
            for name, (req, resp) in _METHODS.items()
        }

    def _call(self, name: str, req, timeout: Optional[float],
              idem_key: Optional[str] = None):
        metadata = ((IDEMPOTENCY_METADATA, idem_key),) \
            if idem_key else None
        try:
            return self._methods[name](req, timeout=timeout,
                                       metadata=metadata)
        except grpc.RpcError as e:
            code = e.code() if callable(getattr(e, "code", None)) else None
            details = e.details() if callable(
                getattr(e, "details", None)) else str(e)
            if code == grpc.StatusCode.DEADLINE_EXCEEDED:
                # Covers both: the server's typed expiry AND a pure
                # transport timeout (request never completed in time).
                raise DeadlineExceeded(f"{name}: {details}") from e
            if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                # Recover the server's Retry-After hint from the
                # trailing metadata _wrap attaches (falling back to the
                # 1.0 s default against servers that sent none) so
                # clients backing off via the typed field honor the
                # server's number.
                retry_after = 1.0
                trailing = getattr(e, "trailing_metadata", None)
                for key, value in (trailing() if callable(trailing)
                                   else None) or ():
                    if key == "retry-after":
                        try:
                            retry_after = float(value)
                        except ValueError:
                            pass
                raise Overloaded(
                    f"{name}: {details}", retry_after_s=retry_after,
                ) from e
            raise

    def predict(self, model: str, inputs: dict,
                version: int = 0, timeout: Optional[float] = None,
                idem_key: Optional[str] = None):
        """``idem_key`` rides the x-kft-idempotency-key metadata: a
        retry with the same key is answered from the server's dedup
        cache (attached in flight / cached result), never re-run."""
        req = pb.PredictRequest()
        req.model_spec.name = model
        req.model_spec.version = version
        for key, value in inputs.items():
            req.inputs[key].CopyFrom(numpy_to_tensor(np.asarray(value)))
        resp = self._call("Predict", req, timeout, idem_key=idem_key)
        return {k: tensor_to_numpy(t) for k, t in resp.outputs.items()}

    def classify(self, model: str, inputs: dict, top_k: int = 5,
                 timeout: Optional[float] = None):
        req = pb.ClassifyRequest(top_k=top_k)
        req.model_spec.name = model
        for key, value in inputs.items():
            req.inputs[key].CopyFrom(numpy_to_tensor(np.asarray(value)))
        resp = self._call("Classify", req, timeout)
        return [list(zip(r.classes, r.scores)) for r in resp.results]

    def metadata(self, model: str,
                 timeout: Optional[float] = None) -> dict:
        req = pb.GetModelMetadataRequest()
        req.model_spec.name = model
        resp = self._call("GetModelMetadata", req, timeout)
        return json.loads(resp.metadata_json)

    def ready(self, timeout: Optional[float] = 5.0) -> bool:
        """grpc.health.v1 Check against this channel: True iff the
        server answers SERVING (mirrors GET /readyz == 200).  Transport
        errors are False, not raised — a probe's job is a verdict."""
        method = self._channel.unary_unary(
            f"/{HEALTH_SERVICE}/Check",
            request_serializer=bytes,
            response_deserializer=bytes)
        try:
            return _health_status(method(b"", timeout=timeout)) \
                == HEALTH_SERVING
        except grpc.RpcError:
            return False

    def close(self) -> None:
        self._channel.close()


def check_health(target: str, timeout: Optional[float] = 5.0) -> bool:
    """One-shot grpc.health.v1 readiness probe of ``target``
    (host:port) — what the fleet endpoint registry uses for gRPC-only
    replicas; the REST twin is GET /readyz."""
    client = PredictionClient(target)
    try:
        return client.ready(timeout=timeout)
    finally:
        client.close()
