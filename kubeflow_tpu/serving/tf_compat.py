"""TF-Serving Predict compatibility: reference clients run unchanged.

The reference's gRPC clients called
``/tensorflow.serving.PredictionService/Predict`` with TF
``TensorProto`` inputs — raw image BYTES for the Inception flagship
(inception-client/label.py:40-57: ``tf.make_tensor_proto(raw_images)``,
DT_STRING), decoded inside the served TF graph.  This module gives the
first-party server that exact wire face:

  * protos/tf_compat.proto — field-number clones of the public
    predict/model/tensor protos (wire-identical; see its header);
  * TensorProto <-> numpy converters for the encodings real clients
    emit (tensor_content, typed ``*_val`` lists, DT_STRING bytes);
  * server-side image decode (PIL) for DT_STRING inputs, standing in
    for the decode_jpeg the reference's TF graph did;
  * a Predict servicer registered under the tensorflow.serving service
    name next to the native kft.serving one (grpc_server.py).

The native ``kft.serving`` surface remains the primary contract; this
is the unchanged-client on-ramp.
"""

from __future__ import annotations

import io
from typing import Any, Dict, List

import numpy as np

from kubeflow_tpu.serving.protos import tf_compat_pb2 as pb

TF_SERVICE = "tensorflow.serving.PredictionService"

# tensorflow DataType enum values <-> numpy dtypes (tensor.proto /
# types.proto; integers cloned so no tf import is needed at runtime).
DT_STRING = 7
_DT_TO_NUMPY = {
    1: np.float32, 2: np.float64, 3: np.int32, 4: np.uint8,
    5: np.int16, 6: np.int8, 9: np.int64, 10: np.bool_,
    17: np.uint16, 19: np.float16, 22: np.uint32, 23: np.uint64,
}
_NUMPY_TO_DT = {np.dtype(v): k for k, v in _DT_TO_NUMPY.items()}

# Which repeated field carries values for each dtype when
# tensor_content is empty (tf.make_tensor_proto's small-tensor path).
_VAL_FIELD = {
    1: "float_val", 2: "double_val", 3: "int_val", 4: "int_val",
    5: "int_val", 6: "int_val", 9: "int64_val", 10: "bool_val",
    17: "int_val", 19: "half_val", 22: "uint32_val", 23: "uint64_val",
}


def tensorproto_to_numpy(t: pb.TensorProto):
    """tensorflow.TensorProto bytes -> numpy array (or list of bytes
    for DT_STRING).  Handles both encodings clients produce:
    ``tensor_content`` (packed little-endian) and the typed ``*_val``
    repeated fields, including the broadcast-one-value shorthand."""
    shape = tuple(d.size for d in t.tensor_shape.dim)
    if t.dtype == DT_STRING:
        return list(t.string_val)
    np_dtype = _DT_TO_NUMPY.get(t.dtype)
    if np_dtype is None:
        raise ValueError(f"unsupported TensorProto dtype {t.dtype}")
    if t.tensor_content:
        # frombuffer views the (immutable) protobuf bytes read-only; a
        # consumer normalizing/padding the input dict in place would
        # hit 'assignment destination is read-only' only on THIS
        # encoding, a payload-dependent failure.  Inputs are request-
        # sized: the copy is cheap next to the decode.
        arr = np.frombuffer(t.tensor_content, dtype=np_dtype)
        return arr.reshape(shape).copy()
    vals = np.asarray(
        list(getattr(t, _VAL_FIELD[t.dtype])))
    if t.dtype == 19:  # half_val carries raw uint16 bit patterns
        vals = vals.astype(np.uint16).view(np.float16)
    vals = vals.astype(np_dtype)
    n = int(np.prod(shape)) if shape else vals.size
    if vals.size == 1 and n > 1:
        # broadcast_to also yields a read-only view; same contract.
        vals = np.broadcast_to(vals, (n,)).copy()
    return vals.reshape(shape)


def numpy_to_tensorproto(arr: np.ndarray) -> pb.TensorProto:
    arr = np.ascontiguousarray(arr)
    dt = _NUMPY_TO_DT.get(arr.dtype)
    if dt is None:
        raise ValueError(f"unsupported response dtype {arr.dtype}")
    t = pb.TensorProto(dtype=dt, tensor_content=arr.tobytes())
    for size in arr.shape:
        t.tensor_shape.dim.add(size=size)
    return t


def decode_image_bytes(blobs: List[bytes]) -> np.ndarray:
    """Raw encoded image bytes -> uint8 [n, h, w, 3] — the server-side
    stand-in for the decode_jpeg node the reference's TF graph ran on
    its DT_STRING inputs.  All images in one request must decode to one
    shape (they share a batch)."""
    from PIL import Image

    rows = []
    for i, blob in enumerate(blobs):
        try:
            img = Image.open(io.BytesIO(blob)).convert("RGB")
        except Exception as e:
            # Client-supplied bytes: surface as INVALID_ARGUMENT (the
            # gRPC wrapper maps ValueError), not a bare UNKNOWN —
            # PIL raises UnidentifiedImageError/OSError, neither of
            # which the status mapping knows.
            raise ValueError(
                f"inputs string tensor element {i} is not a decodable "
                f"image: {e}") from e
        rows.append(np.asarray(img, dtype=np.uint8))
    try:
        return np.stack(rows)
    except ValueError as e:
        raise ValueError(
            f"images in one request must share a shape: {e}") from e


def request_inputs_to_numpy(
    request: pb.PredictRequest,
) -> Dict[str, Any]:
    """Convert a TF-shaped request's inputs for ModelServer.predict.

    DT_STRING inputs are decoded as images; the reference's canonical
    input key ``images`` is aliased to the first-party loaders' singular
    ``image`` (label.py sent ``inputs['images']``)."""
    inputs: Dict[str, Any] = {}
    for key, t in request.inputs.items():
        value = tensorproto_to_numpy(t)
        if isinstance(value, list):  # DT_STRING -> decoded image batch
            value = decode_image_bytes(value)
        if key == "images":
            key = "image"
        inputs[key] = value
    return inputs


class TFPredictServicer:
    """Predict (and GetModelMetadata-free) face of the compat service —
    registered under the tensorflow.serving service name."""

    def __init__(self, server):
        self.server = server

    def Predict(self, request: pb.PredictRequest, context):
        spec = request.model_spec
        version = (spec.version.value
                   if spec.HasField("version") and spec.version.value > 0
                   else None)
        # Resolve BEFORE predicting (same order as the native
        # servicer): resolving after could report a version a
        # concurrent hot-swap installed mid-request.
        model = self.server.get(spec.name, version)
        inputs = request_inputs_to_numpy(request)
        outputs = self.server.predict(spec.name, inputs, version)
        resp = pb.PredictResponse()
        resp.model_spec.name = spec.name
        resp.model_spec.version.value = model.version
        keep = set(request.output_filter)
        for key, value in outputs.items():
            if keep and key not in keep:
                continue
            resp.outputs[key].CopyFrom(
                numpy_to_tensorproto(np.asarray(value)))
        return resp
