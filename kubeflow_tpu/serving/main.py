"""Serving container entrypoint.

Flag-compatible heir of the model server invocation the reference's
manifests assembled: ``tensorflow_model_server --port=9000
--model_name=... --model_base_path=...``
(kubeflow/tf-serving/tf-serving.libsonnet:118-132) plus the http proxy's
``--port 8000`` sidecar (:176-207) — here one process serves both wire
protocols over one set of warm models on the local TPU: the gRPC
PredictionService on ``--grpc_port`` (:9000, the reference's primary
protocol) and the REST contract on ``--port`` (:8000).
"""

from __future__ import annotations

import argparse
import logging
import signal
import sys
import threading
import time

from kubeflow_tpu.serving.http import make_http_server
from kubeflow_tpu.serving.model_server import ModelServer
from kubeflow_tpu.testing import faults


def batcher_factory(*, micro_batch_size: int, batch_timeout_s: float,
                    lm_buckets: str = "",
                    lm_max_promotion_factor: float = 4.0,
                    lm_engine: bool = True,
                    lm_engine_slots: int = 8,
                    lm_engine_prefill_len: int = 0,
                    lm_engine_sync_lag: int = 2,
                    lm_engine_steps_per_call: int = 1,
                    lm_engine_admit_width: int = 4,
                    decode_rounds: int = 1,
                    prefill_chunk_tokens: int = 64,
                    kv_block_tokens: int = 16,
                    kv_pool_blocks: int = 0,
                    host_spill_blocks: int = 0,
                    prefix_caching: bool = True,
                    max_queue_depth: int = 0,
                    overload_retry_after_s: float = 1.0,
                    speculative_tokens: int = 0,
                    adapters_dir: str = "",
                    adapter_slots: int = 8,
                    adapter_rank: int = 4,
                    mesh: str = ""):
    """ModelServer.enable_batching factory: picks the batcher per model.

    lm_generate models default to the continuous-batching DecodeEngine
    (serving/engine.py: persistent slot cache, in-flight admission,
    immediate retirement); ``lm_engine=False`` (--lm_static_batcher)
    falls back to the static left-padding BucketedLMBatcher when
    buckets are configured.  Everything else gets the shape-grouped
    MicroBatcher when micro-batching is on, or no batcher at all
    (build returns None -> direct predict path).  Rebuilt around every
    hot-swapped version by ModelServer.
    """
    from kubeflow_tpu.serving import sharding
    from kubeflow_tpu.serving.engine import DecodeEngine
    from kubeflow_tpu.serving.model_server import (
        BucketedLMBatcher,
        MicroBatcher,
    )

    sizes = [s for s in (1, 2, 4, 8, 16, 32, 64, 128)
             if s <= micro_batch_size]
    if not sizes or sizes[-1] != micro_batch_size:
        sizes.append(micro_batch_size)
    buckets = [int(b) for b in lm_buckets.split(",") if b.strip()]
    # Parsed once (fail fast on a typo'd --mesh), built per engine:
    # the mesh object itself is cheap, and a rebuilt engine after
    # hot-swap must re-place its params on the same devices anyway.
    mesh_axes = sharding.parse_mesh_flag(mesh)

    def build(model):
        spec = getattr(model.predict, "engine_spec", None)
        if lm_engine and spec is not None:
            # Prefill width: explicit flag > largest bucket > a capped
            # share of whatever prompt room the model's max_seq_len
            # leaves after the configured completion budget.  The width
            # is a STATIC program shape (the four-program guarantee), so
            # every admission prefills at this width no matter how
            # short the prompt, and the persistent cache is sized
            # slots x (width + budget) — hence the flagless cap: a
            # 2048-ctx model must not pay near-full-context prefill
            # per admission by default.  Prompts beyond the width fall
            # back to the direct generate() path (exactly the old
            # flagless behavior), and everything is clamped to the
            # model's real prompt room so a config that fit the static
            # batchers never turns into a construction crash here; if
            # no room is left at all, fall through to the static paths.
            cap = (spec["cfg"].max_seq_len
                   - spec["decode"].max_new_tokens)
            prefill = lm_engine_prefill_len or (
                max(buckets) if buckets else min(cap, 512))
            prefill = min(prefill, cap)
            if prefill >= 1:
                registry = None
                if adapters_dir:
                    # Multi-model adapter serving (§5.11): one registry
                    # per engine; hot-loaded per-tenant deltas ride the
                    # stacked adapter array inside the SAME programs.
                    from kubeflow_tpu.serving.adapters import (
                        AdapterRegistry,
                    )

                    registry = AdapterRegistry(
                        spec["cfg"], slots=adapter_slots,
                        rank=adapter_rank, directory=adapters_dir,
                        name=f"{model.name}-v{model.version}",
                        overload_retry_after_s=overload_retry_after_s)
                logging.info(
                    "decode engine for %r v%d: %d slots, prefill width "
                    "%d, cache %d cols/slot", model.name, model.version,
                    lm_engine_slots, prefill,
                    prefill + spec["decode"].max_new_tokens)
                return DecodeEngine(
                    spec["cfg"], spec["params"], spec["decode"],
                    slots=lm_engine_slots, prefill_len=prefill,
                    sync_lag=lm_engine_sync_lag,
                    steps_per_call=lm_engine_steps_per_call,
                    decode_rounds=decode_rounds,
                    admit_width=lm_engine_admit_width,
                    prefill_chunk_tokens=prefill_chunk_tokens,
                    kv_block_tokens=kv_block_tokens,
                    kv_pool_blocks=kv_pool_blocks,
                    host_spill_blocks=host_spill_blocks,
                    prefix_caching=prefix_caching,
                    max_queue_depth=max_queue_depth,
                    overload_retry_after_s=overload_retry_after_s,
                    speculative_tokens=speculative_tokens,
                    adapters=registry,
                    mesh=sharding.build_mesh(mesh_axes),
                    name=f"{model.name}-v{model.version}")
            logging.warning(
                "decode engine disabled for %r: max_new_tokens %d "
                "leaves no prompt room in max_seq_len %d", model.name,
                spec["decode"].max_new_tokens, spec["cfg"].max_seq_len)
        if micro_batch_size <= 0:
            return None  # direct predict path
        kwargs = dict(
            max_batch_size=micro_batch_size,
            batch_timeout_s=batch_timeout_s,
            allowed_batch_sizes=sizes,
            max_queue_depth=max_queue_depth,
            overload_retry_after_s=overload_retry_after_s,
            name=f"{model.name}-v{model.version}",
        )
        loader = str(model.meta.get("loader", ""))
        if buckets and loader.endswith("lm_generate"):
            return BucketedLMBatcher(
                model.predict, buckets=buckets,
                max_promotion_factor=(lm_max_promotion_factor
                                      if lm_max_promotion_factor > 0
                                      else None),
                **kwargs)
        return MicroBatcher(model.predict, **kwargs)

    return build


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubeflow-tpu-serve")
    ap.add_argument("--model_name", required=True)
    ap.add_argument("--model_base_path", required=True)
    ap.add_argument("--port", type=int, default=8000,
                    help="REST port (reference http-proxy contract)")
    ap.add_argument("--grpc_port", type=int, default=9000,
                    help="gRPC PredictionService port (reference "
                         "tensorflow_model_server contract); -1 disables")
    ap.add_argument("--poll_interval_s", type=float, default=2.0,
                    help="model version poll period (hot-swap latency)")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--micro_batch_size", type=int, default=0,
                    help="coalesce concurrent single-row requests into "
                         "device batches up to this size (0 = off) — "
                         "the TF-Serving batching-parameters idea, "
                         "TPU-shaped; survives hot-swap")
    ap.add_argument("--batch_timeout_ms", type=float, default=5.0,
                    help="micro-batch assembly window per shape group")
    ap.add_argument("--lm_buckets", default="",
                    help="comma-separated prompt-length buckets; with "
                         "--micro_batch_size on an lm_generate model, "
                         "mixed-length prompts left-pad to these so "
                         "they share batched decode programs")
    ap.add_argument("--lm_max_promotion_factor", type=float, default=4.0,
                    help="bound on dispatch-time bucket promotion: only "
                         "prompts whose buckets are within this factor "
                         "share a batch (a short prompt then never pays "
                         "more than factor x its own bucket's KV span "
                         "per decode step); <=0 = unbounded, one "
                         "shared queue")
    ap.add_argument("--lm_static_batcher", action="store_true",
                    help="serve lm_generate models through the static "
                         "BucketedLMBatcher (pad-at-dispatch whole-"
                         "generation programs) instead of the default "
                         "continuous-batching DecodeEngine")
    ap.add_argument("--lm_engine_slots", type=int, default=8,
                    help="DecodeEngine concurrent sequences (persistent "
                         "KV-cache rows)")
    ap.add_argument("--lm_engine_prefill_len", type=int, default=0,
                    help="DecodeEngine static prompt width (0 = largest "
                         "--lm_buckets entry, else max_seq_len minus "
                         "max_new_tokens capped at 512; always clamped "
                         "to the model's prompt room); longer prompts "
                         "fall back to the direct generate() path.  "
                         "Every admission prefills at this width and "
                         "the persistent KV cache is sized by it — set "
                         "it near your real prompt lengths on long-"
                         "context models")
    ap.add_argument("--lm_engine_sync_lag", type=int, default=2,
                    help="DecodeEngine host-read lag in steps (host "
                         "dispatches ahead of token materialization; "
                         "0 = synchronous loop)")
    ap.add_argument("--lm_engine_steps_per_call", type=int, default=1,
                    help="DecodeEngine decode steps fused per step-"
                         "program call: amortizes per-dispatch overhead "
                         "k-fold at k-step admission granularity")
    ap.add_argument("--decode_rounds", type=int, default=8,
                    help="DecodeEngine fused decode rounds: up to k "
                         "steps run device-resident per dispatch in a "
                         "while_loop with early exit when every slot "
                         "finishes, host uploads double-buffered "
                         "behind device compute (docs §5.2e).  The "
                         "width adapts between 1 and k on early-exit "
                         "waste and queued admissions, and is clamped "
                         "under the tightest live deadline; 1 restores "
                         "the classic per-step dispatch loop "
                         "bit-for-bit")
    ap.add_argument("--lm_engine_admit_width", type=int, default=4,
                    help="DecodeEngine concurrent mid-prefill "
                         "admissions: further queued requests wait "
                         "even when slots are free, so a burst of long "
                         "prompts cannot hoard every slot half-filled")
    ap.add_argument("--prefill_chunk_tokens", type=int, default=64,
                    help="DecodeEngine per-step prefill token budget "
                         "(and the static chunk width): arriving "
                         "prompts prefill in chunks scheduled between "
                         "decode steps, so in-flight inter-token "
                         "latency is bounded by one chunk regardless "
                         "of prompt length")
    ap.add_argument("--kv_block_tokens", type=int, default=16,
                    help="DecodeEngine paged-KV page size in cache "
                         "positions — also the prefix hash/share "
                         "granularity (shared prefixes alias in "
                         "multiples of this many tokens)")
    ap.add_argument("--kv_pool_blocks", type=int, default=0,
                    help="DecodeEngine device KV block-pool capacity "
                         "in pages (0 = slots x ceil(max_len / "
                         "kv_block_tokens), capacity parity with a "
                         "slot-reserved cache).  Serving capacity is "
                         "bounded by TOKENS RESIDENT in this pool, not "
                         "slot count: mixed-length traffic fits far "
                         "more requests than the worst case, and "
                         "exhaustion sheds typed Overloaded (429)")
    ap.add_argument("--host_spill_blocks", type=int, default=0,
                    help="DecodeEngine host-RAM KV spill tier capacity "
                         "in pages (0 = disabled, §5.10).  LRU-cold "
                         "prefix records and parked multi-turn "
                         "sessions evacuate to host memory under pool "
                         "pressure and re-import through kv_import on "
                         "the next hit — tokens-addressable capacity "
                         "becomes (kv_pool_blocks + host_spill_blocks)"
                         " x kv_block_tokens, and the :fetch_kv route "
                         "serves these pages to failover peers")
    ap.add_argument("--no_prefix_cache", action="store_true",
                    help="disable shared-prefix block aliasing "
                         "(admissions never resume from cached "
                         "prefixes; the paged pool and chunked "
                         "prefill still apply)")
    ap.add_argument("--speculative_tokens", type=int, default=0,
                    help="DecodeEngine self-speculative decoding: up "
                         "to this many n-gram-drafted candidate tokens "
                         "verify per slot in ONE forward pass "
                         "(prompt-lookup drafting, no second model), "
                         "token-identical to greedy decode; per-slot "
                         "adaptive backoff protects low-acceptance "
                         "traffic.  Greedy exports only (sampling "
                         "exports fall back to plain decode); 0 "
                         "disables")
    ap.add_argument("--adapters_dir", default="",
                    help="directory of per-tenant adapter deltas "
                         "(<name>.npz + digest sidecar, §5.11): enables "
                         "multi-model serving on the DecodeEngine — "
                         "requests naming 'model@adapter' hot-load the "
                         "delta into a bounded stacked-array slot and "
                         "co-batch with every other variant in the SAME "
                         "compiled programs.  Empty = adapter requests "
                         "404")
    ap.add_argument("--adapter_slots", type=int, default=8,
                    help="resident adapter variants per engine (the "
                         "stacked array's device rows beyond base); "
                         "idle adapters LRU-evict when the slots fill, "
                         "in-flight ones are pinned — all slots pinned "
                         "sheds 429")
    ap.add_argument("--adapter_rank", type=int, default=4,
                    help="low-rank adapter factor rank: every adapter "
                         "served by one engine shares this rank (the "
                         "stacked array is one static shape)")
    ap.add_argument("--mesh", default="",
                    help="serving mesh spec, e.g. 'tensor=4': shard "
                         "the DecodeEngine's params and paged KV pool "
                         "over that many local devices (regex "
                         "partition rules, serving/sharding.py) so "
                         "one model spans a pod slice.  Empty = "
                         "single-device.  On CPU, simulate chips "
                         "with XLA_FLAGS="
                         "--xla_force_host_platform_device_count=N")
    ap.add_argument("--role", default="unified",
                    choices=("unified", "prefill", "decode"),
                    help="disaggregated-serving tier, advertised on "
                         "/readyz: 'prefill' replicas serve :prefill "
                         "(chunked prefill into KV handoff pages), "
                         "'decode' replicas import handoffs and "
                         "stream; the fleet router pipelines "
                         ":generate across the two pools.  'unified' "
                         "(default) keeps the single-tier path")
    ap.add_argument("--max_queue_depth", type=int, default=256,
                    help="bounded admission: submissions beyond this "
                         "many pending requests per model fail fast "
                         "with HTTP 429 / gRPC RESOURCE_EXHAUSTED "
                         "instead of queueing unboundedly (0 = "
                         "unbounded)")
    ap.add_argument("--max_inflight", type=int, default=512,
                    help="per-model in-flight request cap across ALL "
                         "paths — including the direct (un-batched) "
                         "one, which has no queue to bound it; beyond "
                         "it submissions shed with 429 (0 = unbounded)")
    ap.add_argument("--overload_retry_after_s", type=float, default=1.0,
                    help="Retry-After hint carried by shed (429) "
                         "responses")
    ap.add_argument("--dedup_capacity", type=int, default=1024,
                    help="idempotency dedup cache entries (completed "
                         "results answered to retried keys; in-flight "
                         "duplicates attach instead of re-executing)")
    ap.add_argument("--dedup_ttl_s", type=float, default=120.0,
                    help="how long a completed idempotency-key result "
                         "stays answerable (policy clock); 0 disables "
                         "expiry")
    ap.add_argument("--drain_deadline_s", type=float, default=30.0,
                    help="graceful-drain budget on SIGTERM: /readyz "
                         "flips not-ready immediately, then in-flight "
                         "requests get this long to finish before the "
                         "listeners close (match it to the pod's "
                         "terminationGracePeriodSeconds)")
    ap.add_argument("--reload_backoff_s", type=float, default=0.5,
                    help="initial circuit-breaker backoff after a "
                         "model (re)load failure (doubles per failure, "
                         "jittered; the last-good version keeps "
                         "serving while the breaker is open)")
    ap.add_argument("--reload_backoff_cap_s", type=float, default=60.0,
                    help="circuit-breaker backoff ceiling")
    from kubeflow_tpu.runtime import tracing

    tracing.add_cli_args(ap)
    args = ap.parse_args(argv)

    logging.basicConfig(level=logging.INFO, stream=sys.stderr)
    if tracing.enable_from_args(args) is not None:
        logging.info("request tracing on (sample rate %g, store %d "
                     "traces) — GET /debug/traces",
                     args.trace_sample_rate, args.trace_capacity)
    # Scripted chaos (KFT_FAULTS env var): no-op unless set — see
    # kubeflow_tpu/testing/faults.py for the grammar.
    if faults.install_from_env() is not None:
        logging.warning("fault injection ACTIVE (KFT_FAULTS set)")
    server = ModelServer(
        poll_interval_s=args.poll_interval_s,
        reload_backoff_s=args.reload_backoff_s,
        reload_backoff_cap_s=args.reload_backoff_cap_s,
        max_inflight=args.max_inflight,
        overload_retry_after_s=args.overload_retry_after_s,
        dedup_capacity=args.dedup_capacity,
        dedup_ttl_s=args.dedup_ttl_s,
        role=args.role)
    server.add_model(args.model_name, args.model_base_path)
    # The factory is installed whenever ANY batching path might apply:
    # lm_generate models default to the continuous DecodeEngine even
    # with micro-batching off (it is the serving hot path, not an
    # opt-in); --lm_static_batcher restores the old behavior.
    if args.micro_batch_size > 0 or not args.lm_static_batcher:
        server.enable_batching(
            args.model_name,
            batcher_factory(
                micro_batch_size=args.micro_batch_size,
                batch_timeout_s=args.batch_timeout_ms / 1e3,
                lm_buckets=args.lm_buckets,
                lm_max_promotion_factor=args.lm_max_promotion_factor,
                lm_engine=not args.lm_static_batcher,
                lm_engine_slots=args.lm_engine_slots,
                lm_engine_prefill_len=args.lm_engine_prefill_len,
                lm_engine_sync_lag=args.lm_engine_sync_lag,
                lm_engine_steps_per_call=args.lm_engine_steps_per_call,
                lm_engine_admit_width=args.lm_engine_admit_width,
                decode_rounds=args.decode_rounds,
                prefill_chunk_tokens=args.prefill_chunk_tokens,
                kv_block_tokens=args.kv_block_tokens,
                kv_pool_blocks=args.kv_pool_blocks,
                host_spill_blocks=args.host_spill_blocks,
                prefix_caching=not args.no_prefix_cache,
                max_queue_depth=args.max_queue_depth,
                overload_retry_after_s=args.overload_retry_after_s,
                speculative_tokens=args.speculative_tokens,
                adapters_dir=args.adapters_dir,
                adapter_slots=args.adapter_slots,
                adapter_rank=args.adapter_rank,
                mesh=args.mesh,
            ),
        )
        logging.info(
            "request batching on: %s%s",
            ("continuous decode engine (slots=%d)"
             % args.lm_engine_slots if not args.lm_static_batcher
             else "static batchers"),
            (", micro batch size<=%d, window %.1f ms"
             % (args.micro_batch_size, args.batch_timeout_ms)
             if args.micro_batch_size > 0 else ""))
    server.start_watcher()
    httpd, _ = make_http_server(server, port=args.port, host=args.host)
    grpc_server = None
    if args.grpc_port >= 0:
        # Deferred import: grpcio is the [serving] extra; a REST-only
        # deployment (--grpc_port -1) must run without it installed.
        from kubeflow_tpu.serving.grpc_server import make_grpc_server

        grpc_server = make_grpc_server(server, port=args.grpc_port,
                                       host=args.host)
        logging.info("serving %r on rest=:%d grpc=:%d", args.model_name,
                     args.port, grpc_server.bound_port)
    else:
        logging.info("serving %r on rest=:%d (grpc disabled)",
                     args.model_name, args.port)
    # Readiness marker for process-spawning tests/orchestration: the
    # bound ports, on one parseable stderr line, after both servers are up.
    print(f"KFT_SERVING_READY rest={httpd.server_address[1]} "
          f"grpc={grpc_server.bound_port if grpc_server else -1}",
          file=sys.stderr, flush=True)

    stop = threading.Event()

    def on_signal(*_):
        # Readiness flips INSIDE the handler: the load balancer must
        # see /readyz go 503 at the first possible instant, while
        # /healthz stays 200 (a draining pod is alive, not dead).
        server.begin_drain()
        stop.set()

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)
    stop.wait()
    # Graceful drain: requests already accepted — and stragglers routed
    # here before the endpoint controller catches up — finish inside
    # the drain budget; only then do the listeners close.  Rolling
    # updates on GKE therefore lose zero accepted requests (the engine
    # additionally drains its in-flight slots in server.stop()).
    drained = wait_for_drain(server, args.drain_deadline_s)
    logging.info("drain %s after SIGTERM (in-flight now %d)",
                 "complete" if drained else "deadline exceeded",
                 server.inflight())
    httpd.shutdown()
    if grpc_server is not None:
        grpc_server.stop(grace=1)
    server.stop()
    return 0


def wait_for_drain(server: ModelServer, deadline_s: float,
                   settle_s: float = 0.25,
                   poll_s: float = 0.02) -> bool:
    """Block until the server's in-flight count stays at zero for
    ``settle_s`` (new stragglers may still arrive while load balancers
    catch up with the readiness flip) or ``deadline_s`` passes.
    Returns True when the server quiesced inside the budget."""
    deadline = faults.monotonic() + max(0.0, deadline_s)
    quiet_since = None
    while faults.monotonic() < deadline:
        if server.inflight() == 0:
            if quiet_since is None:
                quiet_since = faults.monotonic()
            elif faults.monotonic() - quiet_since >= settle_s:
                return True
        else:
            quiet_since = None
        time.sleep(poll_s)
    return server.inflight() == 0


if __name__ == "__main__":
    sys.exit(main())
