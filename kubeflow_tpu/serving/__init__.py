"""Serving plane: versioned export, TPU model server, REST contract.

Heir of the reference's L6 serving stack (SURVEY.md §1): the C++
tensorflow_model_server + python http-proxy pair collapses into one
first-party process — export.py is the SavedModel-equivalent on-disk
contract, model_server.py the versioned loader/hot-swapper/batcher,
http.py the reference-compatible REST surface, main.py the container
entrypoint.
"""

from kubeflow_tpu.serving.errors import (
    BatcherClosed,
    DeadlineExceeded,
    Overloaded,
    ServingError,
)
from kubeflow_tpu.serving.export import export, list_versions, load_version
from kubeflow_tpu.serving.http import ServingAPI, make_http_server
from kubeflow_tpu.serving.model_server import MicroBatcher, ModelServer

__all__ = [
    "export",
    "list_versions",
    "load_version",
    "ServingAPI",
    "make_http_server",
    "MicroBatcher",
    "ModelServer",
    "ServingError",
    "BatcherClosed",
    "DeadlineExceeded",
    "Overloaded",
]
