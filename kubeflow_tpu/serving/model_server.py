"""Model server core: versioned loading, hot-swap, micro-batching.

TPU-native heir of C++ ``tensorflow_model_server``
(kubeflow/tf-serving/tf-serving.libsonnet:118-132): watches a model base
path for numbered versions, serves the latest, hot-swaps when new versions
land, and unloads superseded ones — the semantics the reference got for
free from TF-Serving (SURVEY.md §7 "Hard parts: serving on TPU").

Batching: TPU inference wants large, fixed-shape batches for the MXU; the
MicroBatcher coalesces concurrent single requests into one device call,
padding to the nearest allowed batch size so XLA reuses a handful of
compiled programs instead of one per request shape.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.serving.adapters import (
    AdapterNotFound,
    split_model_adapter,
)
from kubeflow_tpu.serving.errors import (  # noqa: F401 — re-exported
    BatcherClosed,
    DeadlineExceeded,
    Overloaded,
)
from kubeflow_tpu.serving.export import list_versions, load_version
from kubeflow_tpu.testing import faults

log = logging.getLogger(__name__)


def locked_snapshot(lock, data: Dict[str, Any],
                    extra: Optional[Callable[[], Dict[str, Any]]] = None):
    """Copy mutable stats counters under their owning lock.

    Returns (dict(data), extra() or {}) taken atomically.  Every stats()
    surface (MicroBatcher, BucketedLMBatcher, DecodeEngine) reads its
    counters through this ONE helper, and writers merge under the same
    lock — a /metrics scrape mid-dispatch must never see a torn
    half-updated cycle profile or an occupancy that sums to more
    requests than exist."""
    with lock:
        return dict(data), (extra() if extra is not None else {})


# One name/help for the request counter shared by the REST and gRPC
# faces — divergent literals would silently create a second series.
REQUESTS_TOTAL = "kft_serving_requests_total"
REQUESTS_HELP = "serving requests by model/route/outcome (REST + gRPC)"
LATENCY_SECONDS = "kft_serving_request_seconds"
LATENCY_HELP = "serving request latency by route (REST + gRPC)"
# Fault-layer series shared by every batching plane (MicroBatcher,
# BucketedLMBatcher, DecodeEngine) — one series per batcher label, so
# overload sheds and deadline expiries are comparable across planes.
SHED_TOTAL = "kft_serving_shed_total"
SHED_HELP = "admissions refused at the queue/in-flight caps, by batcher"
EXPIRED_TOTAL = "kft_serving_deadline_expired_total"
EXPIRED_HELP = "requests failed by their deadline, by batcher"
RELOAD_FAILURES_TOTAL = "kft_serving_reload_failures_total"
RELOAD_FAILURES_HELP = "model (re)load attempts that raised, by model"
BREAKER_OPEN = "kft_serving_reload_breaker_open"
BREAKER_OPEN_HELP = "1 while a model's reload circuit breaker is open"
# Scrape-refreshed load gauges (refresh_gauges): until these existed,
# in-flight was only visible through the :stats JSON route — the fleet
# autoscaler and dashboards scrape ONE endpoint (/metrics) for load.
INFLIGHT_GAUGE = "kft_serving_inflight"
INFLIGHT_HELP = ("requests in flight (transport + predict); unlabeled "
                 "= process total, model= per-model predict calls")
QUEUE_GAUGE = "kft_serving_queue_depth"
QUEUE_HELP = "pending entries in a model's batching plane, by model"
READY_GAUGE = "kft_serving_ready"
READY_HELP = "1 when /readyz would say ready (models loaded, not draining)"
CACHED_RATIO_GAUGE = "kft_serving_cached_token_ratio"
CACHED_RATIO_HELP = ("fraction of prompt tokens served from the engine "
                     "prefix cache; unlabeled = process aggregate, "
                     "model= per-model")
# Hierarchical KV (§5.10): host-tier occupancy as a fraction of the
# spill capacity — the fleet scrape and `fleet status` SPILL% column
# read this per replica.
SPILL_RATIO_GAUGE = "kft_serving_kv_spill_ratio"
SPILL_RATIO_HELP = ("host spill-tier occupancy / host_spill_blocks "
                    "(0 when the tier is disabled), by model; "
                    "unlabeled = process aggregate")
# Idempotency dedup: requests answered from the per-key result cache
# (completed duplicates) or attached to an in-flight execution — the
# survivable-inference counter a chaos run asserts on.
DEDUP_HITS_TOTAL = "kft_serving_dedup_hits_total"
DEDUP_HITS_HELP = ("requests answered from the idempotency dedup "
                   "cache (completed result or in-flight attach), "
                   "by model")


@dataclasses.dataclass
class LoadedModel:
    name: str
    version: int
    predict: Callable[[Dict[str, Any]], Dict[str, Any]]
    meta: Dict[str, Any]


class _ReloadBreaker:
    """Exponential-backoff circuit breaker for one model's (re)loads.

    A corrupt checkpoint directory must not hot-loop the version
    watcher: after a load failure the breaker OPENS for a jittered,
    exponentially-growing backoff during which reload() skips the disk
    entirely (the last-good version keeps serving).  When the backoff
    expires the breaker goes HALF-OPEN: exactly one trial load runs;
    success closes it, failure re-opens with a doubled backoff.  A NEW
    latest version (different from the one that failed) resets the
    breaker immediately — the breaker guards the corrupt artifact, not
    the model name.

    The backoff clock is faults.monotonic() (the skewable policy
    clock), so chaos tests drive the open -> half-open -> closed walk
    without wall-clock sleeps."""

    def __init__(self, base_s: float = 0.5, cap_s: float = 60.0,
                 rng: Optional[random.Random] = None):
        self._base_s = base_s
        self._cap_s = cap_s
        # OS-seeded by default: each replica must walk a DIFFERENT
        # jitter sequence or concurrent replicas watching one shared
        # model path retry in lockstep.  Tests needing a fixed walk
        # pass their own rng.
        self._rng = rng or random.Random()
        self._lock = threading.Lock()
        self.failures = 0
        self.open_until = 0.0
        self.failing_version: Optional[int] = None
        self._half_open = False

    def allow(self, version: int) -> bool:
        """May a load of ``version`` run now?  Claims the single
        half-open trial slot when the backoff has expired."""
        with self._lock:
            if self.failures == 0:
                return True
            if version != self.failing_version:
                self._reset_locked()
                return True
            if self._half_open:
                return False  # a trial is already in flight
            if faults.monotonic() < self.open_until:
                return False
            self._half_open = True
            return True

    def record_failure(self, version: int) -> None:
        with self._lock:
            self.failures += 1
            self.failing_version = version
            self._half_open = False
            backoff = min(self._cap_s,
                          self._base_s * (2 ** (self.failures - 1)))
            # Full jitter up to +25%: concurrent replicas watching one
            # shared model path must not retry in lockstep.
            backoff *= 1.0 + 0.25 * self._rng.random()
            self.open_until = faults.monotonic() + backoff

    def record_success(self) -> None:
        with self._lock:
            self._reset_locked()

    def _reset_locked(self) -> None:
        self.failures = 0
        self.open_until = 0.0
        self.failing_version = None
        self._half_open = False

    @property
    def open(self) -> bool:
        with self._lock:
            return self.failures > 0


class _DedupCache:
    """Bounded, TTL'd idempotency-key -> result cache.

    One entry per key: the FIRST request to present a key becomes the
    primary and executes; concurrent duplicates attach to its entry
    and wait on its event; later duplicates of a COMPLETED key are
    answered from the cached result — so a connection that dies after
    the replica finished no longer forces a client-visible failure or
    a double execution when the request is retried with the same key.

    Failures are never cached: ``fail`` resolves attached waiters with
    the error and drops the entry, so a later retry re-executes (a
    transient Overloaded must not be replayed from cache for the TTL).
    Completed entries expire after ``ttl_s`` on the skewable policy
    clock and are LRU-bounded at ``capacity``; in-flight entries are
    pinned (waiters hold references) and never evicted."""

    def __init__(self, capacity: int = 1024, ttl_s: float = 120.0):
        self.capacity = max(1, int(capacity))
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict[str, dict]" = \
            collections.OrderedDict()

    def begin(self, key: str) -> Tuple[str, dict]:
        """Claim or join ``key``: ("new", entry) makes the caller the
        primary (it MUST finish/fail the entry), ("inflight", entry)
        attaches to a live execution, ("done", entry) hands back the
        cached result."""
        with self._lock:
            self._sweep_locked()
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                verdict = "done" if entry["event"].is_set() \
                    else "inflight"
                return verdict, entry
            entry = {"event": threading.Event(), "result": None,
                     "err": None, "done_at": None}
            self._entries[key] = entry
            while len(self._entries) > self.capacity:
                victim = next(
                    (k for k, e in self._entries.items()
                     if e["event"].is_set()), None)
                if victim is None:
                    break  # everything in flight: pinned
                del self._entries[victim]
            return "new", entry

    def finish(self, key: str, entry: dict, result: Any) -> None:
        with self._lock:
            entry["result"] = result
            entry["done_at"] = faults.monotonic()
        entry["event"].set()

    def fail(self, key: str, entry: dict, exc: BaseException) -> None:
        with self._lock:
            entry["err"] = exc
            if self._entries.get(key) is entry:
                del self._entries[key]
        entry["event"].set()

    def _sweep_locked(self) -> None:
        if self.ttl_s <= 0:
            return
        now = faults.monotonic()
        stale = [k for k, e in self._entries.items()
                 if e["done_at"] is not None
                 and now - e["done_at"] > self.ttl_s]
        for k in stale:
            del self._entries[k]

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


class ModelServer:
    """Serves N named models, each from a versioned base path."""

    def __init__(self, poll_interval_s: float = 2.0,
                 reload_backoff_s: float = 0.5,
                 reload_backoff_cap_s: float = 60.0,
                 max_inflight: int = 0,
                 overload_retry_after_s: float = 1.0,
                 dedup_capacity: int = 1024,
                 dedup_ttl_s: float = 120.0,
                 role: str = "unified"):
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(
                f"role must be unified/prefill/decode, got {role!r}")
        # Disaggregated-serving tier (--role): advertised on /readyz so
        # the fleet registry learns the two-tier topology — "prefill"
        # replicas serve :prefill into KV handoff payloads, "decode"
        # replicas import them, "unified" (default) replicas keep
        # today's single-tier path.  The role is an ADVERTISEMENT, not
        # a gate: every replica still answers every route, so a
        # degraded fleet can always fall back to the untiered path.
        self.role = role
        self._models: Dict[str, Dict[int, LoadedModel]] = {}
        self._base_paths: Dict[str, str] = {}
        self._lock = threading.RLock()
        self._poll_interval_s = poll_interval_s
        self._watcher: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Per-model request batching (enable_batching): factory builds a
        # batcher around each newly-loaded version's predict, so
        # hot-swap keeps batching without a restart.
        self._batcher_factories: Dict[str, Callable] = {}
        self._batchers: Dict[str, Any] = {}
        # Reload circuit breakers, one per model (see _ReloadBreaker).
        self._reload_backoff_s = reload_backoff_s
        self._reload_backoff_cap_s = reload_backoff_cap_s
        self._breakers: Dict[str, _ReloadBreaker] = {}
        # Readiness: /readyz flips not-ready on begin_drain() (SIGTERM)
        # while /healthz stays live — the rolling-update contract.
        self._draining = threading.Event()
        # Requests inside predict() right now, across REST + gRPC +
        # direct callers — the graceful-drain quiescence signal.
        self._inflight = 0
        # Per-model in-flight cap covering EVERY path — including the
        # direct one (multi-row requests, prompts a batcher's accepts()
        # declines), which has no batcher queue to bound it: each such
        # request otherwise runs a whole device program on its own
        # transport thread, unbounded.  0 = unbounded.
        self._max_inflight = max(0, int(max_inflight))
        self._overload_retry_after_s = overload_retry_after_s
        self._inflight_by_model: Dict[str, int] = {}
        # Idempotency-key result dedup (see _DedupCache): both wire
        # faces pass the x-kft-idempotency-key header/metadata through
        # to predict(); the fleet router mints one per proxied POST.
        self._dedup = _DedupCache(dedup_capacity, dedup_ttl_s)

    # -- loading ----------------------------------------------------------

    def add_model(self, name: str, base_path: str) -> None:
        with self._lock:
            self._base_paths[name] = base_path
            self._models.setdefault(name, {})
        self.reload(name)

    def reload(self, name: str) -> bool:
        """Scan the base path; load new latest version, drop stale ones.
        Returns True if the served version changed.

        Load failures (corrupt checkpoint directory, bad loader) raise
        to the caller AND trip the model's circuit breaker: until its
        jittered exponential backoff expires, further reload() calls of
        the same version return False without touching the loader, so
        the version watcher cannot hot-loop on a bad artifact while the
        last-good version keeps serving."""
        base = self._base_paths[name]
        versions = list_versions(base)
        if not versions:
            log.warning("no versions for model %r under %s", name, base)
            return False
        latest = versions[-1]
        with self._lock:
            have = self._models[name]
            if latest in have:
                return False
            breaker = self._breakers.get(name)
            if breaker is None:
                breaker = self._breakers[name] = _ReloadBreaker(
                    self._reload_backoff_s, self._reload_backoff_cap_s)
        if not breaker.allow(latest):
            return False
        from kubeflow_tpu.runtime.prom import REGISTRY

        try:
            faults.fire("loader.load")
            predict, meta = load_version(base, latest)
        except Exception:
            breaker.record_failure(latest)
            REGISTRY.counter(
                RELOAD_FAILURES_TOTAL, RELOAD_FAILURES_HELP).inc(
                    model=name)
            REGISTRY.gauge(BREAKER_OPEN, BREAKER_OPEN_HELP).set(
                1, model=name)
            log.warning(
                "load of %r v%d failed; breaker open until +%.1fs "
                "(failure #%d), last-good version keeps serving",
                name, latest,
                max(0.0, breaker.open_until - faults.monotonic()),
                breaker.failures)
            raise
        breaker.record_success()
        REGISTRY.gauge(BREAKER_OPEN, BREAKER_OPEN_HELP).set(0, model=name)
        with self._lock:
            model = LoadedModel(
                name=name, version=latest, predict=predict, meta=meta
            )
            self._models[name][latest] = model
            # Keep only the latest (TF-Serving default version policy).
            for v in [v for v in self._models[name] if v != latest]:
                del self._models[name][v]
            old_batcher = self._batchers.pop(name, None)
            factory = self._batcher_factories.get(name)
        self._swap_batcher(name, factory, model, old_batcher)
        log.info("model %r now serving version %d", name, latest)
        return True

    def _swap_batcher(self, name, factory, model, old_batcher) -> None:
        """Close-old / build / install / close-displaced, the ONE
        batcher swap sequence (reload and enable_batching share it).

        Runs outside the server lock: close blocks on in-flight
        requests, which themselves may be waiting on get()/predict().
        The old batcher closes BEFORE the successor is built — a
        DecodeEngine owns a device-resident KV cache, and build-then-
        close would hold two at once (OOM on models sized to fit one);
        requests landing in the gap take the direct predict path.  A
        factory may decline a model (return None) — e.g. the serving
        entrypoint's factory engines LM models but leaves others on
        the direct path when micro-batching is off — which DISABLES
        batching rather than leaving the old batcher serving."""
        if old_batcher is not None:
            old_batcher.close()
        if factory is None or model is None:
            return
        batcher = factory(model)
        if batcher is not None:
            with self._lock:
                displaced = self._batchers.get(name)
                self._batchers[name] = batcher
            if displaced is not None and displaced is not batcher:
                displaced.close()  # lost a swap race; don't leak it

    def start_watcher(self) -> None:
        """Background version polling — the hot-swap path."""
        if self._watcher is not None:
            return
        self._stop.clear()

        def run():
            while not self._stop.wait(self._poll_interval_s):
                for name in list(self._base_paths):
                    try:
                        self.reload(name)
                    except Exception:
                        log.exception("reload of %r failed", name)

        self._watcher = threading.Thread(target=run, daemon=True,
                                         name="version-watcher")
        self._watcher.start()

    def enable_batching(
        self, name: str,
        factory: Callable[[LoadedModel], Any],
    ) -> None:
        """Coalesce concurrent predict() calls for ``name`` through a
        batcher built by ``factory(loaded_model)`` (anything with
        submit/close — MicroBatcher or BucketedLMBatcher).  The batcher
        is rebuilt around every newly-loaded version, so hot-swap keeps
        batching; explicit-version requests bypass it (debugging a
        pinned version should not share the live batch path).
        """
        with self._lock:
            self._batcher_factories[name] = factory
            model = None
            versions = self._models.get(name)
            if versions:
                model = versions[max(versions)]
            old_batcher = self._batchers.pop(name, None)
        self._swap_batcher(name, factory, model, old_batcher)

    def stop(self) -> None:
        self._stop.set()
        if self._watcher is not None:
            self._watcher.join(timeout=5)
            self._watcher = None
        with self._lock:
            batchers = list(self._batchers.values())
            self._batchers.clear()
        for b in batchers:
            b.close()

    # -- queries ----------------------------------------------------------

    def get(self, name: str, version: Optional[int] = None) -> LoadedModel:
        with self._lock:
            if name not in self._models or not self._models[name]:
                raise KeyError(f"model {name!r} not loaded")
            versions = self._models[name]
            if version is None:
                return versions[max(versions)]
            if version not in versions:
                raise KeyError(
                    f"model {name!r} has no version {version}; "
                    f"serving {sorted(versions)}"
                )
            return versions[version]

    def models(self) -> Dict[str, List[int]]:
        with self._lock:
            return {n: sorted(v) for n, v in self._models.items()}

    def has_model(self, name: str) -> bool:
        base, _ = split_model_adapter(name)
        with self._lock:
            return base in self._models

    def adapter_info(self) -> Dict[str, List[Dict[str, Any]]]:
        """Resident adapters per engine-served model — name, digest,
        slot index, pins — for the /readyz advertisement the router's
        digest-affinity pick reads (§5.11).  Models without an adapter
        registry are omitted."""
        with self._lock:
            batchers = dict(self._batchers)
        out: Dict[str, List[Dict[str, Any]]] = {}
        for name, batcher in batchers.items():
            info_fn = getattr(batcher, "adapter_info", None)
            if info_fn is None:
                continue
            info = info_fn()
            if info:
                out[name] = info
        return out

    def _resolve_adapter(
        self, name: str, inputs: Dict[str, Any],
    ) -> Tuple[str, Dict[str, Any]]:
        """Split a ``model@adapter`` request name (§5.11): the BASE
        name drives every lookup/metric/batcher route — one model, one
        engine, one program — while the adapter rides
        ``inputs["adapter"]`` for the engine to resolve against its
        registry at admission.  Plain names pass through untouched."""
        base, adapter = split_model_adapter(name)
        if adapter:
            inputs = dict(inputs)
            inputs["adapter"] = adapter
        return base, inputs

    # -- readiness / drain ------------------------------------------------

    def begin_drain(self) -> None:
        """Flip /readyz not-ready (SIGTERM).  Requests already accepted
        — and late arrivals from load balancers that have not yet seen
        the readiness flip — keep being served; only the readiness
        signal changes, so rolling updates drain without dropping."""
        if not self._draining.is_set():
            log.info("drain: readiness flipped to not-ready")
        self._draining.set()

    def draining(self) -> bool:
        return self._draining.is_set()

    def is_ready(self) -> bool:
        """Readiness = at least one model loaded and not draining —
        distinct from /healthz liveness, which stays true throughout a
        drain (a draining pod is alive, just not accepting NEW work)."""
        if self._draining.is_set():
            return False
        with self._lock:
            return any(self._models.values())

    def inflight(self) -> int:
        """Requests currently inside predict() plus accepted transport
        requests still being parsed (enter_request) — the graceful-
        drain quiescence signal."""
        with self._lock:
            return self._inflight

    def enter_request(self) -> None:
        """Transport-level in-flight bracket: the REST handler wraps
        its WHOLE dispatch (body read and parse included) so drain
        cannot conclude quiescence while an accepted connection is
        still deserializing the request it would then lose.  Nests
        with predict()'s own bracket — inflight() is a zero/nonzero
        quiescence signal, not a request count."""
        with self._lock:
            self._inflight += 1

    def exit_request(self) -> None:
        with self._lock:
            self._inflight -= 1

    def refresh_gauges(self) -> None:
        """Push the live load signals into the prom registry — called at
        scrape time by the /metrics route (a gauge the autoscaler reads
        must be current at the instant of the scrape, and in-flight has
        no natural write site that is not the predict hot path)."""
        from kubeflow_tpu.runtime.prom import REGISTRY

        with self._lock:
            total = self._inflight
            per_model = {n: self._inflight_by_model.get(n, 0)
                         for n in self._models}
        inflight = REGISTRY.gauge(INFLIGHT_GAUGE, INFLIGHT_HELP)
        inflight.set(total)
        for name, count in per_model.items():
            inflight.set(count, model=name)
        queue = REGISTRY.gauge(QUEUE_GAUGE, QUEUE_HELP)
        ratio = REGISTRY.gauge(CACHED_RATIO_GAUGE, CACHED_RATIO_HELP)
        spill = REGISTRY.gauge(SPILL_RATIO_GAUGE, SPILL_RATIO_HELP)
        cached_total = prompt_total = 0
        spill_used = spill_cap = 0
        any_engine = any_spill = False
        for name in per_model:
            stats = self.batcher_stats(name) or {}
            queue.set(stats.get("queue_depth", 0) or 0, model=name)
            if "cached_token_ratio" in stats:
                # Prefix-cache effectiveness (DecodeEngine models): the
                # fleet registry scrapes this per replica so operators
                # see cache hit rates across the whole fleet.
                any_engine = True
                ratio.set(stats["cached_token_ratio"], model=name)
                cached_total += stats.get("cached_prompt_tokens", 0)
                prompt_total += stats.get("prompt_tokens", 0)
            cap = stats.get("host_spill_blocks", 0) or 0
            if cap:
                # Host spill-tier occupancy (§5.10): same reset-with-
                # the-engine discipline as the cached ratio above.
                any_spill = True
                used = stats.get("host_tier_used", 0) or 0
                spill.set(round(used / cap, 4), model=name)
                spill_used += used
                spill_cap += cap
        if any_spill:
            spill.set(round(spill_used / spill_cap, 4))
        if any_engine:
            # The unlabeled aggregate must RESET with its engines: a
            # hot-reload rebuilds the engine with an empty cache, and
            # the fleet scrape reads this (first-sorted) series — a
            # stale pre-reload ratio would report a warm cache the
            # replica no longer has.
            ratio.set(round(cached_total / prompt_total, 4)
                      if prompt_total else 0.0)
        REGISTRY.gauge(READY_GAUGE, READY_HELP).set(
            1 if self.is_ready() else 0)

    def batcher_stats(self, name: str) -> Optional[Dict[str, Any]]:
        """Live stats of the model's batcher/engine (None when the model
        serves on the direct path) — the :stats REST route and the gRPC
        metadata face both read through here."""
        with self._lock:
            batcher = self._batchers.get(name)
        stats = getattr(batcher, "stats", None)
        return stats() if callable(stats) else None

    @staticmethod
    def _single_row(inputs: Dict[str, Any]) -> bool:
        """True when every input leaf carries exactly one example — the
        only shape a batcher entry can represent (each entry gets one
        result row back; multi-row requests go straight to predict)."""
        for v in inputs.values():
            if isinstance(v, str):
                continue  # routing metadata (e.g. "adapter"), not a leaf
            shape = getattr(v, "shape", None)
            if shape is None:
                v = np.asarray(v)
                shape = v.shape
            if len(shape) == 0 or shape[0] != 1:
                return False
        return True

    def predict(
        self, name: str, inputs: Dict[str, Any],
        version: Optional[int] = None,
        deadline: Optional[float] = None,
        idem_key: Optional[str] = None,
    ) -> Dict[str, Any]:
        """``deadline`` is an absolute faults.monotonic() instant: the
        batching planes enforce it in their queues and (the engine) mid-
        generation; the direct path checks it at entry only — a jitted
        whole-generation program cannot be interrupted, which is exactly
        why the engine owns the LM hot path.

        ``idem_key`` (the x-kft-idempotency-key header/metadata value)
        dedups retried requests: the first presentation executes, an
        in-flight duplicate attaches to that execution, and a completed
        duplicate is answered from the TTL'd result cache — so a retry
        after a dropped connection is answered, never re-run."""
        name, inputs = self._resolve_adapter(name, inputs)
        if idem_key:
            return self._predict_deduped(name, inputs, version,
                                         deadline, idem_key)
        return self._predict_admitted(name, inputs, version, deadline)

    def _predict_deduped(self, name, inputs, version, deadline,
                         idem_key):
        from kubeflow_tpu.runtime.prom import REGISTRY

        verdict, entry = self._dedup.begin(idem_key)
        if verdict != "new":
            with self._lock:
                label = name if name in self._models else "_unknown_"
            REGISTRY.counter(DEDUP_HITS_TOTAL, DEDUP_HITS_HELP).inc(
                model=label)
            if verdict == "inflight":
                # Attach to the primary (no second execution, no
                # second in-flight slot), bounded by OUR deadline —
                # the primary enforces its own.
                timeout = None if deadline is None else max(
                    0.0, deadline - faults.monotonic())
                if not entry["event"].wait(timeout):
                    raise DeadlineExceeded(
                        f"deadline expired waiting on the in-flight "
                        f"twin of idempotency key {idem_key!r}")
            if entry["err"] is not None:
                raise entry["err"]
            return entry["result"]
        try:
            result = self._predict_admitted(name, inputs, version,
                                            deadline)
        except BaseException as exc:
            # Failures are not cached: waiters get the error, the key
            # frees, and a later retry re-executes.
            self._dedup.fail(idem_key, entry, exc)
            raise
        self._dedup.finish(idem_key, entry, result)
        return result

    def _predict_admitted(
        self, name: str, inputs: Dict[str, Any],
        version: Optional[int], deadline: Optional[float],
    ) -> Dict[str, Any]:
        # Admission child span (trace context set by the transport
        # layer): covers the in-flight-cap verdict; a shed admission
        # records status="shed" so the trace is always tail-retained.
        ctx = tracing.current_ctx()
        t_adm = time.perf_counter() if ctx is not None else 0.0
        try:
            with self._lock:
                if self._max_inflight and self._inflight_by_model.get(
                        name, 0) >= self._max_inflight:
                    from kubeflow_tpu.runtime.prom import REGISTRY

                    REGISTRY.counter(SHED_TOTAL, SHED_HELP).inc(
                        batcher=f"{name}-inflight")
                    raise Overloaded(
                        f"model {name!r} at its in-flight cap "
                        f"({self._max_inflight})",
                        retry_after_s=self._overload_retry_after_s)
                self._inflight += 1
                self._inflight_by_model[name] = \
                    self._inflight_by_model.get(name, 0) + 1
        except Overloaded:
            self._record_admission(ctx, name, t_adm, status="shed")
            raise
        self._record_admission(ctx, name, t_adm)
        try:
            return self._predict(name, inputs, version, deadline)
        finally:
            with self._lock:
                self._inflight -= 1
                self._inflight_by_model[name] -= 1

    def _record_admission(self, ctx, name: str, t_adm: float,
                          status: str = "ok") -> None:
        """The one server.admission stamping site (span names are
        unique per module — span-discipline): shed and admitted
        verdicts both land here."""
        if ctx is not None:
            tracing.record_span(
                "server.admission", ctx, t_adm, time.perf_counter(),
                status=status, attrs={"model": name})

    def _predict(
        self, name: str, inputs: Dict[str, Any],
        version: Optional[int], deadline: Optional[float],
    ) -> Dict[str, Any]:
        if deadline is not None and faults.monotonic() >= deadline:
            raise DeadlineExceeded(
                f"deadline expired before dispatch of {name!r}")
        if version is None:
            # Convert list-typed payloads (raw REST JSON) to arrays ONCE
            # before the batched path touches them — _single_row,
            # _shape_sig, and the dispatch concatenate all consume the
            # same arrays instead of re-materializing the payload.
            converted = {
                k: v if isinstance(v, str) or hasattr(v, "shape")
                else np.asarray(v)
                for k, v in inputs.items()
            }
            # Bounded retry: a hot-swap or drain can close the batcher
            # between the lookup and submit — and close() now FAILS
            # queued entries with BatcherClosed instead of draining
            # them — so the second lap picks up the replacement built
            # by reload(), and a missing replacement falls through to
            # the direct path: an accepted request is never dropped by
            # a swap race.
            for _ in range(2):
                with self._lock:
                    batcher = self._batchers.get(name)
                if batcher is None or not self._single_row(converted):
                    break
                accepts = getattr(batcher, "accepts", None)
                if accepts is not None and not accepts(converted):
                    break  # e.g. prompt beyond the largest bucket
                try:
                    if deadline is None:
                        return batcher.submit(converted)
                    return batcher.submit(converted, deadline=deadline)
                except BatcherClosed:
                    continue
        model = self.get(name, version)
        if inputs.get("adapter"):
            # The direct path dispatches whole-generation programs with
            # the BASE weights only — silently answering an adapter
            # request with base output would be a wrong-tenant response,
            # strictly worse than failing (§5.11).
            raise AdapterNotFound(
                f"adapter {inputs['adapter']!r} requires the "
                f"continuous-batching engine; model {name!r} fell "
                f"through to the direct path")
        # Re-checked at the fallthrough: the request may have spent its
        # whole budget queued in a batcher that closed under it (drain,
        # swap race) — launching an uninterruptible whole-generation
        # program now would return a late 200 the caller abandoned.
        if deadline is not None and faults.monotonic() >= deadline:
            raise DeadlineExceeded(
                f"deadline expired before direct dispatch of {name!r}")
        return model.predict(inputs)

    def prefill_handoff(
        self, name: str, inputs: Dict[str, Any],
        deadline: Optional[float] = None,
    ) -> Dict[str, Any]:
        """Disaggregated serving, prefill tier: run the prompt's
        chunked prefill on this replica's DecodeEngine and return the
        result WITH its finished KV pages (``kv_handoff``) so a
        decode-tier replica can import them and stream the completion
        (the :prefill route).  Raises KeyError on unknown models and
        ValueError when the model has no engine.  Bracketed in the
        in-flight counts like any predict."""
        name, inputs = self._resolve_adapter(name, inputs)
        self.get(name)  # KeyError -> 404 on unknown names
        with self._lock:
            batcher = self._batchers.get(name)
        export_fn = getattr(batcher, "prefill_export", None)
        if export_fn is None:
            raise ValueError(
                f"model {name!r} has no decode engine "
                f"(:prefill requires the continuous-batching engine)")
        with self._lock:
            self._inflight += 1
            self._inflight_by_model[name] = \
                self._inflight_by_model.get(name, 0) + 1
        try:
            return export_fn(inputs, deadline=deadline)
        finally:
            with self._lock:
                self._inflight -= 1
                self._inflight_by_model[name] -= 1

    def fetch_kv(self, name: str,
                 inputs: Dict[str, Any]) -> Dict[str, Any]:
        """Hierarchical KV fetch (§5.10): look ``tokens`` up in the
        model's engine host spill tier and return the covered prefix's
        pages in engine export form, or a miss.  Raises KeyError on
        unknown models and ValueError when the model has no engine.
        A pure host-memory read — no in-flight bracket: a drain must
        not wait on a peer's failover fetch, and the fetch must keep
        answering WHILE this replica drains (the surviving session
        state is exactly what a peer needs then)."""
        name, _ = split_model_adapter(name)
        self.get(name)  # KeyError -> 404 on unknown names
        with self._lock:
            batcher = self._batchers.get(name)
        fetch_fn = getattr(batcher, "fetch_kv", None)
        if fetch_fn is None:
            raise ValueError(
                f"model {name!r} has no decode engine "
                f"(:fetch_kv requires the continuous-batching engine)")
        return fetch_fn(inputs)

    def generate_stream(
        self, name: str, inputs: Dict[str, Any],
        deadline: Optional[float] = None,
    ):
        """Streaming LM generation: (meta, iterator) from the model's
        DecodeEngine (the only batching plane with a streaming
        surface — see DecodeEngine.submit_stream).  Raises KeyError on
        unknown models and ValueError when the model has no engine:
        the static batchers dispatch whole-generation programs and
        cannot stream.  The iterator is bracketed in the in-flight
        counts (drain waits for live streams); callers must exhaust or
        close() it."""
        name, inputs = self._resolve_adapter(name, inputs)
        self.get(name)  # KeyError -> 404 on unknown names
        with self._lock:
            batcher = self._batchers.get(name)
        stream_fn = getattr(batcher, "submit_stream", None)
        if stream_fn is None:
            raise ValueError(
                f"model {name!r} has no streaming decode engine "
                f"(:generate requires the continuous-batching engine)")
        meta, stream = stream_fn(inputs, deadline=deadline)

        def bracketed():
            # Counted from first iteration (a generator closed before
            # its first next() never runs its finally, so an eager
            # increment could leak); the REST transport's own
            # enter_request bracket covers the gap.
            with self._lock:
                self._inflight += 1
                self._inflight_by_model[name] = \
                    self._inflight_by_model.get(name, 0) + 1
            try:
                for chunk in stream:
                    yield chunk
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._inflight_by_model[name] -= 1

        return meta, bracketed()


class MicroBatcher:
    """Coalesce concurrent requests into padded, pipelined device batches.

    Callers block in ``submit`` until their rows come back.  Batches are
    padded up to the next size in ``allowed_batch_sizes`` so the jitted
    predict fn compiles once per size, not once per request count —
    the TF-Serving batching-parameters idea, TPU-shaped.

    Dispatch is pipelined: ``in_flight`` executor threads each collect a
    batch and run predict concurrently, so while batch N's device call is
    in its (possibly high-latency) round trip, batch N+1 is already being
    assembled and dispatched.  With one executor the effective pipeline
    depth is 1 and throughput collapses to batch_size/latency — the
    round-2 failure mode.  Per-batch device results are converted to host
    numpy ONCE per output key (a single device->host transfer), then rows
    are handed out as views; the earlier per-request ``np.asarray`` did
    one transfer per request and serialized the whole batch on latency.

    Instrumentation: every dispatched batch records its occupied size in
    ``stats()`` — the effective-batch-size distribution is the first
    thing to look at when batcher throughput is below expectation.
    """

    def __init__(
        self,
        predict: Callable[[Dict[str, Any]], Dict[str, Any]],
        *,
        max_batch_size: int = 8,
        batch_timeout_s: float = 0.005,
        allowed_batch_sizes: Optional[List[int]] = None,
        in_flight: int = 2,
        max_queue_depth: int = 0,
        overload_retry_after_s: float = 1.0,
        name: str = "default",
        group_key: Optional[Callable[[Dict[str, Any]], Any]] = None,
        collate: Optional[Callable[
            [List[Dict[str, Any]]],
            "tuple[Dict[str, Any], List[Any]]"]] = None,
        finish: Optional[Callable[
            [Dict[str, Any], Any], Dict[str, Any]]] = None,
    ):
        # Batch-assembly hooks (all-or-none, enforced): `group_key`
        # replaces the shape signature — entries with equal keys may
        # share a device batch even when their shapes differ — and
        # `collate` then builds the stacked arrays from the raw inputs
        # (returning per-row metadata that `finish` uses to restore each
        # row's natural shape).  Without hooks, grouping is by exact
        # shape signature and collation is axis-0 concatenation — rows
        # of different shapes can never legally concatenate, which is
        # why cross-shape batching must bring its own collate; a collate
        # without finish would silently drop the per-row metas, so a
        # partial hook set is a construction error, not a latent one.
        hooks = {"group_key": group_key, "collate": collate,
                 "finish": finish}
        given = [k for k, v in hooks.items() if v is not None]
        if given and len(given) != len(hooks):
            missing = sorted(set(hooks) - set(given))
            raise ValueError(
                f"MicroBatcher batch-assembly hooks are all-or-none: "
                f"got {sorted(given)} without {missing}")
        self._predict = predict
        self._group_key = group_key
        self._collate = collate
        self._finish = finish
        self.allowed = sorted(allowed_batch_sizes or [1, 2, 4, 8])
        # A batch larger than the padding table would go to the device
        # unpadded and trigger a fresh XLA compile — the exact thing this
        # class exists to prevent — so the effective cap is the table max.
        self.max_batch_size = min(max_batch_size, self.allowed[-1])
        self.batch_timeout_s = batch_timeout_s
        self._lock = threading.Lock()
        # Pending entries live in per-shape-signature queues: dispatch is
        # O(#groups) per cycle (not a rescan of every pending entry), and
        # each shape group ages against its OWN oldest-entry deadline —
        # under sustained mixed-shape load a minority shape no longer
        # waits an extra full batch_timeout_s per cycle while majority
        # batches reset the clock.
        self._groups: Dict[Any, List[dict]] = {}
        self._next_deadline: Optional[float] = None
        self._flusher = threading.Condition(self._lock)
        self._stopped = False
        self._batch_sizes: Dict[int, int] = {}
        self._requests = 0
        # Bounded admission: > max_queue_depth pending entries shed new
        # submissions with Overloaded (fail-fast 429) instead of
        # queueing unboundedly; 0 = unbounded (library default — the
        # serving entrypoint configures a bound).
        self.max_queue_depth = max(0, int(max_queue_depth))
        self.overload_retry_after_s = overload_retry_after_s
        self._pending_total = 0
        self._shed = 0
        self._expired = 0
        # Per-stage dispatch-cycle accounting (seconds, cumulative) —
        # the first thing VERDICT r4 asked for when capacity came in 5x
        # under the device rate: queue_wait is oldest-entry age at
        # dispatch, the rest split one _process call.  overlap tracks
        # how many runners are actually inside _process concurrently
        # (pipeline depth achieved, not configured).
        self._cycle = {k: 0.0 for k in (
            "queue_wait", "collate", "pad", "predict", "to_host",
            "deliver")}
        self._in_process = 0
        self._max_in_process = 0
        from kubeflow_tpu.runtime.prom import REGISTRY

        # Registered at construction so the series exists on /metrics
        # from the first scrape — an idle or stuck batcher must show a
        # zero-count histogram, not 'no data'.  Effective batch size is
        # the first thing to look at when throughput is below
        # expectation (the round-2 failure mode was mean batch ~1).
        # `name` labels the series per batcher (a process may run one
        # per served model, like the serving-metric model= labels).
        self._metric_name = name
        self._size_hist = REGISTRY.histogram(
            "kft_serving_batch_size",
            "occupied micro-batch size at dispatch, by batcher",
            buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0),
        ).declare(batcher=name)
        self._shed_ctr = REGISTRY.counter(SHED_TOTAL, SHED_HELP)
        self._expired_ctr = REGISTRY.counter(EXPIRED_TOTAL, EXPIRED_HELP)
        self._runners = [
            threading.Thread(target=self._run, daemon=True,
                             name=f"microbatcher-{i}")
            for i in range(max(1, in_flight))
        ]
        for r in self._runners:
            r.start()

    def submit(self, inputs: Dict[str, Any],
               deadline: Optional[float] = None) -> Dict[str, Any]:
        """One logical request of batch-dim 1 ([1, ...] rows).

        Enforced here (loudly, to the offending caller only): each
        entry gets exactly ONE result row back at delivery, so a
        multi-row submission would silently lose every row but the
        first.  Hooked batchers (group_key/collate) validate in their
        own submit (e.g. BucketedLMBatcher).

        ``deadline`` (absolute faults.monotonic() instant): expired-on-
        arrival raises DeadlineExceeded immediately; a queued entry
        whose deadline passes pre-dispatch is failed by the runner
        sweep instead of being dispatched."""
        # Trace context captured on the caller's thread (the transport
        # set it); the runner threads stamp queue-wait/dispatch spans
        # from these perf readings at dispatch time.  None when
        # tracing is off — every span site below is gated on it.
        trace_ctx = tracing.current_ctx()
        entry = {"inputs": inputs,
                 "t": faults.monotonic(), "deadline": deadline,
                 "trace": trace_ctx,
                 "t_perf": time.perf_counter()
                 if trace_ctx is not None else 0.0,
                 "event": threading.Event(), "out": None, "err": None}
        if deadline is not None and faults.monotonic() >= deadline:
            with self._lock:
                self._expired += 1
            self._expired_ctr.inc(batcher=self._metric_name)
            raise DeadlineExceeded(
                f"deadline expired before batcher "
                f"{self._metric_name!r} admission")
        # Signature computed once, outside the lock: np.asarray on
        # list-typed payloads (the REST JSON path) is O(payload).
        if self._group_key is not None:
            sig = self._group_key(inputs)
        else:
            sig = self._shape_sig(inputs)
            for (key, shape, _) in sig:
                if not shape or shape[0] != 1:
                    raise ValueError(
                        f"MicroBatcher.submit takes one row per call: "
                        f"input {key!r} has shape {shape}; submit rows "
                        f"separately")
        with self._lock:
            if self._stopped:
                # After close() the runner threads are gone; an entry
                # appended now would wait forever on its Event.
                raise BatcherClosed(f"batcher {self._metric_name!r} "
                                    "is closed")
            if self.max_queue_depth \
                    and self._pending_total >= self.max_queue_depth:
                # Fail fast: under overload a bounded 429 beats an
                # unbounded queue whose every entry times out.
                self._shed += 1
                self._shed_ctr.inc(batcher=self._metric_name)
                raise Overloaded(
                    f"batcher {self._metric_name!r} queue full "
                    f"({self._pending_total} pending)",
                    retry_after_s=self.overload_retry_after_s)
            self._groups.setdefault(sig, []).append(entry)
            self._pending_total += 1
            self._flusher.notify()
        entry["event"].wait()
        if entry["err"] is not None:
            raise entry["err"]
        return entry["out"]

    def stats(self) -> Dict[str, Any]:
        """Effective-batch-size distribution over dispatched batches,
        plus the mean per-batch cost of each dispatch-cycle stage and
        the achieved pipeline depth (max concurrent _process calls)."""
        cycle, extra = locked_snapshot(
            self._lock, self._cycle,
            lambda: {"hist": dict(sorted(self._batch_sizes.items())),
                     "requests": self._requests,
                     "max_overlap": self._max_in_process,
                     "queue_depth": self._pending_total,
                     "shed": self._shed, "expired": self._expired})
        hist, requests = extra["hist"], extra["requests"]
        max_overlap = extra["max_overlap"]
        batches = sum(hist.values())
        return {
            "requests": requests,
            "batches": batches,
            "batch_size_hist": hist,
            "mean_batch_size": round(requests / batches, 2) if batches
            else 0.0,
            "cycle_profile_ms": {
                k: round(v / batches * 1e3, 3) for k, v in cycle.items()
            } if batches else {},
            "max_pipeline_depth": max_overlap,
            "queue_depth": extra["queue_depth"],
            "shed": extra["shed"],
            "deadline_expired": extra["expired"],
        }

    def close(self) -> None:
        """Refuse new work AND fail queued-undispatched entries with
        BatcherClosed (batches already dispatched complete normally) —
        the same contract as DecodeEngine.close.  Failing instead of
        draining keeps every path consistent: ModelServer.predict
        catches BatcherClosed and retries the replacement batcher (hot
        swap) or falls through to the direct path (drain/stop), so an
        accepted request is never dropped — it just stops waiting on a
        dying queue."""
        with self._lock:
            self._stopped = True
            queued = [e for q in self._groups.values() for e in q]
            self._groups.clear()
            self._pending_total = 0
            self._flusher.notify_all()
        err = BatcherClosed(f"batcher {self._metric_name!r} is closed")
        for e in queued:
            e["err"] = err
            e["event"].set()
        for r in self._runners:
            r.join(timeout=5)

    @staticmethod
    def _shape_sig(inputs: Dict[str, Any]):
        sig = []
        for k, v in sorted(inputs.items()):
            a = np.asarray(v)  # once: O(payload) for list-typed values
            sig.append((k, a.shape, a.dtype.str))
        return tuple(sig)

    def _take_batch_locked(
            self, expired: List[dict]) -> Optional[List[dict]]:
        """Pop the next dispatchable shape group, or None with no group
        ready yet (caller waits until the earliest group deadline).

        Only rows of one shape signature share a device batch (they are
        concatenated on axis 0) — without the grouping, one odd-shaped
        request poisoned the whole batch with a concatenate error.  A
        group becomes dispatchable when it is full or its OLDEST entry
        has aged past batch_timeout_s (or at shutdown, immediately);
        among dispatchable groups the oldest head goes first — full
        groups get no priority over expired ones, or a saturating
        majority shape would starve minority shapes forever (their
        clients block in submit with no timeout).

        Request deadlines are swept here too: entries whose deadline
        (policy clock) has passed move into ``expired`` — the caller
        fails them with DeadlineExceeded outside the lock — and pending
        request deadlines join the wakeup computation so an expiring
        entry is failed promptly even when no batch deadline is near.
        """
        # ONE skewable policy clock for both request deadlines and
        # batch-window aging: a seeded skew must age queued entries
        # exactly like it expires deadlines, or the two sweeps drift.
        now = faults.monotonic()
        pnow = now
        best_sig, best_t = None, None
        self._next_deadline = None

        def note_wake(at: float) -> None:
            if self._next_deadline is None or at < self._next_deadline:
                self._next_deadline = at

        for sig in list(self._groups):
            q = self._groups[sig]
            keep = []
            for e in q:
                d = e["deadline"]
                if d is not None and d <= pnow:
                    expired.append(e)
                    continue
                keep.append(e)
                if d is not None:
                    note_wake(d)
            if len(keep) != len(q):
                self._pending_total -= len(q) - len(keep)
                if not keep:
                    del self._groups[sig]
                    continue
                self._groups[sig] = q = keep
            deadline = q[0]["t"] + self.batch_timeout_s
            if (len(q) >= self.max_batch_size or deadline <= now
                    or self._stopped):
                if best_t is None or q[0]["t"] < best_t:
                    best_sig, best_t = sig, q[0]["t"]
            else:
                note_wake(deadline)
        if best_sig is None:
            return None
        q = self._groups[best_sig]
        batch, rest = q[:self.max_batch_size], q[self.max_batch_size:]
        if rest:
            self._groups[best_sig] = rest
        else:
            del self._groups[best_sig]
        self._pending_total -= len(batch)
        return batch

    def _record_queue_wait(self, entries: List[dict],
                           status: str = "ok") -> None:
        """The one batcher.queue_wait stamping site (span names are
        unique per module — span-discipline): dispatched and
        deadline-expired entries both land here."""
        if not any(e["trace"] is not None for e in entries):
            return
        now_perf = time.perf_counter()
        for e in entries:
            if e["trace"] is not None:
                tracing.record_span(
                    "batcher.queue_wait", e["trace"], e["t_perf"],
                    now_perf, status=status,
                    attrs={"batcher": self._metric_name})

    def _run(self) -> None:
        while True:
            expired: List[dict] = []
            with self._lock:
                batch = None
                while batch is None and not expired:
                    if not self._groups:
                        if self._stopped:
                            return
                        self._flusher.wait()
                        continue
                    batch = self._take_batch_locked(expired)
                    if batch is None and not expired:
                        # Sleep only until the earliest group's own
                        # deadline — each shape ages independently —
                        # or the earliest request deadline, whichever
                        # comes first.
                        self._flusher.wait(
                            timeout=None if self._next_deadline is None
                            else max(0.0, self._next_deadline
                                     - faults.monotonic()))
                if expired:
                    self._expired += len(expired)
                if batch is not None:
                    # stats() and the scrapeable histogram record the
                    # same quantity at the same site.
                    self._batch_sizes[len(batch)] = \
                        self._batch_sizes.get(len(batch), 0) + 1
                    self._requests += len(batch)
                    self._size_hist.observe(
                        float(len(batch)), batcher=self._metric_name)
                    self._cycle["queue_wait"] += (
                        faults.monotonic() - batch[0]["t"])
                    self._in_process += 1
                    self._max_in_process = max(self._max_in_process,
                                               self._in_process)
            if expired:
                # Failed OUTSIDE the lock: waking a waiter is not queue
                # work, and the swept entries are no longer reachable
                # from the groups.
                self._expired_ctr.inc(len(expired),
                                      batcher=self._metric_name)
                err = DeadlineExceeded(
                    f"deadline expired in batcher "
                    f"{self._metric_name!r} queue")
                self._record_queue_wait(expired,
                                        status="deadline_expired")
                for e in expired:
                    e["err"] = err
                    e["event"].set()
            if batch is None:
                continue
            self._record_queue_wait(batch)
            try:
                self._process(batch)
            finally:
                with self._lock:
                    self._in_process -= 1

    def _pad_size(self, n: int) -> int:
        for size in self.allowed:
            if n <= size:
                return size
        return self.allowed[-1]

    def _process(self, batch: List[dict]) -> None:
        try:
            # Chaos hook: a scripted stall here simulates a wedged
            # dispatch (queue builds, deadlines expire, admission
            # sheds); a scripted raise takes the same propagate-to-
            # waiters path as a device failure.  See
            # kubeflow_tpu/testing/faults.py.
            faults.fire("batcher.dispatch")
            # Stage timings accumulate LOCALLY and merge into
            # self._cycle under the queue lock at the end — _process
            # runs on dispatch threads while stats()/the /metrics
            # scrape snapshot the counters, and an unlocked float +=
            # against that read shows torn cycle profiles (impossible
            # occupancy was the observed symptom).
            cyc = {k: 0.0 for k in self._cycle}
            t0 = time.perf_counter()
            metas: Optional[List[Any]] = None
            n = len(batch)
            size = self._pad_size(n)
            if self._collate is not None:
                stacked, metas = self._collate(
                    [e["inputs"] for e in batch])
                t1 = time.perf_counter()
                cyc["collate"] += t1 - t0
                if size > n:
                    stacked = {
                        k: np.concatenate(
                            [v] + [v[:1]] * (size - n), axis=0
                        ) for k, v in stacked.items()
                    }
                t2 = time.perf_counter()
                cyc["pad"] += t2 - t1
            else:
                # One preallocated buffer per key, filled row-by-row and
                # tail-padded in place: the earlier concatenate-of-N
                # (plus a second concatenate for padding) built the
                # batch from dozens of small Python-level array ops —
                # measured 38 ms collate + 62 ms pad per batch-64 cycle
                # under a 192-client GIL storm, pure assembly overhead
                # on the serving hot path.
                stacked = {}
                pad_s = 0.0
                for k in batch[0]["inputs"].keys():
                    first = np.asarray(batch[0]["inputs"][k])
                    out = np.empty((size,) + first.shape[1:],
                                   first.dtype)
                    out[0] = first[0]
                    for i, e in enumerate(batch[1:], 1):
                        out[i] = np.asarray(e["inputs"][k])[0]
                    if size > n:
                        tp = time.perf_counter()
                        out[n:] = out[0]
                        pad_s += time.perf_counter() - tp
                    stacked[k] = out
                t2 = time.perf_counter()
                cyc["collate"] += t2 - t0 - pad_s
                cyc["pad"] += pad_s
            outputs = self._predict(stacked)
            t3 = time.perf_counter()
            cyc["predict"] += t3 - t2
            # One device->host transfer per output key, then row views.
            host = {k: np.asarray(v) for k, v in outputs.items()}
            t4 = time.perf_counter()
            cyc["to_host"] += t4 - t3
            for i, e in enumerate(batch):
                row = {k: v[i:i + 1] for k, v in host.items()}
                if metas is not None and self._finish is not None:
                    row = self._finish(row, metas[i])
                e["out"] = row
                e["event"].set()
            t5 = time.perf_counter()
            cyc["deliver"] += t5 - t4
            with self._lock:
                for k, v in cyc.items():
                    self._cycle[k] += v
            # Batch-assembly span per traced entry: the whole dispatch
            # cycle (collate -> pad -> predict -> deliver) each row
            # rode, annotated with the occupied/padded batch shape.
            for e in batch:
                if e["trace"] is not None:
                    tracing.record_span(
                        "batcher.dispatch", e["trace"], t0, t5,
                        attrs={"batcher": self._metric_name,
                               "batch_size": n, "padded_to": size})
        except Exception as exc:
            # Propagate to all waiters still pending.  Rows already
            # delivered (event set) keep their results — a `finish`
            # hook raising on row i must not retroactively poison rows
            # 0..i-1, whose waiters may not have woken yet.
            for e in batch:
                if not e["event"].is_set():
                    e["err"] = exc
                    e["event"].set()


class BucketedLMBatcher:
    """Mixed-length LM decode batching: one queue, pad at dispatch.

    The MicroBatcher shares a device batch only among requests of one
    shape signature — correct (concatenation needs it), but it means
    mixed-length prompts NEVER coalesce and concurrent clients fall
    back to batch-1 throughput.  Left-padding fixes that:
    models/generate.py masks the pad keys and offsets rope so a padded
    row with its real length in ``prompt_len`` decodes exactly as it
    would alone, which makes ANY two prompts batch-compatible.

    So all requests share ONE queue, and padding happens at DISPATCH:
    the batch pads to the smallest bucket covering its longest member
    (bucket promotion).  Padding each prompt to its own bucket at
    submit time — the obvious design — re-splits the clients across
    per-bucket programs: measured on-chip, an 8-client mixed-length
    workload ran at mean batch 2.67 and ~5x below the uniform-length
    req/s, because every dispatch costs a full device round trip no
    matter how few rows it carries.  Promotion buys full batches at a
    padding cost paid on prefill FLOPs AND on every decode step:
    generate() sizes the KV cache from the padded width, so each step
    of a promoted row attends over the batch bucket's key span, not
    its own.  The bound is the largest bucket a co-batched prompt
    occupies (not the bucket spacing) — a losing trade only when the
    length distribution is wide and batched decode is compute-bound,
    and a winning one whenever round trips or batch count dominate,
    as in interactive decode (measured ~6x at the bench config).

    Buckets still bound the program count: one jitted generate program
    per (bucket, allowed batch size) that actually occurs, compiled on
    first use.  A uniform-length workload pads to its own bucket and
    behaves exactly as before.

    Promotion is BOUNDED (VERDICT r4 item 7): unbounded promotion is a
    cliff on a wide length spread — a 128-token prompt co-batched with
    a 4096-token one pays the 4096 bucket's KV span on every decode
    step (measured on-chip: see bench.py's promotion-cost probe).
    ``max_promotion_factor`` partitions the buckets into bands whose
    largest/smallest ratio stays <= the factor; only requests in the
    same band share a queue, so a request's worst-case padded bucket is
    bounded at factor x its own.  The trade is explicit: more bands =
    tighter per-request KV bound but fewer co-batching partners (a
    uniform workload is unaffected; a maximally-wide one degrades
    toward per-band batching).  ``None`` restores the single queue.
    """

    def __init__(
        self,
        predict: Callable[[Dict[str, Any]], Dict[str, Any]],
        *,
        buckets: Optional[List[int]] = None,
        pad_token: int = 0,
        max_promotion_factor: Optional[float] = 4.0,
        **batcher_kwargs,
    ):
        self.buckets = sorted(buckets or [32, 64, 128, 256, 512, 1024])
        self.pad_token = pad_token
        # Band id per bucket: a new band starts when the bucket exceeds
        # factor x the band's smallest member.
        self._band: Dict[int, int] = {}
        if max_promotion_factor is None:
            self._band = {b: 0 for b in self.buckets}
        else:
            band, band_min = -1, None
            for b in self.buckets:
                if band_min is None or b > band_min * max_promotion_factor:
                    band, band_min = band + 1, b
                self._band[b] = band
        self._inner = MicroBatcher(
            predict,
            group_key=lambda inputs: (
                "lm", self._band[self.bucket_for(
                    np.asarray(inputs["tokens"]).shape[-1])]),
            collate=self._collate,
            finish=self._strip,
            **batcher_kwargs)

    def _collate(self, rows: List[Dict[str, Any]]):
        """Stack raw single-row submissions, left-padding every prompt
        to the batch bucket (smallest bucket >= the longest prompt).

        A per-request ``max_new_tokens`` never reaches the device (the
        generate program bakes the config budget in); it rides the
        per-row meta so _strip trims the surplus on the way out — the
        same budget contract as the DecodeEngine and the direct path,
        minus the decode compute savings only the engine can deliver.
        """
        tokens = [np.asarray(r["tokens"]) for r in rows]
        lengths = [t.shape[1] for t in tokens]
        bucket = self.bucket_for(max(lengths))
        padded = [
            np.concatenate(
                [np.full((1, bucket - n), self.pad_token, t.dtype), t],
                axis=1) if bucket > n else t
            for t, n in zip(tokens, lengths)
        ]
        stacked = {
            "tokens": np.concatenate(padded, axis=0),
            "prompt_len": np.asarray(lengths, np.int32),
        }
        meta = [
            (bucket - n, n,
             max(1, int(np.asarray(r["max_new_tokens"]).reshape(())))
             if r.get("max_new_tokens") is not None else None)
            for r, n in zip(rows, lengths)
        ]
        return stacked, meta

    # Output keys aligned to the FULL padded position axis (pad keys at
    # the left, like the input tokens), stripped per-row on the way
    # out.  Any NEW per-position output a loader grows MUST either be
    # added here (if it spans the padded prompt+completion axis) or be
    # returned pad-free by the loader (e.g. per-NEW-token logprobs of
    # shape [b, new] carry no pad and must NOT be listed) — an
    # unlisted padded key returns silently left-padded.
    _POSITIONAL_KEYS = ("tokens",)

    @classmethod
    def _strip(cls, row: Dict[str, Any], meta) -> Dict[str, Any]:
        pad, prompt_len, new = meta

        def cut(v):
            if pad:
                v = v[:, pad:]
            if new is not None:
                v = v[:, : prompt_len + new]  # per-request budget trim
            return v

        return {
            k: (cut(v) if k in cls._POSITIONAL_KEYS else v)
            for k, v in row.items()
        }

    def bucket_for(self, length: int) -> int:
        for b in self.buckets:
            if length <= b:
                return b
        raise ValueError(
            f"prompt length {length} exceeds largest bucket "
            f"{self.buckets[-1]}")

    def accepts(self, inputs: Dict[str, Any]) -> bool:
        """ModelServer routing hook: prompts beyond the largest bucket
        fall back to the direct predict path (they served fine before
        batching was enabled; enabling it must not break them).  Seeded
        requests also go direct: all rows of a batched generate program
        share one sample stream, so a per-request seed can only be
        honored unbatched (the DecodeEngine, with per-slot keys, keeps
        them batched)."""
        if inputs.get("seed") is not None:
            return False
        tokens = np.asarray(inputs.get("tokens", ()))
        length = tokens.shape[-1] if tokens.ndim else 0
        return bool(length and length <= self.buckets[-1])

    def submit(self, inputs: Dict[str, Any],
               deadline: Optional[float] = None) -> Dict[str, Any]:
        """One logical request: tokens [t] or [1, t] (the MicroBatcher
        hands each entry exactly one result row back, so multi-row
        submissions would silently lose rows — rejected up front)."""
        tokens = np.asarray(inputs["tokens"])
        if tokens.ndim == 1:
            tokens = tokens[None]
        n, length = tokens.shape
        if n != 1:
            raise ValueError(
                f"BucketedLMBatcher.submit takes one prompt per call "
                f"(got batch dim {n}); submit rows separately")
        self.bucket_for(length)  # reject oversize up front, pre-queue
        # Raw tokens go into the shared queue; _collate pads the whole
        # batch to one bucket at dispatch and _strip restores this
        # row's natural shape on the way out.  A per-request
        # max_new_tokens rides along as row meta (never a device
        # input): _strip trims the surplus of the config budget.
        row = {"tokens": tokens}
        if inputs.get("max_new_tokens") is not None:
            row["max_new_tokens"] = inputs["max_new_tokens"]
        return self._inner.submit(row, deadline=deadline)

    def stats(self) -> Dict[str, Any]:
        return self._inner.stats()

    def close(self) -> None:
        self._inner.close()
