"""REST front-end implementing the reference http-proxy wire contract.

Routes, request keys, b64 handling, and response shapes mirror
components/k8s-model-server/http-proxy/server.py:283-297 (route table),
:208-249 (PredictHandler: {"instances": [...]} -> {"predictions": [...]}),
:177-186 (decode_b64_if_needed), :200-206 (MetadataHandler) — so clients
written against the reference proxy work unchanged.  The gRPC hop behind
the proxy is gone: the model lives in this process on the TPU, the REST
layer calls it through ModelServer (optionally via the MicroBatcher).

Implementation is stdlib http.server (threaded): zero extra deps, and the
serving container stays a single process.
"""

from __future__ import annotations

import base64
import json
import logging
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kubeflow_tpu.runtime import tracing
from kubeflow_tpu.serving.adapters import split_model_adapter
from kubeflow_tpu.serving.errors import DeadlineExceeded, Overloaded
from kubeflow_tpu.serving.model_server import ModelServer
from kubeflow_tpu.testing import faults

log = logging.getLogger(__name__)

WELCOME = "kubeflow-tpu model server"

_ROUTES = [
    ("GET", re.compile(r"^/model/(?P<name>[^/:]+):metadata$"), "metadata"),
    ("GET", re.compile(r"^/model/(?P<name>[^/:]+):stats$"), "stats"),
    ("POST", re.compile(r"^/model/(?P<name>[^/:]+):predict$"), "predict"),
    ("POST", re.compile(r"^/model/(?P<name>[^/:]+):classify$"), "classify"),
    # Streaming LM generation (DecodeEngine models only): chunked
    # NDJSON — one meta line, {"tokens": [...]} lines as the engine
    # emits, a terminal done/error line.  The resume_tokens body key is
    # the mid-generation-failover payload the fleet router replays
    # with (docs §5.6).
    ("POST", re.compile(r"^/model/(?P<name>[^/:]+):generate$"),
     "generate"),
    # Disaggregated serving, prefill tier: run the prompt's chunked
    # prefill and answer with the finished KV pages as a wire-encoded
    # ``kv_handoff`` payload (block-page list, docs §5.9) the router
    # forwards into a decode-tier :generate body.
    ("POST", re.compile(r"^/model/(?P<name>[^/:]+):prefill$"),
     "prefill"),
    # Hierarchical KV, fetch tier (§5.10): answer with this replica's
    # spilled/parked pages for a session prefix as a wire-encoded
    # ``kv_handoff``, or {"kv_handoff": null} on a miss.  The fleet
    # router's failover replay asks surviving peers here BEFORE
    # falling back to resume-by-recompute.
    ("POST", re.compile(r"^/model/(?P<name>[^/:]+):fetch_kv$"),
     "fetch_kv"),
    ("POST", re.compile(
        r"^/model/(?P<name>[^/:]+)/version/(?P<version>\d+):predict$"),
     "predict"),
    ("POST", re.compile(
        r"^/model/(?P<name>[^/:]+)/version/(?P<version>\d+):classify$"),
     "classify"),
    ("GET", re.compile(r"^/$"), "index"),
    ("GET", re.compile(r"^/healthz$"), "health"),
    # Readiness (load-balancer signal) is deliberately a DIFFERENT
    # route from liveness: /readyz flips 503 during SIGTERM drain so
    # rolling updates stop routing here, while /healthz stays 200 so
    # the kubelet does not kill a pod that is busy draining.
    ("GET", re.compile(r"^/readyz$"), "ready"),
    ("GET", re.compile(r"^/metrics$"), "metrics"),
    # Retained request traces (tail-sampled spans: admission, queue
    # wait, prefill chunks, decode — see runtime/tracing.py); rendered
    # by `kubeflow-tpu trace list|show`.  Unknown /debug/* paths fall
    # through to the drained-body 404 like any unrouted request.
    ("GET", re.compile(r"^/debug/traces$"), "traces"),
]


IDEMPOTENCY_HEADER = "x-kft-idempotency-key"


def parse_deadline_ms(body: Dict[str, Any]) -> Optional[float]:
    """``deadline_ms`` body key -> absolute policy-clock instant (or
    None).  Shared by predict/classify and the streaming generate
    route so every POST surface validates deadlines identically."""
    deadline_ms = body.get("deadline_ms")
    if deadline_ms is None:
        return None
    try:
        deadline_ms = float(deadline_ms)
    except (TypeError, ValueError):
        raise ValueError(
            f"deadline_ms must be a number, got "
            f"{deadline_ms!r}") from None
    # NaN would sail through `<= 0` and then lose every later
    # comparison — a deadline the client believes is set but
    # nothing enforces.
    if not math.isfinite(deadline_ms) or deadline_ms <= 0:
        raise ValueError(
            f"deadline_ms must be a positive finite number, "
            f"got {deadline_ms}")
    return faults.monotonic() + deadline_ms / 1e3


def _enc_arr(a: np.ndarray) -> Dict[str, Any]:
    return {"b64": base64.b64encode(
                np.ascontiguousarray(a).tobytes()).decode(),
            "shape": list(a.shape), "dtype": str(a.dtype)}


def _dec_arr(d: Any) -> np.ndarray:
    if not isinstance(d, dict) or "b64" not in d:
        raise ValueError("kv_handoff array must be "
                         "{b64, shape, dtype}")
    try:
        raw = base64.b64decode(d["b64"])
        arr = np.frombuffer(raw, dtype=np.dtype(str(d["dtype"])))
        return arr.reshape([int(s) for s in d["shape"]])
    except (ValueError, TypeError, KeyError) as e:
        raise ValueError(f"malformed kv_handoff array: {e}") from None


def encode_kv_handoff(payload: Dict[str, Any]) -> Dict[str, Any]:
    """Engine-form KV handoff (numpy page stacks, serving/engine.py
    _attach_export) -> JSON wire form: each array becomes
    {b64, shape, dtype}.  The router never decodes this — it forwards
    the :prefill response's payload verbatim into the decode-tier
    :generate body; only the two engines' ends touch the bytes."""
    def enc_side(side):
        if isinstance(side, dict):  # int8: values + scale
            return {"values": _enc_arr(side["values"]),
                    "scale": _enc_arr(side["scale"])}
        return _enc_arr(side)

    return {"block_tokens": int(payload["block_tokens"]),
            "tokens_covered": int(payload["tokens_covered"]),
            "k": enc_side(payload["k"]),
            "v": enc_side(payload["v"])}


def decode_kv_handoff(wire: Any) -> Dict[str, Any]:
    """Wire form -> the engine's normalized import form (the engine
    re-validates geometry/dtype against its own pool)."""
    if not isinstance(wire, dict):
        raise ValueError("kv_handoff must be an object")

    def dec_side(side):
        if isinstance(side, dict) and "values" in side:
            return {"values": _dec_arr(side.get("values")),
                    "scale": _dec_arr(side.get("scale"))}
        return _dec_arr(side)

    return {"block_tokens": int(wire.get("block_tokens", 0)),
            "k": dec_side(wire.get("k")),
            "v": dec_side(wire.get("v"))}


def decode_b64_if_needed(value: Any) -> Any:
    """Recursively decode {"b64": "..."} leaves (reference server.py:177)."""
    if isinstance(value, dict):
        if len(value) == 1 and "b64" in value:
            return np.frombuffer(
                base64.b64decode(value["b64"]), dtype=np.uint8
            )
        return {k: decode_b64_if_needed(v) for k, v in value.items()}
    if isinstance(value, list):
        return [decode_b64_if_needed(v) for v in value]
    return value


def instances_to_inputs(
    instances: List[Any], input_names: Optional[List[str]] = None
) -> Dict[str, np.ndarray]:
    """Column-ize row-major instances, as the reference did per-column
    (server.py:240-242).  Non-dict rows bind to the signature's sole
    input."""
    if not isinstance(instances, (list, tuple)) or not instances:
        raise ValueError("'instances' must be a non-empty list")
    first = instances[0]
    if isinstance(first, dict):
        columns = list(first.keys())
        return {
            c: np.stack([np.asarray(row[c]) for row in instances])
            for c in columns
        }
    if input_names and len(input_names) == 1:
        name = input_names[0]
    else:
        raise ValueError(
            "non-dict instances require a single-input signature"
        )
    return {name: np.stack([np.asarray(row) for row in instances])}


def outputs_to_predictions(outputs: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Row-ize output columns back to per-instance dicts
    (reference server.py:246-248)."""
    arrays = {k: np.asarray(v) for k, v in outputs.items()}
    n = next(iter(arrays.values())).shape[0]
    return [
        {k: v[i].tolist() for k, v in arrays.items()} for i in range(n)
    ]


class ServingAPI:
    """Transport-independent request handling (shared by tests + HTTP)."""

    def __init__(self, server: ModelServer):
        self.server = server

    def metadata(self, name: str) -> Dict[str, Any]:
        model = self.server.get(name)
        return {
            "model_spec": {"name": name, "version": str(model.version)},
            "metadata": {
                "signature": model.meta.get("signature", {}),
                "loader": model.meta.get("loader"),
            },
        }

    def stats(self, name: str) -> Dict[str, Any]:
        """Live batching-plane stats for one model: the DecodeEngine's
        slot occupancy / tokens-per-sec / queue depth / per-token
        latency, or a batcher's dispatch profile (null on the direct
        path)."""
        model = self.server.get(name)  # 404 on unknown names
        return {
            "model_spec": {"name": name, "version": str(model.version)},
            "batcher": self.server.batcher_stats(name),
        }

    def predict(
        self, name: str, body: Dict[str, Any],
        version: Optional[int] = None,
        idem_key: Optional[str] = None,
    ) -> Dict[str, Any]:
        instances = body.get("instances")
        if instances is None:
            raise ValueError("Request json object must use the key: instances")
        # Per-request deadline: {"deadline_ms": 500, "instances": [...]}
        # becomes an absolute policy-clock instant enforced in the
        # batching planes (queued AND, on the engine, mid-generation).
        # Expiry surfaces as DeadlineExceeded -> HTTP 504.
        deadline = parse_deadline_ms(body)
        instances = decode_b64_if_needed(instances)
        # ``model@adapter`` names (§5.11): the signature lookup needs
        # the BASE model; ModelServer.predict re-splits the full name
        # to thread the adapter into the engine admission.
        model = self.server.get(split_model_adapter(name)[0], version)
        sig_inputs = list(
            model.meta.get("signature", {}).get("inputs", []) or []
        )
        inputs = instances_to_inputs(instances, sig_inputs or None)
        outputs = self.server.predict(name, inputs, version,
                                      deadline=deadline,
                                      idem_key=idem_key)
        return {"predictions": outputs_to_predictions(outputs)}

    def generate(self, name: str, body: Dict[str, Any]):
        """Streaming generation admission: (meta, iterator) from the
        model's DecodeEngine.  Body keys: ``tokens`` (the prompt),
        optional ``max_new_tokens`` / ``seed`` / ``prompt_len`` /
        ``deadline_ms`` / ``resume_tokens`` (the router's failover
        payload — tokens a prior attempt already delivered)."""
        tokens = body.get("tokens")
        if tokens is None:
            raise ValueError("Request json object must use the key: tokens")
        deadline = parse_deadline_ms(body)
        inputs: Dict[str, Any] = {"tokens": np.asarray(tokens, np.int32)}
        for key in ("max_new_tokens", "seed", "prompt_len",
                    "resume_tokens", "park_kv"):
            if body.get(key) is not None:
                inputs[key] = body[key]
        if body.get("kv_handoff") is not None:
            # Disaggregated decode tier: a prefill replica's exported
            # pages ride the body; the engine imports them and chunk-
            # prefills only the uncovered suffix.
            inputs["kv_handoff"] = decode_kv_handoff(
                body["kv_handoff"])
        return self.server.generate_stream(name, inputs,
                                           deadline=deadline)

    def prefill(
        self, name: str, body: Dict[str, Any],
        version: Optional[int] = None,
        idem_key: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Disaggregated serving, prefill tier: chunk-prefill the
        prompt on this replica's engine and answer with the finished
        pages as a wire-encoded ``kv_handoff`` (block-page list).
        ``kv_handoff`` is null when the prompt is too short to cover
        one full page — the router then falls back to the untiered
        path.  ``idem_key`` is accepted for signature parity with the
        generic dispatch; prefill is pure, so replays are harmless
        without dedup."""
        tokens = body.get("tokens")
        if tokens is None:
            raise ValueError("Request json object must use the key: tokens")
        deadline = parse_deadline_ms(body)
        inputs: Dict[str, Any] = {"tokens": np.asarray(tokens, np.int32)}
        for key in ("seed", "prompt_len"):
            if body.get(key) is not None:
                inputs[key] = body[key]
        out = self.server.prefill_handoff(name, inputs,
                                          deadline=deadline)
        payload = out.get("kv_handoff")
        return {
            "kv_handoff": None if payload is None
            else encode_kv_handoff(payload),
            "tokens_covered": 0 if payload is None
            else int(payload["tokens_covered"]),
        }

    def fetch_kv(
        self, name: str, body: Dict[str, Any],
        version: Optional[int] = None,
        idem_key: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Hierarchical KV fetch (§5.10): look the prompt up in this
        replica's host spill tier and answer with the covered prefix's
        pages as a wire ``kv_handoff`` — or null on a miss (no spill
        tier, no parked record, fault).  Pure read: replays are
        harmless without dedup, like :prefill."""
        tokens = body.get("tokens")
        if tokens is None:
            raise ValueError("Request json object must use the key: tokens")
        out = self.server.fetch_kv(
            name, {"tokens": np.asarray(tokens, np.int32)})
        payload = out.get("kv_handoff")
        return {
            "kv_handoff": None if payload is None
            else encode_kv_handoff(payload),
            "tokens_covered": int(out.get("tokens_covered", 0)),
        }

    def classify(
        self, name: str, body: Dict[str, Any],
        version: Optional[int] = None,
        idem_key: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Classification response shape: [[ [class_id, score], ... ], ...]
        per instance (TF-Serving ClassificationResult equivalent)."""
        result = self.predict(name, body, version, idem_key=idem_key)
        classifications = []
        for row in result["predictions"]:
            if "top_k_classes" in row:
                pairs = [
                    [str(c), float(s)]
                    for c, s in zip(row["top_k_classes"], row["top_k_scores"])
                ]
            else:
                scores = row.get("scores", [])
                pairs = [[str(i), float(s)] for i, s in enumerate(scores)]
            classifications.append(pairs)
        return {"result": {"classifications": classifications}}


class _Handler(BaseHTTPRequestHandler):
    api: ServingAPI  # set by make_http_server

    # Keep-alive: every response carries Content-Length (see _send), so
    # persistent connections are safe — and the fleet router's upstream
    # connection pool depends on them (a fresh TCP connect + handler
    # thread per proxied request measured ~3.5 ms p50 on loopback,
    # pure overhead on the serving hot path).
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # route to logging, not stderr spam
        log.debug("http: " + fmt, *args)

    # Pure-read probe routes: excluded from the in-flight bracket — a
    # load-balancer probe or Prometheus scrape is not work a drain must
    # wait for, and counting scrapes as in-flight would feed the fleet
    # autoscaler a phantom +1 load per scrape.
    _PROBE_PATHS = ("/metrics", "/healthz", "/readyz",
                    "/debug/traces")

    def _dispatch(self, method: str) -> None:
        # Bracket the WHOLE dispatch — body read included — in the
        # server's in-flight count: a drain must wait for a request
        # that was accepted but is still parsing, not just for ones
        # already inside predict().
        if self.path in self._PROBE_PATHS:
            self._dispatch_inner(method)
            return
        self.api.server.enter_request()
        try:
            self._dispatch_inner(method)
        finally:
            self.api.server.exit_request()

    def _dispatch_inner(self, method: str) -> None:
        for m, pattern, action in _ROUTES:
            if m != method:
                continue
            match = pattern.match(self.path)
            if not match:
                continue
            try:
                self._run(action, match.groupdict())
            except KeyError as e:
                self._send(404, {"error": str(e)})
            except ValueError as e:
                self._send(400, {"error": str(e)})
            except Overloaded as e:
                # Load shed: bounded-admission refusal.  Retry-After
                # carries the batcher's hint so well-behaved clients
                # back off instead of hammering a full queue.
                self._send(429, {"error": str(e)},
                           headers={"Retry-After":
                                    f"{max(1, round(e.retry_after_s))}"})
            except DeadlineExceeded as e:
                self._send(504, {"error": str(e)})
            except Exception as e:  # noqa: BLE001 — serving must not die
                log.exception("handler error")
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
            return
        # Drain an unrouted request's body BEFORE answering: with
        # keep-alive (HTTP/1.1) an unread body would be parsed as the
        # next request line, desyncing the persistent connection —
        # including a router's pooled upstream one.
        length = int(self.headers.get("Content-Length", 0))
        if length:
            self.rfile.read(length)
        self._send(404, {"error": f"no route for {method} {self.path}"})

    def _run(self, action: str, groups: Dict[str, str]) -> None:
        version = int(groups["version"]) if groups.get("version") else None
        if action == "index":
            self._send(200, WELCOME, raw=True)
        elif action == "health":
            self._send(200, {"status": "ok", "models": self.api.server.models()})
        elif action == "ready":
            server = self.api.server
            # ``role`` advertises the disaggregation tier (prefill /
            # decode / unified): the fleet registry's readiness probe
            # reads it off this route, which is how the router learns
            # the two-tier topology without any extra discovery hop.
            if server.is_ready():
                body = {"status": "ready",
                        "role": server.role,
                        "models": server.models()}
                # Loaded adapter digests per engine model (§5.11): the
                # fleet registry's readiness probe reads these so the
                # router can prefer replicas that already hold a
                # request's adapter resident (digest-affinity).
                adapters = server.adapter_info()
                if adapters:
                    body["adapters"] = adapters
                self._send(200, body)
            else:
                self._send(503, {
                    "status": "draining" if server.draining()
                    else "no models loaded",
                    "role": server.role})
        elif action == "metrics":
            from kubeflow_tpu.runtime.prom import REGISTRY

            # In-flight/queue/readiness gauges are refreshed at scrape
            # time: the autoscaler reads load off THIS render, so the
            # values must be current now, not as of the last request.
            self.api.server.refresh_gauges()
            self._send(200, REGISTRY.render(), raw=True)
        elif action == "traces":
            self._send(200, tracing.snapshot())
        elif action == "metadata":
            self._send(200, self.api.metadata(groups["name"]))
        elif action == "stats":
            self._send(200, self.api.stats(groups["name"]))
        elif action == "generate":
            self._run_generate(groups["name"])
        else:
            import time as _time

            from kubeflow_tpu.runtime.prom import REGISTRY
            from kubeflow_tpu.serving.model_server import (
                LATENCY_HELP,
                LATENCY_SECONDS,
                REQUESTS_HELP,
                REQUESTS_TOTAL,
            )

            length = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(length) or b"{}")
            fn = getattr(self.api, action)
            # Only KNOWN model names become label values: the URL is
            # attacker-controlled, and each distinct label value is a
            # permanent series — scanner probes must not grow /metrics.
            name = groups["name"]
            model_label = name if self.api.server.has_model(name) \
                else "_unknown_"
            # Server span: continues the router's trace (traceparent
            # header) or roots a fresh one; becoming the thread's
            # current context is what lets the batching planes stamp
            # child spans without signature changes.  Ends with the
            # same outcome vocabulary the request counter uses, so
            # tail sampling always keeps shed/expired/errored traces.
            span = tracing.start_span(
                f"server.{action}", parent=tracing.extract(self.headers),
                attrs={"model": model_label, "transport": "rest"})
            # `outcome` keeps the pre-tracing metric vocabulary (4xx
            # counts as "error"); `span_status` additionally names the
            # client faults so tail sampling treats a 404/400 as an
            # answer, not an always-keep incident.
            outcome = span_status = "error"
            t0 = _time.perf_counter()
            # Idempotency key (router-minted or client-supplied): the
            # dedup layer in ModelServer.predict answers retried keys
            # from its result cache instead of re-executing.
            idem_key = self.headers.get(IDEMPOTENCY_HEADER)
            try:
                with tracing.use_span(span):
                    out = fn(name, body, version, idem_key=idem_key)
                outcome = span_status = "ok"
            except KeyError:
                span_status = "not_found"
                raise
            except ValueError:
                span_status = "invalid_argument"
                raise
            except Overloaded:
                outcome = span_status = "shed"
                raise
            except DeadlineExceeded:
                outcome = span_status = "deadline_exceeded"
                raise
            finally:
                REGISTRY.counter(REQUESTS_TOTAL, REQUESTS_HELP).inc(
                    model=model_label, route=action, outcome=outcome)
                # Failures included: the slowest requests in an incident
                # are usually the failing ones.
                REGISTRY.histogram(
                    LATENCY_SECONDS, LATENCY_HELP,
                ).observe(_time.perf_counter() - t0, route=action)
                span.end(status=span_status)
            self._send(200, out)

    def _run_generate(self, name: str) -> None:
        """The streaming :generate route: chunked NDJSON over the
        keep-alive connection.  Admission failures (shed, expired
        deadline, bad request, no engine) raise BEFORE the status line
        and map to the ordinary error codes; once streaming has begun
        a failure becomes a terminal ``{"error": ..., "code": ...}``
        line — a second status line on a half-written chunked body
        would corrupt the connection."""
        import time as _time

        from kubeflow_tpu.runtime.prom import REGISTRY
        from kubeflow_tpu.serving.model_server import (
            LATENCY_HELP,
            LATENCY_SECONDS,
            REQUESTS_HELP,
            REQUESTS_TOTAL,
        )

        length = int(self.headers.get("Content-Length", 0))
        body = json.loads(self.rfile.read(length) or b"{}")
        model_label = name if self.api.server.has_model(name) \
            else "_unknown_"
        span = tracing.start_span(
            "server.generate", parent=tracing.extract(self.headers),
            attrs={"model": model_label, "transport": "rest"})
        outcome = span_status = "error"
        t0 = _time.perf_counter()
        try:
            try:
                with tracing.use_span(span):
                    meta, stream = self.api.generate(name, body)
            except KeyError:
                span_status = "not_found"
                raise
            except ValueError:
                span_status = "invalid_argument"
                raise
            except Overloaded:
                outcome = span_status = "shed"
                raise
            except DeadlineExceeded:
                outcome = span_status = "deadline_exceeded"
                raise
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header("Transfer-Encoding", "chunked")
            self.end_headers()
            emitted = 0
            try:
                self._write_chunk({"meta": dict(meta, model=name)})
                for chunk in stream:
                    emitted += len(chunk)
                    self._write_chunk({"tokens": chunk})
                self._write_chunk({"done": True,
                                   "tokens_emitted": emitted})
                outcome = span_status = "ok"
            except DeadlineExceeded as e:
                outcome = span_status = "deadline_exceeded"
                self._write_chunk({"error": str(e), "code": 504})
            except ConnectionError:
                # The CLIENT went away mid-stream (crashed router /
                # closed laptop lid): nothing is left to write to and
                # nothing to report — the engine entry resolves on its
                # own.  Returning skips the chunk terminator; the
                # connection is dead anyway.
                span_status = "client_disconnected"
                return
            except Exception as e:  # noqa: BLE001 — stream must close
                log.exception("generate stream error")
                self._write_chunk({"error": f"{type(e).__name__}: {e}",
                                   "code": 500})
            finally:
                stream.close()
            self._end_chunks()
        finally:
            REGISTRY.counter(REQUESTS_TOTAL, REQUESTS_HELP).inc(
                model=model_label, route="generate", outcome=outcome)
            REGISTRY.histogram(
                LATENCY_SECONDS, LATENCY_HELP,
            ).observe(_time.perf_counter() - t0, route="generate")
            span.end(status=span_status)

    def _write_chunk(self, payload: Dict[str, Any]) -> None:
        """One NDJSON line as one HTTP/1.1 chunk, flushed — a proxy
        (the fleet router) splices streams on line boundaries, so each
        line must hit the wire when it exists, not when a buffer
        fills."""
        data = json.dumps(payload).encode() + b"\n"
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _end_chunks(self) -> None:
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    def _send(self, code: int, payload: Any, raw: bool = False,
              headers: Optional[Dict[str, str]] = None) -> None:
        data = (payload if raw else json.dumps(payload)).encode()
        self.send_response(code)
        self.send_header(
            "Content-Type", "text/plain" if raw else "application/json"
        )
        self.send_header("Content-Length", str(len(data)))
        for k, v in (headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(data)

    def do_GET(self):
        self._dispatch("GET")

    def do_POST(self):
        self._dispatch("POST")


def make_http_server(
    model_server: ModelServer, port: int = 8000, host: str = "0.0.0.0",
    server_cls: type = ThreadingHTTPServer,
) -> Tuple[ThreadingHTTPServer, threading.Thread]:
    """Build and start the REST server on a daemon thread; returns
    (httpd, thread).  Port 8000 matches the reference proxy
    (kubeflow/tf-serving/tf-serving.libsonnet:176-207).
    ``server_cls`` lets the chaos harness substitute a
    ThreadingHTTPServer subclass whose kill() severs live connections
    (a SIGKILL's socket signature, in process)."""
    handler = type("BoundHandler", (_Handler,), {"api": ServingAPI(model_server)})
    httpd = server_cls((host, port), handler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True,
                              name="serving-http")
    thread.start()
    return httpd, thread
