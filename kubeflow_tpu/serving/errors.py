"""Typed serving errors — the wire-status contract of the fault layer.

Every fault-tolerance path in the serving stack resolves to one of
these, and the two transport faces map them to the SAME status pair so
a client sees one failure semantics regardless of protocol:

    DeadlineExceeded  -> HTTP 504            / gRPC DEADLINE_EXCEEDED
    Overloaded        -> HTTP 429+Retry-After/ gRPC RESOURCE_EXHAUSTED
    BatcherClosed     -> never reaches the wire: ModelServer.predict
                         retries the replacement batcher or falls back
                         to the direct path (hot-swap / drain races)

They live in their own module (not model_server.py) because every layer
imports them — batchers, engine, both transports, the gRPC client
helpers — and the transports must not import the batching plane just to
classify an exception.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base of the typed serving failures."""


class BatcherClosed(ServingError):
    """Raised by submit() on a closed batcher — callers holding a stale
    reference (hot-swap races, drain) retry against the replacement."""


class DeadlineExceeded(ServingError):
    """The request's deadline passed before its result was ready.

    Raised on admission when the deadline is already spent, from the
    queue when it expires pre-dispatch, and mid-generation when the
    engine retires an expired in-flight slot.  HTTP 504 / gRPC
    DEADLINE_EXCEEDED."""


class Overloaded(ServingError):
    """Admission refused: queue depth or in-flight cap reached.

    Fails fast instead of queueing unboundedly — under overload a
    bounded 429 beats a timed-out 200.  ``retry_after_s`` rides to the
    HTTP ``Retry-After`` header and the gRPC status detail."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = float(retry_after_s)
