"""Host-side block-hashed prefix index over the donor KV pool.

The DecodeEngine pays full prefill for every admission even when
thousands of chat requests share an identical system-prompt prefix.
This module is the bookkeeping half of shared-prefix KV reuse: the
device half (models/generate.py ``copy_prefix_into_slot`` /
``prefill_chunk_into_slot``) copies and fills donor rows of a small
pinned KV pool; this index remembers which pool row holds which
token prefix, at BLOCK granularity.

Design, in the radix-tree-lite shape vLLM/SGLang use:

  - prompts are hashed in fixed-size token blocks, each block's digest
    chained over its predecessor's (``h_i = H(h_{i-1} || block_i)``),
    so a digest identifies an exact token PREFIX, not a bag of blocks;
  - a committed pool row publishes one digest per full block it holds;
    lookup walks the querying prompt's chain from the longest candidate
    down and returns the deepest published match — the longest cached
    prefix, in O(blocks) with no tree structure to rebalance;
  - eviction is LRU over committed rows, and a row pinned by an active
    slot (a capture in flight — the chunked prefill currently writing
    it) is NEVER evicted: a donor must not be reallocated under the
    program that is filling it;
  - the index holds tokens and row numbers only — no device memory —
    and dies with its engine, which is what makes model-reload
    invalidation automatic (the serving layer rebuilds the engine, and
    with it this index, around every hot-swapped version).

Single-writer by design: the engine's loop thread is the only caller
of the mutating surface, so the class needs no lock of its own (the
engine snapshots counters under its own lock for stats()).
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Optional, Tuple

import numpy as np

_SEED_DIGEST = b"\x00" * 16


def _block_digests(tokens: np.ndarray, block: int,
                   n_blocks: int) -> List[bytes]:
    """Chained digests of the first ``n_blocks`` full ``block``-token
    blocks of ``tokens`` — digest i commits to tokens[0 : (i+1)*block]."""
    out: List[bytes] = []
    h = _SEED_DIGEST
    flat = np.asarray(tokens, np.int32).reshape(-1)
    for i in range(n_blocks):
        h = hashlib.blake2b(
            h + flat[i * block:(i + 1) * block].tobytes(),
            digest_size=16).digest()
        out.append(h)
    return out


class PrefixIndex:
    """Block-hashed prefix -> donor pool row map with LRU + pin
    eviction.

    Args:
      rows: donor pool entries (device rows; ``--prefix_pool_blocks``).
      block_tokens: hash/publish granularity — a prefix is cacheable
        in multiples of this many tokens.
      pool_len: cache columns per pool row; caps how much prefix one
        donor can hold.
    """

    def __init__(self, rows: int, block_tokens: int, pool_len: int):
        if rows < 1:
            raise ValueError(f"rows must be >= 1, got {rows}")
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        self.rows = int(rows)
        self.block = int(block_tokens)
        self.pool_len = int(pool_len)
        self._free: List[int] = list(range(self.rows))
        # digest -> (row, cached columns); committed rows only.
        self._chains: Dict[bytes, Tuple[int, int]] = {}
        # row -> its published digests, in insertion order = LRU order
        # (move-to-end on hit).
        self._lru: Dict[int, List[bytes]] = {}
        self._pinned: set = set()
        self.evictions = 0

    # -- lookup ------------------------------------------------------------

    def lookup(self, tokens: np.ndarray,
               limit: int) -> Tuple[Optional[int], int]:
        """Longest published block-prefix of ``tokens`` covering at
        most ``limit`` columns; returns (pool row, cached columns) or
        (None, 0).  Callers pass ``limit = prompt_len - 1`` so at least
        one prompt token is always recomputed — the KV pool caches
        keys/values, not the logits the first sampled token needs."""
        n_blocks = min(int(limit), self.pool_len) // self.block
        if n_blocks <= 0 or not self._chains:
            return None, 0
        digests = _block_digests(tokens, self.block, n_blocks)
        for i in range(n_blocks, 0, -1):
            hit = self._chains.get(digests[i - 1])
            if hit is not None:
                row, _ = hit
                self._lru[row] = self._lru.pop(row)  # move to end
                return row, i * self.block
        return None, 0

    # -- capture lifecycle -------------------------------------------------

    def begin_capture(self) -> Tuple[Optional[int], bool]:
        """Claim (and pin) a pool row for a new donor capture; returns
        (row, evicted_flag).  Evicts the least-recently-used committed
        row when no free row exists; (None, False) when every row is
        pinned by an active capture."""
        evicted = False
        if self._free:
            row = self._free.pop()
        else:
            row = next((r for r in self._lru if r not in self._pinned),
                       None)
            if row is None:
                return None, False
            self._drop_row(row)
            self.evictions += 1
            evicted = True
        self._pinned.add(row)
        return row, evicted

    def commit_capture(self, row: int, tokens: np.ndarray,
                       true_len: int) -> int:
        """Publish a filled capture: register one digest per FULL block
        of real prompt the row now holds (partial trailing blocks carry
        right-pad garbage and are never published).  Returns published
        columns; a capture too short to publish is released instead."""
        n_blocks = min(int(true_len), self.pool_len) // self.block
        if n_blocks <= 0:
            self.abort_capture(row)
            return 0
        digests = _block_digests(tokens, self.block, n_blocks)
        for i, d in enumerate(digests):
            # FIRST-writer-wins on digest collisions between rows
            # holding the same prefix (two misses racing to capture one
            # hot prompt): the established row keeps serving the
            # digest, so evicting the duplicate later cannot orphan it
            # — eviction removes only digests still pointing at the
            # evicted row.
            self._chains.setdefault(d, (row, (i + 1) * self.block))
        self._lru[row] = digests
        self._pinned.discard(row)
        return n_blocks * self.block

    def abort_capture(self, row: int) -> None:
        """Release a claimed row without publishing (expired or failed
        admission): its partial writes are unreachable garbage and the
        row returns to the free list."""
        self._pinned.discard(row)
        if row not in self._lru and row not in self._free:
            self._free.append(row)

    # -- maintenance -------------------------------------------------------

    def _drop_row(self, row: int) -> None:
        for d in self._lru.pop(row, ()):  # only digests still ours
            if self._chains.get(d, (None,))[0] == row:
                del self._chains[d]

    def invalidate(self) -> None:
        """Forget every cached prefix (model reload: the new version's
        KV is numerically unrelated — serving stale prefixes would be
        silent corruption, so the serving layer rebuilds engine + index
        per version and close() calls this as a belt-and-braces)."""
        self._chains.clear()
        self._lru.clear()
        self._pinned.clear()
        self._free = list(range(self.rows))

    def stats(self) -> Dict[str, int]:
        return {
            "rows": self.rows,
            "committed_rows": len(self._lru),
            "pinned_rows": len(self._pinned),
            "published_blocks": len(self._chains),
            "evictions": self.evictions,
        }
