"""Host-side block manager for the paged KV pool.

The DecodeEngine's unified KV store is a device-side BLOCK POOL
(models/generate.py ``init_paged_state``): fixed-size pages of
``block_tokens`` cache positions, shared by every slot through per-slot
block tables the host passes into each program call.  This module is
ALL of the host bookkeeping for that pool:

  - **physical allocation with refcounts** — a block is free, held by
    one or more slots (``slot_ref``: live requests whose tables point
    at it), and/or held by the prefix cache (``rec_ref``: published
    prefix records that advertise it).  A block returns to the free
    list only when both counts are zero, so a cached prefix can never
    be reallocated under a slot that aliased it;

  - **token-reservation admission accounting** — admission reserves a
    request's WORST-CASE block count (ceil((prompt + budget) /
    block_tokens)) up front and physical blocks are taken lazily from
    that reservation as the frontier grows, so a mid-prefill or
    mid-decode slot can never be starved by later admissions
    (deadlock-freedom by construction: ``free + evictable >= reserved``
    is the invariant every operation preserves), while speculative
    rollback returns rejected-tail blocks to the pool without losing
    the guarantee;

  - **the block-hashed prefix index** — prompts are hashed in
    ``block_tokens``-token blocks, each digest chained over its
    predecessor's (``h_i = H(h_{i-1} || block_i)``) so a digest
    identifies an exact token PREFIX; a completed prefill publishes its
    full-block prefix as a record mapping digests to the PHYSICAL
    blocks that already hold the computed k/v.  A later admission that
    matches simply aliases those blocks into its own table (refcount
    bump — zero device copies; divergence starts at the first
    non-shared block, which is always a freshly allocated private
    block because sharing is block-aligned, i.e. copy-on-write with
    the copy statically dead);

  - **LRU eviction of refcount-0 cached blocks** — when allocation
    needs pages and the free list is dry, least-recently-used prefix
    records are dropped; only blocks no live slot still references
    actually free (a record evicted mid-use keeps its aliased blocks
    resident until the aliasing slots retire).  First-writer-wins on
    digest collisions (two misses racing to capture one hot prompt):
    the established record keeps serving the digest, so evicting the
    duplicate cannot orphan the survivor.  A prefix being captured is
    "pinned" structurally — its blocks are slot-referenced until the
    capturing request retires.

  - **the host-RAM spill tier** — an optional second tier
    (``host_blocks`` pages of capacity) holding COPIES of cold KV
    pages in host memory, keyed by the same chained digests.  The
    engine gathers a cold record's device pages (one batched fancy
    index over the pool), hands the resulting host arrays to
    ``spill()``, and the device record is dropped — pages free without
    destroying their contents.  A later admission that misses the
    device index but hits ``lookup_spilled`` re-imports through the
    existing ``kv_import`` program instead of re-prefilling.  The tier
    is a pure overlay: host records never reference device block ids,
    so no page is ever simultaneously device-writable and
    host-spilled, and the device-side accounting (free/idle/reserved
    arithmetic and its deadlock-freedom invariant) is untouched.
    Host capacity is LRU-bounded like the device index; parked
    session KV (``park_kv``) enters via ``host_put`` so idle
    conversations stop squatting on HBM between turns.

The index holds tokens hashes and block numbers only — no device
memory (the host tier holds host copies, still no device handles) —
and dies with its engine, which is what makes model-reload
invalidation automatic (the serving layer rebuilds the engine, and
with it this manager, around every hot-swapped version).

Single-writer by design: the engine's loop thread is the only caller
of the mutating surface, and the engine wraps every call in its own
lock so ``available()``/gauge reads from the submit path are never
torn.
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

_SEED_DIGEST = b"\x00" * 16


def _block_digests(tokens: np.ndarray, block: int,
                   n_blocks: int, salt: bytes = b"") -> List[bytes]:
    """Chained digests of the first ``n_blocks`` full ``block``-token
    blocks of ``tokens`` — digest i commits to tokens[0 : (i+1)*block].

    ``salt`` seeds the whole chain (adapter-scoped KV, §5.11: the
    engine passes each request's adapter CONTENT digest, so two
    variants prefilling the same tokens produce disjoint chains and
    can never alias each other's pages — while the same adapter on any
    replica hashes identically, which keeps :fetch_kv addressable
    fleet-wide).  Empty salt is the base chain, bit-identical to the
    pre-adapter index."""
    out: List[bytes] = []
    h = hashlib.blake2b(salt, digest_size=16).digest() if salt \
        else _SEED_DIGEST
    flat = np.asarray(tokens, np.int32).reshape(-1)
    for i in range(n_blocks):
        h = hashlib.blake2b(
            h + flat[i * block:(i + 1) * block].tobytes(),
            digest_size=16).digest()
        out.append(h)
    return out


class _PrefixRecord:
    """One published prefix: its digest chain and the physical blocks
    (index i of ``blocks`` holds tokens [i*block, (i+1)*block))."""

    __slots__ = ("digests", "blocks")

    def __init__(self, digests: List[bytes], blocks: List[int]):
        self.digests = digests
        self.blocks = blocks


class _HostRecord:
    """One spilled/parked prefix in the host tier: the digest chain and
    an opaque payload (the engine stores gathered numpy pages; block i
    of the payload holds tokens [i*block, (i+1)*block)).  Never holds
    device block ids."""

    __slots__ = ("digests", "payload", "n_blocks")

    def __init__(self, digests: List[bytes], payload, n_blocks: int):
        self.digests = digests
        self.payload = payload
        self.n_blocks = n_blocks


class BlockManager:
    """Paged-KV pool bookkeeping: refcounted physical blocks,
    reservation accounting, and the prefix index (module docstring).

    Args:
      num_blocks: physical pool pages (``--kv_pool_blocks``).
      block_tokens: cache positions per page — also the prefix
        hash/share granularity (``--kv_block_tokens``).
      caching: publish/lookup prefixes (False = pure allocator; the
        engine's identity tests compare ON vs OFF).
      host_blocks: host-tier capacity in pages (0 = no spill tier).
    """

    def __init__(self, num_blocks: int, block_tokens: int,
                 caching: bool = True, host_blocks: int = 0):
        if num_blocks < 1:
            raise ValueError(
                f"num_blocks must be >= 1, got {num_blocks}")
        if block_tokens < 1:
            raise ValueError(
                f"block_tokens must be >= 1, got {block_tokens}")
        if host_blocks < 0:
            raise ValueError(
                f"host_blocks must be >= 0, got {host_blocks}")
        self.num_blocks = int(num_blocks)
        self.block = int(block_tokens)
        self.caching = bool(caching)
        self.host_blocks = int(host_blocks)
        # Free LIFO (pop from the end -> low block ids first, which
        # keeps tests deterministic and device pages warm).
        self._free: List[int] = list(range(self.num_blocks - 1, -1, -1))
        self._slot_ref = [0] * self.num_blocks
        self._rec_ref = [0] * self.num_blocks
        # Blocks with slot_ref == 0 and rec_ref > 0: resident cache
        # pages reclaimable by eviction.  Maintained incrementally so
        # available() is O(1).
        self._cached_idle = 0
        # Admission reservations not yet backed by a physical take().
        self._reserved = 0
        # digest -> (record, depth): lookup returns record.blocks[:depth].
        self._chains: Dict[bytes, Tuple[_PrefixRecord, int]] = {}
        # id(record) -> record, insertion order == LRU order.
        self._lru: "OrderedDict[int, _PrefixRecord]" = OrderedDict()
        self.evictions = 0        # prefix records evicted (LRU)
        self.block_evictions = 0  # physical blocks freed by eviction
        # Host spill tier (module docstring): digest -> (record, depth);
        # id(record) -> record, insertion order == LRU order.
        self._host_chains: Dict[bytes, Tuple[_HostRecord, int]] = {}
        self._host_lru: "OrderedDict[int, _HostRecord]" = OrderedDict()
        self._host_used = 0       # host pages resident
        self.spills_out = 0       # device pages copied into the host tier
        self.spills_in = 0        # host pages re-imported to device
        self.host_evictions = 0   # host pages destroyed by host-LRU

    # -- capacity ----------------------------------------------------------

    def available(self) -> int:
        """Blocks an admission could still reserve: free pages plus
        evictable cached pages, minus reservations already promised."""
        return len(self._free) + self._cached_idle - self._reserved

    def used_blocks(self) -> int:
        """Pages resident (slot- or cache-held)."""
        return self.num_blocks - len(self._free)

    def host_used_blocks(self) -> int:
        """Pages resident in the host spill tier."""
        return self._host_used

    # -- admission ---------------------------------------------------------

    def admit(self, tokens: np.ndarray, limit: int,
              total_blocks: int, salt: bytes = b"",
              ) -> Optional[Tuple[List[int], int]]:
        """Admission, atomically: find the longest cached block-prefix
        of ``tokens`` covering at most ``limit`` positions, alias its
        blocks (slot refs bumped), and reserve the remaining
        ``total_blocks - shared`` private pages.  Returns
        (shared_blocks, cached_tokens), or None when the pool cannot
        currently cover the request (the engine leaves it queued;
        retirement frees pages).  Callers pass ``limit = prompt_len -
        1`` so at least one prompt token always recomputes — blocks
        cache k/v, not the logits the first sampled token needs."""
        shared, cached = self._lookup(tokens, limit, salt)
        private = max(0, int(total_blocks) - len(shared))
        # Aliasing an idle cached page consumes an evictable page, so
        # it must be covered by headroom exactly like a reservation —
        # otherwise an earlier admission's reserve could become
        # unsatisfiable (the invariant free + evictable >= reserved).
        shared_idle = sum(1 for b in shared if self._slot_ref[b] == 0)
        if (len(self._free) + self._cached_idle - self._reserved
                < private + shared_idle):
            return None
        for b in shared:
            if self._slot_ref[b] == 0:
                self._cached_idle -= 1
            self._slot_ref[b] += 1
        self._reserved += private
        return shared, cached

    def take(self) -> int:
        """One physical page from the caller's reservation (admission
        guaranteed it — evicts LRU records if the free list is dry).
        The returned block is exclusively owned (slot_ref 1, no record
        refs): the caller is its only writer until release."""
        if self._reserved <= 0:
            raise RuntimeError(
                "BlockManager.take() without a reservation — paged-KV "
                "accounting bug")
        while not self._free:
            self._evict_lru()
        self._reserved -= 1
        b = self._free.pop()
        self._slot_ref[b] = 1
        return b

    def release(self, blocks: Sequence[int], unreserve: int = 0) -> None:
        """Drop one slot reference per block (retirement, expiry) and
        return ``unreserve`` never-taken reserved pages.  Pages a
        published record still advertises stay resident as evictable
        cache; the rest free immediately."""
        if unreserve:
            self._reserved -= int(unreserve)
            assert self._reserved >= 0, "reservation accounting broken"
        for b in blocks:
            b = int(b)
            self._slot_ref[b] -= 1
            assert self._slot_ref[b] >= 0, f"double release of block {b}"
            if self._slot_ref[b] == 0:
                if self._rec_ref[b] > 0:
                    self._cached_idle += 1
                else:
                    self._free.append(b)

    def rollback(self, blocks: Sequence[int]) -> None:
        """Speculative rollback: return freshly written tail pages to
        the pool AND restore the owner's reservation (it may regrow
        over the same positions after the rejected window)."""
        self.release(blocks)
        self._reserved += len(blocks)

    # -- prefix index ------------------------------------------------------

    def _lookup(self, tokens: np.ndarray, limit: int,
                salt: bytes = b"") -> Tuple[List[int], int]:
        n_blocks = int(limit) // self.block
        if not self.caching or n_blocks <= 0 or not self._chains:
            return [], 0
        digests = _block_digests(tokens, self.block, n_blocks, salt)
        for i in range(n_blocks, 0, -1):
            ent = self._chains.get(digests[i - 1])
            if ent is not None:
                rec, _ = ent
                self._lru.move_to_end(id(rec))
                return list(rec.blocks[:i]), i * self.block
        return [], 0

    def peek(self, tokens: np.ndarray, limit: int,
             salt: bytes = b"") -> int:
        """Device-tier coverage of ``tokens`` in cached positions,
        without aliasing anything or touching LRU order (the engine
        compares this against ``lookup_spilled`` coverage to decide
        whether a spilled record beats the resident index)."""
        n_blocks = int(limit) // self.block
        if not self.caching or n_blocks <= 0 or not self._chains:
            return 0
        digests = _block_digests(tokens, self.block, n_blocks, salt)
        for i in range(n_blocks, 0, -1):
            if digests[i - 1] in self._chains:
                return i * self.block
        return 0

    def publish(self, tokens: np.ndarray, true_len: int,
                blocks: Sequence[int], salt: bytes = b"") -> int:
        """Register a completed prefill's full-block prefix: digest i
        maps to ``blocks[i]``, which already holds the computed k/v —
        publication is a refcount bump, never a copy.  Partial trailing
        blocks carry positions the request keeps writing (decode) and
        are never published.  First-writer-wins per digest.  Returns
        newly published tokens (0 = fully covered already, too short,
        or caching off)."""
        if not self.caching:
            return 0
        n_blocks = min(int(true_len) // self.block, len(blocks))
        if n_blocks <= 0:
            return 0
        digests = _block_digests(tokens, self.block, n_blocks, salt)
        if digests[-1] in self._chains:
            return 0  # the full chain is already served
        rec = _PrefixRecord(digests,
                            [int(b) for b in blocks[:n_blocks]])
        new_tokens = 0
        for i, d in enumerate(digests):
            if d not in self._chains:
                self._chains[d] = (rec, i + 1)
                new_tokens += self.block
        for b in rec.blocks:
            # Publishing happens while the capturing slot still holds
            # the pages (slot_ref >= 1), so no page transitions
            # free/idle here.
            self._rec_ref[b] += 1
        self._lru[id(rec)] = rec
        return new_tokens

    # -- host spill tier ---------------------------------------------------

    def spillable_blocks(self) -> int:
        """Device pages that spilling could preserve instead of
        destroy-evicting: idle cached pages, when the tier is on."""
        return self._cached_idle if self.host_blocks else 0

    def spill_pressure(self) -> int:
        """Reservation pages the free list alone cannot cover — the
        number of upcoming take() calls that would have to DESTROY
        cached pages via LRU eviction.  The engine spills while this
        is positive (and candidates exist), which is what turns
        `free + spillable >= reserved` from an eviction bound into a
        preservation guarantee."""
        if not self.host_blocks:
            return 0
        return max(0, self._reserved - len(self._free))

    def spill_candidates(self, max_records: int = 1) -> List[_PrefixRecord]:
        """Up to ``max_records`` LRU-coldest device records whose pages
        are ALL idle (no live slot aliases them) — safe to gather and
        drop.  Selection only; the engine gathers the pages off-lock
        and completes with ``spill()``."""
        if not self.host_blocks:
            return []
        out: List[_PrefixRecord] = []
        for rec in self._lru.values():
            if len(rec.digests) > self.host_blocks:
                continue  # never storable; destroy-evict is its fate
            if all(self._slot_ref[b] == 0 for b in rec.blocks):
                out.append(rec)
                if len(out) >= max_records:
                    break
        return out

    def spill(self, rec: _PrefixRecord, payload) -> Optional[int]:
        """Complete a spill: store ``payload`` (the gathered host copy
        of ``rec``'s pages) in the host tier and drop the device
        record, freeing its idle pages WITHOUT destroying their
        contents.  Validates the record is still live and still fully
        idle (the gather ran outside the manager's lock); a stale or
        unstorable candidate declines with None.  Returns device pages
        freed (0 is a SUCCESS whose pages other records still pin).

        ``payload=None`` is the gather-free fast path: succeed ONLY if
        the record's chain is already host-resident (a parked session
        the engine host_put at delivery) — the device pages can drop
        without any copy because the host tier already serves them.
        Declining (None) tells the caller to gather and retry."""
        if not self.host_blocks or id(rec) not in self._lru:
            return None
        if any(self._slot_ref[b] != 0 for b in rec.blocks):
            return None  # re-aliased since selection; still hot
        if payload is None and rec.digests[-1] not in self._host_chains:
            return None  # no host copy to lean on; caller must gather
        freed = sum(1 for b in rec.blocks
                    if self._rec_ref[b] == 1 and self._slot_ref[b] == 0)
        if payload is not None:
            self._host_store(rec.digests, payload)
        else:
            hrec, _ = self._host_chains[rec.digests[-1]]
            self._host_lru.move_to_end(id(hrec))
        if rec.digests[-1] not in self._host_chains:
            # Not storable (larger than the whole host tier) and not
            # already resident: dropping would destroy the only copy.
            return None
        del self._lru[id(rec)]
        self._drop_record(rec, count=False)
        self.spills_out += len(rec.blocks)
        return freed

    def host_put(self, tokens: np.ndarray, true_len: int,
                 payload, salt: bytes = b"") -> int:
        """Store a host copy of ``tokens``' full-block prefix directly
        (parked session KV: the engine gathers the pages at delivery
        and parks them here so the session's device pages can retire).
        Returns host pages stored (0 = disabled, dup, or too short)."""
        if not self.host_blocks:
            return 0
        n_blocks = int(true_len) // self.block
        if n_blocks <= 0:
            return 0
        digests = _block_digests(tokens, self.block, n_blocks, salt)
        return self._host_store(digests, payload)

    def _host_store(self, digests: List[bytes], payload) -> int:
        if len(digests) > self.host_blocks:
            return 0  # larger than the whole tier — never storable
        if digests[-1] in self._host_chains:
            # First-writer-wins, same as publish(): the established
            # host record already serves the full chain.
            hrec, _ = self._host_chains[digests[-1]]
            self._host_lru.move_to_end(id(hrec))
            return 0
        hrec = _HostRecord(list(digests), payload, len(digests))
        for i, d in enumerate(digests):
            if d not in self._host_chains:
                self._host_chains[d] = (hrec, i + 1)
        self._host_lru[id(hrec)] = hrec
        self._host_used += hrec.n_blocks
        # The new record is MRU and fits by the guard above, so this
        # terminates with it resident.
        while self._host_used > self.host_blocks:
            self._evict_host_lru()
        return hrec.n_blocks

    def lookup_spilled(self, tokens: np.ndarray, limit: int,
                       salt: bytes = b"") -> Tuple[Optional[object], int]:
        """Longest host-tier match of ``tokens`` covering at most
        ``limit`` positions: (payload, depth_blocks) — the payload
        covers AT LEAST ``depth_blocks`` pages and the caller trims to
        that depth — or (None, 0) on a miss.  Touches host LRU."""
        n_blocks = int(limit) // self.block
        if not self.host_blocks or n_blocks <= 0 or not self._host_chains:
            return None, 0
        digests = _block_digests(tokens, self.block, n_blocks, salt)
        for i in range(n_blocks, 0, -1):
            ent = self._host_chains.get(digests[i - 1])
            if ent is not None:
                hrec, depth = ent
                assert depth == i, (depth, i)
                self._host_lru.move_to_end(id(hrec))
                return hrec.payload, i
        return None, 0

    def _evict_host_lru(self) -> None:
        _, hrec = self._host_lru.popitem(last=False)
        for d in hrec.digests:
            ent = self._host_chains.get(d)
            if ent is not None and ent[0] is hrec:
                del self._host_chains[d]
        self._host_used -= hrec.n_blocks
        self.host_evictions += hrec.n_blocks

    # -- maintenance -------------------------------------------------------

    def _drop_record(self, rec: _PrefixRecord, count: bool) -> None:
        for d in rec.digests:
            ent = self._chains.get(d)
            if ent is not None and ent[0] is rec:
                del self._chains[d]
        for b in rec.blocks:
            self._rec_ref[b] -= 1
            if self._rec_ref[b] == 0 and self._slot_ref[b] == 0:
                self._cached_idle -= 1
                self._free.append(b)
                if count:
                    self.block_evictions += 1

    def _evict_lru(self) -> None:
        if not self._lru:
            raise RuntimeError(
                "paged-KV pool accounting broken: take() with no free "
                "and no evictable blocks")
        _, rec = self._lru.popitem(last=False)
        self.evictions += 1
        self._drop_record(rec, count=True)

    def invalidate(self) -> None:
        """Forget every cached prefix (engine close / model reload: a
        new version's KV is numerically unrelated, so serving a stale
        prefix would be silent corruption).  Pages still aliased by
        live slots stay resident until those slots release them.  The
        host tier drops too — its copies are the same stale KV."""
        while self._lru:
            _, rec = self._lru.popitem(last=False)
            self._drop_record(rec, count=False)
        self._host_chains.clear()
        self._host_lru.clear()
        self._host_used = 0

    def stats(self) -> Dict[str, int]:
        return {
            "blocks": self.num_blocks,
            "block_tokens": self.block,
            "used_blocks": self.used_blocks(),
            "free_blocks": len(self._free),
            "cached_idle_blocks": self._cached_idle,
            "reserved_blocks": self._reserved,
            "published_records": len(self._lru),
            "published_digests": len(self._chains),
            "evictions": self.evictions,
            "block_evictions": self.block_evictions,
            "host_blocks": self.host_blocks,
            "host_used_blocks": self._host_used,
            "host_records": len(self._host_lru),
            "spills_out": self.spills_out,
            "spills_in": self.spills_in,
            "host_evictions": self.host_evictions,
        }

    def check_invariants(self) -> None:
        """Debug/test hook: every structural invariant, or raise."""
        assert self._reserved >= 0
        free_set = set(self._free)
        assert len(free_set) == len(self._free), "duplicate free block"
        idle = 0
        for b in range(self.num_blocks):
            assert self._slot_ref[b] >= 0 and self._rec_ref[b] >= 0
            held = self._slot_ref[b] > 0 or self._rec_ref[b] > 0
            assert held != (b in free_set), (
                f"block {b} ref/free disagreement")
            if self._slot_ref[b] == 0 and self._rec_ref[b] > 0:
                idle += 1
        assert idle == self._cached_idle, (idle, self._cached_idle)
        assert len(self._free) + self._cached_idle >= self._reserved, (
            "reservation invariant violated")
        for rec_id, rec in self._lru.items():
            assert rec_id == id(rec)
            for b in rec.blocks:
                assert self._rec_ref[b] >= 1
        # Host tier: the overlay never references device pages, its
        # page accounting matches its records, and every chain entry
        # points into a live record at the right depth.
        assert self._host_used == sum(
            h.n_blocks for h in self._host_lru.values()), (
            self._host_used, "host page accounting broken")
        assert self._host_used <= self.host_blocks, "host tier over capacity"
        live_host = {id(h) for h in self._host_lru.values()}
        for d, (hrec, depth) in self._host_chains.items():
            assert id(hrec) in live_host, "host chain to evicted record"
            assert 1 <= depth <= hrec.n_blocks
            assert hrec.digests[depth - 1] == d
        for hrec_id, hrec in self._host_lru.items():
            assert hrec_id == id(hrec)
            assert hrec.n_blocks == len(hrec.digests)
            # The full chain must resolve through _host_chains (its
            # tail digest always maps to this record or a first-writer
            # predecessor covering the same prefix).
            assert hrec.digests[-1] in self._host_chains
