"""Ring attention: context parallelism over the `sequence` mesh axis.

The capability SURVEY.md §5 flags as absent from the reference in any form
("no ring attention, no context/sequence parallel") — its era scaled replica
count, not sequence length.  Here long-context is first-class: the sequence
dimension of q/k/v is sharded over the `sequence` mesh axis, each device
keeps its resident query block, and key/value blocks rotate around the ring
via ``ppermute`` — on a TPU slice that permutation compiles to
neighbour-to-neighbour ICI transfers, overlapping each hop with the local
blockwise attention (the Ring Attention schedule of Liu et al. 2023,
per PAPERS.md).

Numerics: each (q-block, kv-block) pair yields a partial output plus a
log-sum-exp; partials combine with the standard online-softmax merge, so
the result is exactly softmax attention — verified bit-close against the
single-device reference in tests/test_ring.py.

Memory: O(seq/ring_size) per device — sequence length scales linearly with
the mesh axis.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from kubeflow_tpu.parallel.mesh import DATA, FSDP, SEQUENCE, TENSOR

NEG_INF = float(jnp.finfo(jnp.float32).min)


def _block_partial(
    q: jax.Array, k: jax.Array, v: jax.Array,
    q_offset: jax.Array, k_offset: jax.Array, causal: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One (q-block, kv-block) partial of the online-softmax recurrence.

    q: [b, sq, h, d]; k/v: [b, sk, h, d]; offsets are the blocks' absolute
    sequence positions (traced values — the ring step index is dynamic).
    Returns (u, m, l): u = sum_k exp(s - m) v  [b, sq, h, d] fp32,
    m = rowwise max score [b, h, sq] (NEG_INF if fully masked),
    l = sum_k exp(s - m)  [b, h, sq].
    """
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])[:, None]
        k_pos = k_offset + jnp.arange(k.shape[1])[None, :]
        scores = jnp.where(
            (q_pos >= k_pos)[None, None], scores, NEG_INF
        )
    m = jnp.max(scores, axis=-1)                       # [b, h, q]
    safe_m = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(scores - safe_m[..., None])
    p = jnp.where(jnp.isfinite(m)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)                            # [b, h, q]
    u = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return u, m, l


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQUENCE,
    causal: bool = True,
) -> jax.Array:
    """Per-shard ring attention body — call inside shard_map.

    q/k/v: the local sequence shard [b, s_local, h_local, d].  Requires the
    global sequence be evenly sharded over ``axis_name``.
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[1]
    q_offset = my_idx * s_local

    def expand(w):
        # [b, h, q] -> [b, q, h, 1] for broadcasting against u.
        return w.swapaxes(1, 2)[..., None]

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def body(step, carry):
        u_acc, m_acc, l_acc, k_cur, v_cur = carry
        src = (my_idx - step) % axis_size          # whose kv block we hold
        u_p, m_p, l_p = _block_partial(
            q, k_cur, v_cur, q_offset, src * s_local, causal
        )
        # Rotate kv to the next device; overlapped with the merge math.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        # Online-softmax merge of (u, m, l) pairs.
        m_new = jnp.maximum(m_acc, m_p)
        safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        a_acc = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - safe), 0.0)
        a_p = jnp.where(jnp.isfinite(m_p), jnp.exp(m_p - safe), 0.0)
        u_new = u_acc * expand(a_acc) + u_p * expand(a_p)
        l_new = l_acc * a_acc + l_p * a_p
        return u_new, m_new, l_new, k_nxt, v_nxt

    b, s, h, d = q.shape
    # Initial accumulators must carry the same varying-manual-axes type as
    # the loop outputs (shard_map vma rule), so derive them from q.
    vma = tuple(jax.typeof(q).vma)
    vary = lambda x: jax.lax.pcast(x, vma, to="varying")
    u0 = vary(jnp.zeros((b, s, h, d), jnp.float32))
    m0 = vary(jnp.full((b, h, s), NEG_INF, jnp.float32))
    l0 = vary(jnp.zeros((b, h, s), jnp.float32))
    u, m, l, _, _ = jax.lax.fori_loop(
        0, axis_size, body, (u0, m0, l0, k, v)
    )
    out = u / jnp.maximum(expand(l), 1e-37)
    return out.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    *,
    causal: bool = True,
    axis_name: str = SEQUENCE,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """shard_map-wrapped ring attention over a mesh.

    Layout contract (matches DEFAULT_RULES): batch over (data, fsdp),
    sequence over `sequence`, heads over `tensor`.
    """
    spec = PartitionSpec((DATA, FSDP), axis_name, TENSOR, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
    )
    def fn(q, k, v):
        return ring_attention(q, k, v, axis_name=axis_name, causal=causal)

    return fn
