"""Ring attention: context parallelism over the `sequence` mesh axis.

The capability SURVEY.md §5 flags as absent from the reference in any form
("no ring attention, no context/sequence parallel") — its era scaled replica
count, not sequence length.  Here long-context is first-class: the sequence
dimension of q/k/v is sharded over the `sequence` mesh axis, each device
keeps its resident query block, and key/value blocks rotate around the ring
via ``ppermute`` — on a TPU slice that permutation compiles to
neighbour-to-neighbour ICI transfers, overlapping each hop with the local
blockwise attention (the Ring Attention schedule of Liu et al. 2023,
per PAPERS.md).

Composition with the Pallas flash kernel (ops/flash.py): each hop computes
its local block with ``flash_fwd_with_lse`` — VMEM-blockwise, O(s_local)
memory — and hops merge in log-sum-exp space, which is exactly the online
softmax recurrence lifted to the ring level.  Causal hops are classified
statically-per-branch (kv strictly behind the resident queries -> unmasked
kernel; the diagonal hop -> causal kernel; kv strictly ahead -> skipped
entirely), so the causal schedule does half the FLOPs and each branch's
kernel has a static mask shape.

Backward is a custom VJP that *re-rotates* the kv ring instead of saving
per-hop residuals: dk/dv partial gradients travel around the ring with
their kv blocks and arrive home after axis_size hops.  Training memory is
therefore O(s_local) = O(s/ring) — the whole point of ring attention —
rather than the O(s) per device a scanned-and-saved forward would keep.

Numerics: partials combine with the standard log-space online-softmax
merge, so the result is exactly softmax attention — verified against the
single-device reference in tests/test_ring.py.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec

from kubeflow_tpu.parallel.mesh import DATA, FSDP, SEQUENCE, TENSOR

NEG_INF = float(jnp.finfo(jnp.float32).min)


# ---------------------------------------------------------------------------
# Per-hop block attention: (o fp32 [b,s,h,d], lse fp32 [b,h,s])
# ---------------------------------------------------------------------------


def _xla_block_fwd(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool
) -> Tuple[jax.Array, jax.Array]:
    """XLA fallback block (equal head counts): one (q-block, kv-block)
    attention with its log-sum-exp.  O(s_local^2) transient — used off-TPU
    where Pallas isn't available; the hermetic CPU tests run through it."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])[:, None]
        k_pos = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    m = jnp.max(s, axis=-1)                            # [b, h, q]
    safe_m = jnp.where(m > NEG_INF / 2, m, 0.0)
    p = jnp.exp(s - safe_m[..., None])
    p = jnp.where((m > NEG_INF / 2)[..., None], p, 0.0)
    l = jnp.sum(p, axis=-1)                            # [b, h, q]
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    o = o / jnp.maximum(l, 1e-37).swapaxes(1, 2)[..., None]
    lse = jnp.where(l > 0.0, safe_m + jnp.log(jnp.maximum(l, 1e-37)), NEG_INF)
    return o, lse


def _xla_block_bwd(
    q: jax.Array, k: jax.Array, v: jax.Array, g: jax.Array,
    lse: jax.Array, delta: jax.Array, causal: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """XLA fallback block backward.  lse/delta: [b, h, s]."""
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        q_pos = jnp.arange(q.shape[1])[:, None]
        k_pos = jnp.arange(k.shape[1])[None, :]
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    finite = lse > NEG_INF / 2                         # [b, h, q]
    p = jnp.where(
        finite[..., None],
        jnp.exp(s - jnp.where(finite, lse, 0.0)[..., None]),
        0.0,
    )                                                  # [b, h, q, k]
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bqhd->bkhd", p, g32)
    dp = jnp.einsum("bqhd,bkhd->bhqk", g32, v.astype(jnp.float32))
    ds = p * (dp - delta[..., None]) * scale
    dq = jnp.einsum("bhqk,bkhd->bqhd", ds, k.astype(jnp.float32))
    dk = jnp.einsum("bhqk,bqhd->bkhd", ds, q.astype(jnp.float32))
    return dq, dk, dv


def _use_flash(use_flash: Optional[bool]) -> bool:
    if use_flash is None:
        return jax.default_backend() == "tpu"
    return use_flash


def _block_fwd(q, k, v, causal, use_flash, block_q, block_k, interpret):
    if _use_flash(use_flash) or interpret:
        from kubeflow_tpu.ops.flash import flash_fwd_with_lse

        o, lse = flash_fwd_with_lse(
            q, k, v, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret,
        )
        return o.astype(jnp.float32), lse
    return _xla_block_fwd(q, k, v, causal)


def _block_bwd(q, k, v, g, lse, delta, causal, use_flash, block_q, block_k,
               interpret):
    if _use_flash(use_flash) or interpret:
        from kubeflow_tpu.ops.flash import flash_bwd_block

        return flash_bwd_block(
            q, k, v, g, lse, delta, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret,
        )
    return _xla_block_bwd(q, k, v, g, lse, delta, causal)


# ---------------------------------------------------------------------------
# Ring schedule (runs inside shard_map)
# ---------------------------------------------------------------------------


def _merge(o_acc, lse_acc, o_p, lse_p):
    """Log-space online-softmax merge of two normalized partials:
    o [b, s, h, d] with lse [b, h, s].  The sentinel/floor numerics
    live in ONE place — ops/flash.py merge_partials (shared with the
    two-pass forward); this wrapper only adapts the ring's lse layout
    (head-major) to the o-aligned layout the core expects."""
    from kubeflow_tpu.ops.flash import merge_partials

    o_new, lse_aligned = merge_partials(
        o_acc, lse_acc.swapaxes(1, 2), o_p, lse_p.swapaxes(1, 2))
    return o_new, lse_aligned.swapaxes(1, 2)


def _fold_heads(dk, hkv):
    """Transpose of jnp.repeat(axis=2): sum gradient over each head group."""
    b, s, h, d = dk.shape
    if h == hkv:
        return dk
    return dk.reshape(b, s, hkv, h // hkv, d).sum(axis=3)


def _vary_like(x, ref):
    """Give constant x ref's varying-manual-axes type (shard_map requires
    loop carries / switch branches to agree on vma)."""
    return jax.lax.pcast(x, tuple(jax.typeof(ref).vma), to="varying")


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring(q, k, v, axis_name, causal, use_flash, block_q, block_k, interpret):
    o, _ = _ring_fwd_impl(
        q, k, v, axis_name, causal, use_flash, block_q, block_k, interpret
    )
    return o.astype(q.dtype)


def _ring_fwd_impl(q, k, v, axis_name, causal, use_flash, block_q, block_k,
                   interpret):
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    block = functools.partial(
        _block_fwd, use_flash=use_flash, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )

    # GQA kv-head broadcast happens INSIDE each live branch, so skipped
    # hops (and the rotation itself) never materialize the repeated heads.
    from kubeflow_tpu.ops.flash import repeat_kv

    def hop_partial(step_src, k_cur, v_cur):
        if not causal:
            return block(q, *repeat_kv(k_cur, v_cur, h), causal=False)

        def skip(k_cur, v_cur):
            return (
                _vary_like(jnp.zeros((b, s, h, d), jnp.float32), q),
                _vary_like(jnp.full((b, h, s), NEG_INF, jnp.float32), q),
            )

        def full(k_cur, v_cur):
            return block(q, *repeat_kv(k_cur, v_cur, h), causal=False)

        def diag(k_cur, v_cur):
            return block(q, *repeat_kv(k_cur, v_cur, h), causal=True)

        # src > my_idx: kv strictly ahead of every resident query -> dead.
        case = jnp.where(
            step_src == my_idx, 2, jnp.where(step_src < my_idx, 1, 0)
        )
        return jax.lax.switch(case, [skip, full, diag], k_cur, v_cur)

    def body(step, carry):
        o_acc, lse_acc, k_cur, v_cur = carry
        src = (my_idx - step) % axis_size          # whose kv block we hold
        o_p, lse_p = hop_partial(src, k_cur, v_cur)
        # Rotate kv to the next device; overlapped with the merge math.
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        o_new, lse_new = _merge(o_acc, lse_acc, o_p, lse_p)
        return o_new, lse_new, k_nxt, v_nxt

    o0 = _vary_like(jnp.zeros((b, s, h, d), jnp.float32), q)
    lse0 = _vary_like(jnp.full((b, h, s), NEG_INF, jnp.float32), q)
    o, lse, _, _ = jax.lax.fori_loop(0, axis_size, body, (o0, lse0, k, v))
    return o, lse


def _ring_vjp_fwd(q, k, v, axis_name, causal, use_flash, block_q, block_k,
                  interpret):
    o, lse = _ring_fwd_impl(
        q, k, v, axis_name, causal, use_flash, block_q, block_k, interpret
    )
    return o.astype(q.dtype), (q, k, v, o.astype(q.dtype), lse)


def _ring_vjp_bwd(axis_name, causal, use_flash, block_q, block_k, interpret,
                  res, g):
    q, k, v, o, lse = res
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    b, s, h, d = q.shape
    hkv = k.shape[2]
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]
    delta = jnp.sum(
        g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    ).swapaxes(1, 2)                                   # [b, h, s]
    block = functools.partial(
        _block_bwd, use_flash=use_flash, block_q=block_q, block_k=block_k,
        interpret=interpret,
    )

    from kubeflow_tpu.ops.flash import repeat_kv

    def hop_grads(step_src, k_cur, v_cur):
        def run(k_cur, v_cur, causal_block):
            kr, vr = repeat_kv(k_cur, v_cur, h)
            dq, dk, dv = block(q, kr, vr, g, lse, delta,
                               causal=causal_block)
            return (dq.astype(jnp.float32), dk.astype(jnp.float32),
                    dv.astype(jnp.float32))

        def zeros(k_cur, v_cur):
            z = _vary_like(jnp.zeros((b, s, h, d), jnp.float32), q)
            return z, z, z

        def full(k_cur, v_cur):
            return run(k_cur, v_cur, False)

        def diag(k_cur, v_cur):
            return run(k_cur, v_cur, True)

        if not causal:
            return full(k_cur, v_cur)
        case = jnp.where(
            step_src == my_idx, 2, jnp.where(step_src < my_idx, 1, 0)
        )
        return jax.lax.switch(case, [zeros, full, diag], k_cur, v_cur)

    def body(step, carry):
        dq_acc, dk_rot, dv_rot, k_cur, v_cur = carry
        src = (my_idx - step) % axis_size
        dq_p, dk_p, dv_p = hop_grads(src, k_cur, v_cur)
        dq_acc = dq_acc + dq_p
        # dk/dv partials travel WITH their kv block: after axis_size
        # rotations both the block and its accumulated gradient are home.
        dk_rot = dk_rot + _fold_heads(dk_p, hkv)
        dv_rot = dv_rot + _fold_heads(dv_p, hkv)
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        dk_nxt = jax.lax.ppermute(dk_rot, axis_name, perm)
        dv_nxt = jax.lax.ppermute(dv_rot, axis_name, perm)
        return dq_acc, dk_nxt, dv_nxt, k_nxt, v_nxt

    dq0 = _vary_like(jnp.zeros((b, s, h, d), jnp.float32), q)
    dkv0 = _vary_like(jnp.zeros((b, s, hkv, d), jnp.float32), q)
    dq, dk, dv, _, _ = jax.lax.fori_loop(
        0, axis_size, body, (dq0, dkv0, dkv0, k, v)
    )
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_vjp_fwd, _ring_vjp_bwd)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQUENCE,
    causal: bool = True,
    use_flash: Optional[bool] = None,
    block_q: int = 512,
    block_k: int = 512,
    interpret: bool = False,
) -> jax.Array:
    """Per-shard ring attention body — call inside shard_map.

    q/k/v: the local sequence shard [b, s_local, h_local, d]; GQA welcome
    (kv heads rotate unrepeated — less ICI traffic — and are broadcast to
    the query head count only inside each hop's kernel call).  Requires
    the global sequence be evenly sharded over ``axis_name``.

    use_flash: None = auto (Pallas kernel on TPU, XLA block off-TPU).
    """
    return _ring(
        q, k, v, axis_name, causal, use_flash, block_q, block_k, interpret
    )


def make_ring_attention(
    mesh: Mesh,
    *,
    causal: bool = True,
    axis_name: str = SEQUENCE,
    use_flash: Optional[bool] = None,
    block_q: int = 512,
    block_k: int = 512,
) -> Callable[[jax.Array, jax.Array, jax.Array], jax.Array]:
    """shard_map-wrapped ring attention over a mesh.

    Layout contract (matches DEFAULT_RULES): batch over (data, fsdp),
    sequence over `sequence`, heads over `tensor`.
    """
    spec = PartitionSpec((DATA, FSDP), axis_name, TENSOR, None)

    @functools.partial(
        jax.shard_map, mesh=mesh,
        in_specs=(spec, spec, spec), out_specs=spec,
    )
    def fn(q, k, v):
        return ring_attention(
            q, k, v, axis_name=axis_name, causal=causal,
            use_flash=use_flash, block_q=block_q, block_k=block_k,
        )

    return fn
