"""Pipeline parallelism: GPipe schedule over the `pipeline` mesh axis.

Absent from the reference in any form (SURVEY.md §2.3 "Pipeline parallel:
absent").  TPU-native design: the layer dimension of a scanned model
(params stacked [L, ...], see models/transformer.py nn.scan) is sharded
over the `pipeline` axis — each device group holds L/S contiguous layers —
and microbatches stream through the ring via ``ppermute``.  All control
flow is a single ``lax.fori_loop`` (compiler-friendly: one trace, static
shapes), and the bubble is the standard (S-1)/(M+S-1) GPipe overhead.

The primitive is model-agnostic: ``pipelined_scan`` takes any per-layer
body ``fn(layer_params, x) -> x``.  The flagship Transformer wires its
block through it (models/transformer.py ``Transformer._pipelined_layers``)
when ``TransformerConfig.pipeline_microbatches > 0`` and the mesh has a
``pipeline`` axis > 1: shard_map is manual over the pipeline axis ONLY
(``axis_names={PIPELINE}``), so batch/fsdp/tensor stay auto-sharded and
XLA still inserts the usual collectives inside each stage.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from kubeflow_tpu.parallel.mesh import PIPELINE


def pipelined_scan(
    fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    *,
    axis_name: str = PIPELINE,
    with_aux: bool = False,
) -> jax.Array:
    """Run x through L layers, pipeline-parallel.  Call inside shard_map.

    fn: one layer body, fn(params_for_layer, activation) -> activation —
      or, with ``with_aux=True``, -> (activation, aux_scalar).
    stacked_params: pytree with leading dim = layers-per-stage (the global
      [L, ...] stack sharded over `axis_name`, so each stage holds L/S).
    x: microbatched activations [M, mb, ...] (replicated across the
      pipeline axis; the caller shards batch over data axes as usual).

    Returns [M, mb, ...] outputs, replicated across the pipeline axis.
    With ``with_aux=True`` returns ``(outputs, aux)``: the f32 sum of
    every layer's aux over all (layer, microbatch) pairs, psummed across
    stages — the MoE load-balance loss thread (VERDICT r4 item 3).  Only
    VALID schedule steps contribute: each stage runs M + S - 1 loop
    iterations but owns microbatch t - stage at step t, and the bubble
    steps compute on stale/zero activations whose aux must not leak into
    the loss (gradients included — the mask zeroes their cotangents).
    """
    n_stages = jax.lax.psum(1, axis_name)
    stage = jax.lax.axis_index(axis_name)
    n_micro = x.shape[0]
    total_steps = n_micro + n_stages - 1
    # stage s -> s+1; the wrap link (S-1 -> 0) carries no live data.
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def run_stage(act: jax.Array):
        # Sequential local layers: lax.scan over this stage's param stack.
        def body(carry, layer_params):
            if with_aux:
                out, aux = fn(layer_params, carry)
                return out, aux.astype(jnp.float32)
            return fn(layer_params, carry), None

        out, auxs = jax.lax.scan(body, act, stacked_params)
        return out, (jnp.sum(auxs) if with_aux else None)

    # The input stack enters the schedule as an explicitly VARYING f32
    # array (for narrow floats).  Two reasons, both about the transpose:
    # a replicated x used inside the varying loop would transpose to one
    # psum per use site, and any of those psums in bf16 aborts XLA's
    # partitioner inside a partial-manual shard_map ("Invalid binary
    # instruction opcode copy" — the Shardy custom-call root in the
    # reducer trips the bf16 all-reduce rewrite).  Hoisting one pcast
    # here makes the backward pay exactly ONE psum, of the f32 stack,
    # at this boundary.  Carries between stages stay in the original
    # dtype (ppermute is dtype-safe), so only the input stack pays the
    # wider ride.
    in_dtype = x.dtype
    ride_f32 = (jnp.issubdtype(in_dtype, jnp.floating)
                and jnp.finfo(in_dtype).bits < 32)
    x_stack = x.astype(jnp.float32) if ride_f32 else x

    # Loop carries become varying over the pipeline axis (stage-dependent
    # values flow through them) even when x enters replicated.  Each
    # array pcasts only the axes it is MISSING: under the composed
    # pp x ring shard_map the input is already varying over `sequence`,
    # and pcast rejects re-adding an axis already in the varying set.
    vma = {*jax.typeof(x).vma, axis_name}

    def vary(a):
        missing = tuple(vma - set(jax.typeof(a).vma))
        return jax.lax.pcast(a, missing, to="varying") if missing else a

    def vary_param(p):
        # Params replicated over a non-pipeline manual axis (sequence,
        # under the composed pp x ring shard_map) would otherwise get
        # their cotangent psum inserted implicitly at each USE site —
        # in the compute dtype, and a sub-f32 all-reduce inside a
        # partial-manual region aborts XLA's partitioner (the Shardy
        # constraint in the reducer trips AllReducePromotion's clone:
        # "Invalid binary instruction opcode copy").  One explicit
        # pcast here moves that psum to this boundary, riding f32 for
        # narrow-float leaves.
        missing = tuple(vma - set(jax.typeof(p).vma))
        if not missing:
            return p
        narrow = (jnp.issubdtype(p.dtype, jnp.floating)
                  and jnp.finfo(p.dtype).bits < 32)
        if narrow:
            return jax.lax.pcast(
                p.astype(jnp.float32), missing, to="varying"
            ).astype(p.dtype)
        return jax.lax.pcast(p, missing, to="varying")

    stacked_params = jax.tree_util.tree_map(vary_param, stacked_params)
    x_var = vary(x_stack)
    zero_mb = vary(jnp.zeros_like(x[0]))
    ys0 = vary(jnp.zeros(x.shape, in_dtype))

    aux0 = vary(jnp.zeros((), jnp.float32))

    def step(t, carry):
        recv, ys, aux_acc = carry
        # Stage 0 injects microbatch t (clamped; masked out when t >= M).
        mb_idx = jnp.clip(t, 0, n_micro - 1)
        injected = jax.lax.dynamic_index_in_dim(
            x_var, mb_idx, keepdims=False).astype(in_dtype)
        inp = jnp.where(stage == 0, injected, recv)
        out, aux = run_stage(inp)
        if with_aux:
            # This stage owns microbatch t - stage at step t; outside
            # [0, M) it is a bubble step whose aux is garbage.
            mb = t - stage
            aux_acc = aux_acc + jnp.where(
                (mb >= 0) & (mb < n_micro), aux, 0.0)
        # The last stage owns microbatch t-(S-1) at step t.
        out_idx = jnp.clip(t - (n_stages - 1), 0, n_micro - 1)
        is_valid = (t >= n_stages - 1) & (stage == n_stages - 1)
        updated = jax.lax.dynamic_update_index_in_dim(
            ys, jnp.where(is_valid, out, ys[out_idx]), out_idx, axis=0
        )
        nxt = jax.lax.ppermute(out, axis_name, perm)
        return nxt, updated, aux_acc

    _, ys, aux_acc = jax.lax.fori_loop(
        0, total_steps, step, (zero_mb, ys0, aux0))
    # Only the last stage holds real outputs; broadcast them to every
    # stage so downstream (loss) code is stage-agnostic.  The psum rides
    # f32 for sub-f32 floats: XLA's partitioner aborts ("Invalid binary
    # instruction opcode copy") on a bf16 all-reduce inside a
    # partial-manual shard_map, and the detour is exact here — every
    # stage but one contributes zeros, so the f32 sum of bf16 values
    # round-trips bit-identically.
    ys = jnp.where(stage == n_stages - 1, ys, jnp.zeros_like(ys))
    if jnp.issubdtype(ys.dtype, jnp.floating) and \
            jnp.finfo(ys.dtype).bits < 32:
        ys = jax.lax.psum(
            ys.astype(jnp.float32), axis_name).astype(ys.dtype)
    else:
        ys = jax.lax.psum(ys, axis_name)
    if with_aux:
        # Each stage accumulated its OWN layers' aux; the total is the
        # sum across stages (f32, so no sub-f32 all-reduce detour).
        return ys, jax.lax.psum(aux_acc, axis_name)
    return ys


def microbatch(x: jax.Array, n_micro: int) -> jax.Array:
    """[B, ...] -> [M, B/M, ...]."""
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {n_micro} microbatches"
        )
    return x.reshape(n_micro, x.shape[0] // n_micro, *x.shape[1:])


def unmicrobatch(x: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [B, ...]."""
    return x.reshape(x.shape[0] * x.shape[1], *x.shape[2:])
