"""Parallelism library: device meshes, sharding rules, collectives.

TPU-native replacement for the reference's PS/MPI/NCCL distribution
machinery (SURVEY.md §2.3-2.4): one mesh abstraction covers data, FSDP,
pipeline, expert, sequence, and tensor parallelism, with collectives
compiled by XLA onto ICI/DCN instead of daemons and hostfiles.
"""

from kubeflow_tpu.parallel.mesh import (
    AXIS_ORDER,
    DATA,
    DEFAULT_RULES,
    EXPERT,
    FSDP,
    PIPELINE,
    SEQUENCE,
    TENSOR,
    MeshSpec,
    batch_sharding,
    constrain,
    logical_spec,
    named_sharding,
    replicated,
)

__all__ = [
    "AXIS_ORDER",
    "DATA",
    "FSDP",
    "PIPELINE",
    "EXPERT",
    "SEQUENCE",
    "TENSOR",
    "DEFAULT_RULES",
    "MeshSpec",
    "batch_sharding",
    "constrain",
    "logical_spec",
    "named_sharding",
    "replicated",
]
