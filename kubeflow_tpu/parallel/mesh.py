"""Device mesh construction and logical-axis sharding rules.

This is the TPU-native replacement for the reference's entire distribution
story.  The reference expressed parallelism as *replica counts in a CRD*
(num_ps/num_workers, kubeflow/tf-job/prototypes/tf-job.jsonnet:11-14) wired
together by TF_CONFIG/gRPC or an MPI hostfile
(kubeflow/openmpi/assets.libsonnet:27-38).  Here parallelism is a *mesh*:
a named, multi-dimensional view of the slice's devices over which arrays are
sharded and XLA compiles the collectives.  One MeshSpec subsumes what the
reference spread across three job kinds (TFJob PS-parallelism, PyTorchJob
DDP, openmpi allreduce) and adds the axes the reference never had: tensor,
sequence/context, expert, and pipeline parallelism (SURVEY.md §2.3).

Axis order matters on hardware: the innermost axes map onto the ICI torus
closest together, so put the most communication-hungry axis (tensor) last.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Optional, Sequence, Tuple, Union

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from kubeflow_tpu.runtime.topology import SliceTopology

# Canonical mesh axis names, outermost -> innermost.  Data-parallel axes
# (data, fsdp) tolerate slow links so they get the outermost placement
# (cross-slice DCN when multi-slice); tensor parallelism is latency-bound
# and must ride adjacent-ICI, so it is innermost.
DATA = "data"
FSDP = "fsdp"
PIPELINE = "pipeline"
EXPERT = "expert"
SEQUENCE = "sequence"
TENSOR = "tensor"

AXIS_ORDER: Tuple[str, ...] = (DATA, FSDP, PIPELINE, EXPERT, SEQUENCE, TENSOR)


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A declarative parallelism layout: axis name -> size.

    Sizes of 1 are kept (so PartitionSpecs referencing the axis stay valid);
    a single axis may be -1 meaning "absorb all remaining devices".  This is
    the typed heir of the reference's stringly num_ps/num_workers params
    (SURVEY.md §5 "config/flag system" warts).
    """

    data: int = -1
    fsdp: int = 1
    pipeline: int = 1
    expert: int = 1
    sequence: int = 1
    tensor: int = 1

    def sizes(self, n_devices: int) -> Dict[str, int]:
        """Resolve -1 against a device count; validate divisibility."""
        raw = {
            DATA: self.data,
            FSDP: self.fsdp,
            PIPELINE: self.pipeline,
            EXPERT: self.expert,
            SEQUENCE: self.sequence,
            TENSOR: self.tensor,
        }
        bad = {k: v for k, v in raw.items() if v < 1 and v != -1}
        if bad:
            raise ValueError(
                f"axis sizes must be positive (or -1 to infer), got {bad}"
            )
        wild = [k for k, v in raw.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one axis may be -1, got {wild}")
        fixed = math.prod(v for v in raw.values() if v != -1)
        if wild:
            if n_devices % fixed:
                raise ValueError(
                    f"fixed axes product {fixed} does not divide {n_devices} devices"
                )
            raw[wild[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh {raw} has {fixed} slots but the slice has {n_devices} devices"
            )
        return raw

    def build(
        self,
        devices: Optional[Sequence[jax.Device]] = None,
        topology: Optional[SliceTopology] = None,
    ) -> Mesh:
        """Construct a jax Mesh over the given (or all) devices.

        On real TPU slices ``jax.devices()`` is already ordered so that
        contiguous runs are ICI-adjacent; reshaping in AXIS_ORDER therefore
        lands the tensor axis on neighbouring chips.
        """
        devs = list(devices if devices is not None else jax.devices())
        if topology is not None and topology.devices != len(devs):
            raise ValueError(
                f"topology {topology.name} expects {topology.devices} devices, "
                f"runtime sees {len(devs)}"
            )
        sizes = self.sizes(len(devs))
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        return Mesh(np.asarray(devs).reshape(shape), AXIS_ORDER)

    @property
    def dp_axes(self) -> Tuple[str, ...]:
        """Axes over which gradients are averaged (batch-sharding axes)."""
        return (DATA, FSDP)


# ---------------------------------------------------------------------------
# Logical axis rules
#
# Models annotate arrays with *logical* dimension names; one rule table maps
# them to mesh axes.  Changing the parallelism layout is then a config edit,
# not a model edit — the property the reference achieved for replica counts
# via prototype params, extended to intra-array sharding.
# ---------------------------------------------------------------------------

LogicalRules = Tuple[Tuple[str, Union[str, Tuple[str, ...], None]], ...]

# Default rule table for transformer + conv models (megatron-style TP with
# FSDP weight sharding on the embed dim — the maxtext-proven layout):
#   - weights: embed dim over fsdp (ZeRO-3: gathered per layer), heads/mlp/
#     vocab over tensor (column/row-parallel matmuls; XLA inserts the
#     all-reduce after the row-parallel projection);
#   - activations: batch over (data, fsdp), seq over sequence (ring
#     attention / context parallelism).
DEFAULT_RULES: LogicalRules = (
    ("batch", (DATA, FSDP)),        # global batch sharded over both dp axes
    ("seq", SEQUENCE),              # context parallelism (ring attention)
    ("embed", FSDP),                # weight embed dim: ZeRO-3 over fsdp
    ("act_embed", None),            # activation feature dim between blocks
    ("heads", TENSOR),              # attention heads split across TP
    ("kv", None),                   # per-head dim never sharded
    ("mlp", TENSOR),                # MLP hidden dim split across TP
    ("vocab", TENSOR),              # embedding/output table split
    ("expert", EXPERT),             # MoE expert dim
    ("stage", PIPELINE),            # pipeline stage dim
    ("layers", PIPELINE),           # nn.scan layer stack: L/S layers per
                                    # stage under GPipe (no-op at pipeline=1)
    ("conv_out", None),             # conv channels replicated (ResNet is DP-only)
    ("norm", None),
)


def rules_to_dict(rules: LogicalRules) -> Dict[str, Union[str, Tuple[str, ...], None]]:
    return dict(rules)


def logical_spec(
    logical_axes: Sequence[Optional[str]], rules: LogicalRules = DEFAULT_RULES
) -> PartitionSpec:
    """Map a tuple of logical dim names to a PartitionSpec via the rule table.

    Unknown or None logical names become unsharded dims.  A mesh axis may be
    used at most once per spec (jax requirement); later duplicates degrade to
    None rather than erroring, so e.g. ("embed", "mlp") with both mapped to
    TENSOR shards only the first.
    """
    table = rules_to_dict(rules)
    used: set = set()
    out: List[Union[str, Tuple[str, ...], None]] = []
    for name in logical_axes:
        target = table.get(name) if name is not None else None
        if target is None:
            out.append(None)
            continue
        axes = (target,) if isinstance(target, str) else tuple(target)
        free = tuple(a for a in axes if a not in used)
        if not free:
            out.append(None)
            continue
        used.update(free)
        out.append(free[0] if len(free) == 1 else free)
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def named_sharding(
    mesh: Mesh,
    logical_axes: Sequence[Optional[str]],
    rules: LogicalRules = DEFAULT_RULES,
) -> NamedSharding:
    return NamedSharding(mesh, logical_spec(logical_axes, rules))


def constrain(x, mesh: Mesh, logical_axes: Sequence[Optional[str]],
              rules: LogicalRules = DEFAULT_RULES):
    """with_sharding_constraint by logical names (no-op outside jit)."""
    return jax.lax.with_sharding_constraint(
        x, named_sharding(mesh, logical_axes, rules)
    )


def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Sharding for a [batch, ...] input array: batch over (data, fsdp)."""
    return NamedSharding(mesh, PartitionSpec((DATA, FSDP), *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec())
