"""E2E test drivers — heir of testing/test_deploy.py's argparse
subcommands (deploy_model :160-190, deploy_pytorchjob :219-235,
teardown :520-626), each wrapped into JUnit artifacts.

Two backends: against a real cluster these drive kubectl-applied
manifests; hermetically they drive the FakeKube + reconciler, which is
how CI exercises the full TPUJob lifecycle without hardware (the
improvement SURVEY.md §4 calls for over the reference's rented-VM
strategy).
"""

from __future__ import annotations

import argparse
import sys
import time

from kubeflow_tpu.testing.junit import JUnitSuite


def tpujob_smoke(namespace: str = "kubeflow-test") -> None:
    """Submit a tiny TPUJob to the in-process control plane and drive it
    to completion — the simple_tfjob equivalent
    (testing/workflows/components/workflows.libsonnet:398-411)."""
    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler
    from kubeflow_tpu.operator.kube import RUNNING, SUCCEEDED, FakeKube
    from kubeflow_tpu.operator.reconciler import (
        JOB_RUNNING,
        JOB_SUCCEEDED,
        TPUJobController,
    )

    kube = FakeKube()
    controller = TPUJobController(kube, GangScheduler({"v5e-8": 1}))
    job = crd.TPUJobSpec(name="smoke", namespace=namespace,
                         slice_type="v5e-8")
    kube.create_custom(job.to_custom_resource())
    cr = kube.list_custom()[0]
    controller.reconcile_once(cr)
    for pod in kube.list_pods(namespace):
        kube.set_pod_phase(namespace, pod["metadata"]["name"], RUNNING)
    assert controller.reconcile_once(cr) == JOB_RUNNING
    for pod in kube.list_pods(namespace):
        kube.set_pod_phase(namespace, pod["metadata"]["name"], SUCCEEDED)
    assert controller.reconcile_once(cr) == JOB_SUCCEEDED


def serving_smoke(namespace: str = "kubeflow-test") -> None:
    """Export a tiny model, serve it over HTTP, assert a live predict —
    the inception-golden equivalent (testing/test_tf_serving.py)."""
    import json
    import tempfile
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.resnet import ResNet18
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.model_server import ModelServer

    with tempfile.TemporaryDirectory() as tmp:
        model = ResNet18(num_classes=4, num_filters=8)
        variables = model.init(
            jax.random.key(0), np.zeros((1, 32, 32, 3), np.float32),
            train=False)
        export(f"{tmp}/m", 1, variables,
               loader="kubeflow_tpu.serving.loaders:classifier",
               config={"family": "resnet18", "num_classes": 4,
                       "num_filters": 8},
               signature={"inputs": ["image"]})
        server = ModelServer()
        server.add_model("m", f"{tmp}/m")
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            body = json.dumps({"instances": [
                {"image": np.zeros((32, 32, 3), np.float32).tolist()}
            ]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/model/m:predict", data=body)
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
            assert len(out["predictions"]) == 1
            scores = out["predictions"][0]["scores"]
            assert abs(sum(scores) - 1.0) < 1e-3
        finally:
            httpd.shutdown()


def engine_smoke(namespace: str = "kubeflow-test") -> None:
    """Admit mixed-length LM requests through the HTTP surface against
    the in-process continuous-batching DecodeEngine: all must complete
    (in-flight admission + slot reuse, 3 requests through 2 slots) and
    the engine must report zero occupancy and an empty queue after."""
    import json
    import tempfile
    import threading
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 8
    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    with tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        server = ModelServer()
        server.add_model("lm", f"{tmp}/lm")
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=16))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            rng = np.random.RandomState(0)
            prompts = [rng.randint(1, 128, size=(n,)).tolist()
                       for n in (3, 9, 16)]
            outs: dict = {}

            def client(i, prompt):
                body = json.dumps(
                    {"instances": [{"tokens": prompt}]}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/model/lm:predict",
                    data=body)
                with urllib.request.urlopen(req, timeout=120) as resp:
                    outs[i] = json.loads(resp.read())

            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, prompt in enumerate(prompts):
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            # Occupancy must return to zero once the work drains (the
            # :stats route reads the engine's locked snapshot).
            deadline = time.time() + 30
            while True:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/model/lm:stats",
                        timeout=30) as resp:
                    stats = json.loads(resp.read())["batcher"]
                if (stats["active_slots"] == 0
                        and stats["queue_depth"] == 0
                        and stats["in_flight_requests"] == 0):
                    break
                assert time.time() < deadline, (
                    f"engine never drained: {stats}")
                time.sleep(0.05)
            assert stats["requests"] == len(prompts)
        finally:
            httpd.shutdown()
            server.stop()


def fault_injection_smoke(namespace: str = "kubeflow-test") -> None:
    """Seeded chaos scenario against the whole serving fault layer,
    driven by the KFT_FAULTS harness (kubeflow_tpu/testing/faults.py):

      1. overload shed — slots full + queue full => HTTP 429 with a
         Retry-After header, while accepted requests still complete;
      2. deadline expiry MID-GENERATION (slow steps injected) => HTTP
         504, and the freed slot serves a follow-up request;
      3. loader circuit-break — a corrupt model version trips the
         reload breaker (no loader hot-loop) while the last-good
         version keeps serving; a fixed version recovers;
      4. graceful drain — /readyz flips 503 with a request in flight,
         /healthz stays 200, and the accepted request completes;
      5. every shed/expired/reload-failure is visible in kft_* metrics.

    Override the scenario by exporting KFT_FAULTS (same grammar).
    """
    import json
    import os
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory, wait_for_drain
    from kubeflow_tpu.serving.model_server import ModelServer
    from kubeflow_tpu.testing import faults

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 16
    scenario = os.environ.get(faults.ENV) or \
        "seed=20260803;engine.step:sleep=0.03"
    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))

    def predict_req(port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/lm:predict",
            data=json.dumps(body).encode())
        try:
            with urllib.request.urlopen(req, timeout=180) as resp:
                return resp.status, dict(resp.headers), \
                    json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    def engine_stats(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/model/lm:stats",
                timeout=30) as resp:
            return json.loads(resp.read())["batcher"]

    prompt = list(range(1, 9))
    body_full = {"instances": [{"tokens": prompt}]}
    with faults.injected(scenario) as inj, \
            tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        server = ModelServer(reload_backoff_s=0.5)
        server.add_model("lm", f"{tmp}/lm")
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=1,
            lm_engine_prefill_len=16, max_queue_depth=1))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        port = httpd.server_address[1]
        try:
            # -- 1. overload shed ---------------------------------------
            results: dict = {}

            def client(i, body):
                results[i] = predict_req(port, body)

            t0 = threading.Thread(target=client, args=(0, body_full))
            t0.start()
            deadline = time.time() + 120
            while engine_stats(port)["in_flight_requests"] < 1:
                assert time.time() < deadline, "first request never ran"
                time.sleep(0.01)
            # Slot busy (slow steps injected): 4 more arrivals — the
            # single queue seat takes one, the rest shed as 429.
            burst = [threading.Thread(target=client, args=(i, body_full))
                     for i in range(1, 5)]
            for t in burst:
                t.start()
            for t in [t0] + burst:
                t.join(timeout=180)
            codes = sorted(results[i][0] for i in range(5))
            assert codes.count(429) >= 1, codes
            assert codes.count(200) >= 2, codes  # slot + queue seat
            shed_headers = [results[i][1] for i in range(5)
                            if results[i][0] == 429]
            assert all(h.get("Retry-After") for h in shed_headers), (
                "429 responses must carry Retry-After")
            ok = [results[i][2] for i in range(5)
                  if results[i][0] == 200]
            for out in ok:
                tokens = out["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            # -- 2. deadline expiry mid-generation ----------------------
            code, _, payload = predict_req(
                port, {**body_full, "deadline_ms": 120})
            assert code == 504, (code, payload)
            assert "deadline" in payload["error"].lower()
            # The expired request's slot is reclaimed: a follow-up
            # full-budget request completes on the same single slot.
            code, _, payload = predict_req(port, body_full)
            assert code == 200, (code, payload)
            stats = engine_stats(port)
            assert stats["deadline_expired"] >= 1, stats
            assert stats["shed"] >= 1, stats
            # -- 3. loader circuit-break --------------------------------
            os.makedirs(f"{tmp}/lm/2")
            with open(f"{tmp}/lm/2/model.json", "w") as f:
                f.write("{corrupt json")
            raised = False
            try:
                server.reload("lm")
            except Exception:
                raised = True
            assert raised, "corrupt version must raise"
            attempts = inj.fired("loader.load")
            # Breaker open: repeated polls (the watcher loop) skip the
            # loader entirely — no hot-loop on the corrupt artifact.
            for _ in range(5):
                assert server.reload("lm") is False
            assert inj.fired("loader.load") == attempts
            # Last-good version keeps serving through the open breaker.
            code, _, _ = predict_req(port, body_full)
            assert code == 200
            assert server.get("lm").version == 1
            # Half-open after backoff (policy clock skipped forward):
            # the trial load runs, still corrupt, breaker re-opens.
            inj.advance_clock(30)
            raised = False
            try:
                server.reload("lm")
            except Exception:
                raised = True
            assert raised, "still-corrupt version must raise"
            assert inj.fired("loader.load") == attempts + 1
            # A NEW good version resets the breaker and loads at once.
            export(f"{tmp}/lm", 3, variables,
                   loader="kubeflow_tpu.serving.loaders:lm_generate",
                   config={"model": overrides,
                           "max_new_tokens": max_new,
                           "temperature": 0.0})
            assert server.reload("lm") is True
            assert server.get("lm").version == 3
            code, _, _ = predict_req(port, body_full)
            assert code == 200
            # -- 4. graceful drain --------------------------------------
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=30) as r:
                assert r.status == 200
            holder: dict = {}
            t = threading.Thread(
                target=lambda: holder.update(
                    {"resp": predict_req(port, body_full)}))
            t.start()
            deadline = time.time() + 120
            while server.inflight() < 1:
                assert time.time() < deadline, "drain request never ran"
                time.sleep(0.01)
            server.begin_drain()
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=30)
                raise AssertionError("/readyz must be 503 while draining")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["status"] == "draining"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
                assert r.status == 200  # alive, just not ready
            t.join(timeout=180)
            assert holder["resp"][0] == 200, (
                "request accepted before drain was lost")
            assert wait_for_drain(server, deadline_s=30)
            # -- 5. shed/expired/breaker visible in kft_* metrics -------
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
                metrics = r.read().decode()
            for needle in ('kft_serving_shed_total{batcher="lm-v1"}',
                           'kft_serving_deadline_expired_total'
                           '{batcher="lm-v1"}',
                           'kft_serving_reload_failures_total'
                           '{model="lm"}'):
                line = [ln for ln in metrics.splitlines()
                        if ln.startswith(needle)]
                assert line and float(line[0].rsplit(" ", 1)[1]) >= 1, (
                    f"expected a nonzero {needle} series")
        finally:
            httpd.shutdown()
            server.stop()


def train_smoke(namespace: str = "kubeflow-test") -> None:
    """A few real SPMD train steps on whatever devices exist."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.tools.train_cnn",
         "--model", "resnet18", "--steps", "2",
         "--batch-size-per-device", "2", "--image-size", "32",
         "--num-classes", "4"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def _kubectl(args, *, input_text: str = None, timeout: int = 300) -> str:
    import subprocess

    proc = subprocess.run(
        ["kubectl"] + args, input=input_text, text=True,
        capture_output=True, timeout=timeout,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"kubectl {' '.join(args)} failed: {proc.stderr[-2000:]}")
    return proc.stdout


def deploy_real(namespace: str = "kubeflow-test") -> None:
    """Deploy the platform to the CURRENT kubectl context and verify it
    comes up — the reference's center-of-gravity E2E
    (testing/test_deploy.py:160-190 deploy-then-verify; cluster may be
    kind/minikube/GKE, exactly as prow_config.yaml parameterised it).

    Renders the platform through the same registry path a user drives,
    applies it, then waits for every Deployment to roll out within the
    reference's 10-minute readiness budget (test_deploy.py:188-189).
    KFT_E2E_DEPLOY selects the prototypes (comma-separated; default the
    full kubeflow-core — clusters that can only pull a subset of images,
    e.g. kind with locally built ones, set e.g. `tpujob-operator`).
    """
    import os

    import kubeflow_tpu.manifests  # noqa: F401 — registers prototypes
    from kubeflow_tpu.config.registry import App
    from kubeflow_tpu.manifests.base import to_yaml

    app = App()
    prototypes = os.environ.get("KFT_E2E_DEPLOY", "kubeflow-core")
    for i, proto in enumerate(p.strip() for p in prototypes.split(",")):
        app.add(proto, f"c{i}-{proto}", namespace=namespace)
    objects = app.render()
    _kubectl(["create", "namespace", namespace,
              "--dry-run=client", "-o", "yaml"])  # validates kubectl works
    try:
        _kubectl(["create", "namespace", namespace])
    except RuntimeError:
        pass  # already exists
    _kubectl(["apply", "-n", namespace, "-f", "-"],
             input_text=to_yaml(objects))
    deployments = [o["metadata"]["name"] for o in objects
                   if o["kind"] == "Deployment"]
    for name in deployments:
        _kubectl(["rollout", "status", f"deployment/{name}",
                  "-n", namespace, "--timeout=600s"], timeout=650)


def deploy_crds(namespace: str = "kubeflow-test") -> None:
    """Apply only the CRDs (+ namespace) to the current context.

    The control-plane-only footing for clusters that cannot pull the
    platform images (ephemeral kind, ci/run_e2e_kind.sh): the operator
    then runs as a host process against the cluster, so exactly one
    reconciler owns the CRs."""
    import kubeflow_tpu.manifests  # noqa: F401
    from kubeflow_tpu.config.registry import default_registry
    from kubeflow_tpu.manifests.base import to_yaml

    objs = default_registry.generate("tpujob-operator", "op",
                                     namespace=namespace)
    crds = [o for o in objs if o["kind"] == "CustomResourceDefinition"]
    try:
        _kubectl(["create", "namespace", namespace])
    except RuntimeError:
        pass  # already exists
    _kubectl(["apply", "-f", "-"], input_text=to_yaml(crds))


def tpujob_real(namespace: str = "kubeflow-test") -> None:
    """Submit the tpu-job-simple example to the real cluster and poll the
    CR until the operator reports a terminal phase (the simple_tfjob
    check, workflows.libsonnet:398-411, against a live control plane)."""
    import json
    import os

    import kubeflow_tpu.manifests  # noqa: F401
    from kubeflow_tpu.config.registry import default_registry
    from kubeflow_tpu.manifests.base import to_yaml

    objs = default_registry.generate(
        "tpu-job-simple", "e2e-smoke", namespace=namespace,
        slice_type=os.environ.get("KFT_E2E_SLICE", "v5e-1"))
    _kubectl(["apply", "-n", namespace, "-f", "-"],
             input_text=to_yaml(objs))
    deadline = time.time() + 600
    phase = ""
    while time.time() < deadline:
        out = _kubectl(["get", "tpujobs.kubeflow-tpu.org", "e2e-smoke",
                        "-n", namespace, "-o", "json"])
        phase = json.loads(out).get("status", {}).get("phase", "")
        if phase in ("Succeeded", "Failed"):
            break
        time.sleep(5)
    assert phase == "Succeeded", f"TPUJob ended in phase {phase!r}"


def teardown(namespace: str = "kubeflow-test") -> None:
    """Hermetic backend has nothing persistent; real clusters delete the
    test namespace (the reference's teardown subcommand,
    test_deploy.py:520-626)."""
    try:
        _kubectl(["delete", "namespace", namespace, "--ignore-not-found"],
                 timeout=600)
    except (RuntimeError, FileNotFoundError):
        pass  # no cluster in hermetic runs — nothing to tear down


COMMANDS = {
    "tpujob": tpujob_smoke,
    "serving": serving_smoke,
    "engine": engine_smoke,
    "faults": fault_injection_smoke,
    "train": train_smoke,
    "deploy": deploy_real,
    "deploy-crds": deploy_crds,
    "tpujob-real": tpujob_real,
    "teardown": teardown,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubeflow-tpu-e2e")
    ap.add_argument("command", choices=sorted(COMMANDS))
    ap.add_argument("--namespace", default="kubeflow-test")
    ap.add_argument("--artifacts-dir", default="/tmp/artifacts")
    args = ap.parse_args(argv)

    suite = JUnitSuite(args.command)
    suite.run(args.command, lambda: COMMANDS[args.command](args.namespace))
    path = suite.write(args.artifacts_dir)
    print(f"junit: {path}", file=sys.stderr)
    return 0 if suite.ok else 1


if __name__ == "__main__":
    sys.exit(main())
