"""E2E test drivers — heir of testing/test_deploy.py's argparse
subcommands (deploy_model :160-190, deploy_pytorchjob :219-235,
teardown :520-626), each wrapped into JUnit artifacts.

Two backends: against a real cluster these drive kubectl-applied
manifests; hermetically they drive the FakeKube + reconciler, which is
how CI exercises the full TPUJob lifecycle without hardware (the
improvement SURVEY.md §4 calls for over the reference's rented-VM
strategy).
"""

from __future__ import annotations

import argparse
import sys
import time

from kubeflow_tpu.testing.junit import JUnitSuite


def tpujob_smoke(namespace: str = "kubeflow-test") -> None:
    """Submit a tiny TPUJob to the in-process control plane and drive it
    to completion — the simple_tfjob equivalent
    (testing/workflows/components/workflows.libsonnet:398-411)."""
    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler
    from kubeflow_tpu.operator.kube import RUNNING, SUCCEEDED, FakeKube
    from kubeflow_tpu.operator.reconciler import (
        JOB_RUNNING,
        JOB_SUCCEEDED,
        TPUJobController,
    )

    kube = FakeKube()
    controller = TPUJobController(kube, GangScheduler({"v5e-8": 1}))
    job = crd.TPUJobSpec(name="smoke", namespace=namespace,
                         slice_type="v5e-8")
    kube.create_custom(job.to_custom_resource())
    cr = kube.list_custom()[0]
    controller.reconcile_once(cr)
    for pod in kube.list_pods(namespace):
        kube.set_pod_phase(namespace, pod["metadata"]["name"], RUNNING)
    assert controller.reconcile_once(cr) == JOB_RUNNING
    for pod in kube.list_pods(namespace):
        kube.set_pod_phase(namespace, pod["metadata"]["name"], SUCCEEDED)
    assert controller.reconcile_once(cr) == JOB_SUCCEEDED


def serving_smoke(namespace: str = "kubeflow-test") -> None:
    """Export a tiny model, serve it over HTTP, assert a live predict —
    the inception-golden equivalent (testing/test_tf_serving.py)."""
    import json
    import tempfile
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.resnet import ResNet18
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.model_server import ModelServer

    with tempfile.TemporaryDirectory() as tmp:
        model = ResNet18(num_classes=4, num_filters=8)
        variables = model.init(
            jax.random.key(0), np.zeros((1, 32, 32, 3), np.float32),
            train=False)
        export(f"{tmp}/m", 1, variables,
               loader="kubeflow_tpu.serving.loaders:classifier",
               config={"family": "resnet18", "num_classes": 4,
                       "num_filters": 8},
               signature={"inputs": ["image"]})
        server = ModelServer()
        server.add_model("m", f"{tmp}/m")
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            body = json.dumps({"instances": [
                {"image": np.zeros((32, 32, 3), np.float32).tolist()}
            ]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/model/m:predict", data=body)
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
            assert len(out["predictions"]) == 1
            scores = out["predictions"][0]["scores"]
            assert abs(sum(scores) - 1.0) < 1e-3
        finally:
            httpd.shutdown()


def engine_smoke(namespace: str = "kubeflow-test") -> None:
    """Admit mixed-length LM requests through the HTTP surface against
    the in-process continuous-batching DecodeEngine: all must complete
    (in-flight admission + slot reuse, 3 requests through 2 slots) and
    the engine must report zero occupancy and an empty queue after."""
    import json
    import tempfile
    import threading
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 8
    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    with tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        server = ModelServer()
        server.add_model("lm", f"{tmp}/lm")
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=16))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            rng = np.random.RandomState(0)
            prompts = [rng.randint(1, 128, size=(n,)).tolist()
                       for n in (3, 9, 16)]
            outs: dict = {}

            def client(i, prompt):
                body = json.dumps(
                    {"instances": [{"tokens": prompt}]}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/model/lm:predict",
                    data=body)
                with urllib.request.urlopen(req, timeout=120) as resp:
                    outs[i] = json.loads(resp.read())

            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, prompt in enumerate(prompts):
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            # Occupancy must return to zero once the work drains (the
            # :stats route reads the engine's locked snapshot).
            deadline = time.time() + 30
            while True:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/model/lm:stats",
                        timeout=30) as resp:
                    stats = json.loads(resp.read())["batcher"]
                if (stats["active_slots"] == 0
                        and stats["queue_depth"] == 0
                        and stats["in_flight_requests"] == 0):
                    break
                assert time.time() < deadline, (
                    f"engine never drained: {stats}")
                time.sleep(0.05)
            assert stats["requests"] == len(prompts)
        finally:
            httpd.shutdown()
            server.stop()


def train_smoke(namespace: str = "kubeflow-test") -> None:
    """A few real SPMD train steps on whatever devices exist."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.tools.train_cnn",
         "--model", "resnet18", "--steps", "2",
         "--batch-size-per-device", "2", "--image-size", "32",
         "--num-classes", "4"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def _kubectl(args, *, input_text: str = None, timeout: int = 300) -> str:
    import subprocess

    proc = subprocess.run(
        ["kubectl"] + args, input=input_text, text=True,
        capture_output=True, timeout=timeout,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"kubectl {' '.join(args)} failed: {proc.stderr[-2000:]}")
    return proc.stdout


def deploy_real(namespace: str = "kubeflow-test") -> None:
    """Deploy the platform to the CURRENT kubectl context and verify it
    comes up — the reference's center-of-gravity E2E
    (testing/test_deploy.py:160-190 deploy-then-verify; cluster may be
    kind/minikube/GKE, exactly as prow_config.yaml parameterised it).

    Renders the platform through the same registry path a user drives,
    applies it, then waits for every Deployment to roll out within the
    reference's 10-minute readiness budget (test_deploy.py:188-189).
    KFT_E2E_DEPLOY selects the prototypes (comma-separated; default the
    full kubeflow-core — clusters that can only pull a subset of images,
    e.g. kind with locally built ones, set e.g. `tpujob-operator`).
    """
    import os

    import kubeflow_tpu.manifests  # noqa: F401 — registers prototypes
    from kubeflow_tpu.config.registry import App
    from kubeflow_tpu.manifests.base import to_yaml

    app = App()
    prototypes = os.environ.get("KFT_E2E_DEPLOY", "kubeflow-core")
    for i, proto in enumerate(p.strip() for p in prototypes.split(",")):
        app.add(proto, f"c{i}-{proto}", namespace=namespace)
    objects = app.render()
    _kubectl(["create", "namespace", namespace,
              "--dry-run=client", "-o", "yaml"])  # validates kubectl works
    try:
        _kubectl(["create", "namespace", namespace])
    except RuntimeError:
        pass  # already exists
    _kubectl(["apply", "-n", namespace, "-f", "-"],
             input_text=to_yaml(objects))
    deployments = [o["metadata"]["name"] for o in objects
                   if o["kind"] == "Deployment"]
    for name in deployments:
        _kubectl(["rollout", "status", f"deployment/{name}",
                  "-n", namespace, "--timeout=600s"], timeout=650)


def deploy_crds(namespace: str = "kubeflow-test") -> None:
    """Apply only the CRDs (+ namespace) to the current context.

    The control-plane-only footing for clusters that cannot pull the
    platform images (ephemeral kind, ci/run_e2e_kind.sh): the operator
    then runs as a host process against the cluster, so exactly one
    reconciler owns the CRs."""
    import kubeflow_tpu.manifests  # noqa: F401
    from kubeflow_tpu.config.registry import default_registry
    from kubeflow_tpu.manifests.base import to_yaml

    objs = default_registry.generate("tpujob-operator", "op",
                                     namespace=namespace)
    crds = [o for o in objs if o["kind"] == "CustomResourceDefinition"]
    try:
        _kubectl(["create", "namespace", namespace])
    except RuntimeError:
        pass  # already exists
    _kubectl(["apply", "-f", "-"], input_text=to_yaml(crds))


def tpujob_real(namespace: str = "kubeflow-test") -> None:
    """Submit the tpu-job-simple example to the real cluster and poll the
    CR until the operator reports a terminal phase (the simple_tfjob
    check, workflows.libsonnet:398-411, against a live control plane)."""
    import json
    import os

    import kubeflow_tpu.manifests  # noqa: F401
    from kubeflow_tpu.config.registry import default_registry
    from kubeflow_tpu.manifests.base import to_yaml

    objs = default_registry.generate(
        "tpu-job-simple", "e2e-smoke", namespace=namespace,
        slice_type=os.environ.get("KFT_E2E_SLICE", "v5e-1"))
    _kubectl(["apply", "-n", namespace, "-f", "-"],
             input_text=to_yaml(objs))
    deadline = time.time() + 600
    phase = ""
    while time.time() < deadline:
        out = _kubectl(["get", "tpujobs.kubeflow-tpu.org", "e2e-smoke",
                        "-n", namespace, "-o", "json"])
        phase = json.loads(out).get("status", {}).get("phase", "")
        if phase in ("Succeeded", "Failed"):
            break
        time.sleep(5)
    assert phase == "Succeeded", f"TPUJob ended in phase {phase!r}"


def teardown(namespace: str = "kubeflow-test") -> None:
    """Hermetic backend has nothing persistent; real clusters delete the
    test namespace (the reference's teardown subcommand,
    test_deploy.py:520-626)."""
    try:
        _kubectl(["delete", "namespace", namespace, "--ignore-not-found"],
                 timeout=600)
    except (RuntimeError, FileNotFoundError):
        pass  # no cluster in hermetic runs — nothing to tear down


COMMANDS = {
    "tpujob": tpujob_smoke,
    "serving": serving_smoke,
    "engine": engine_smoke,
    "train": train_smoke,
    "deploy": deploy_real,
    "deploy-crds": deploy_crds,
    "tpujob-real": tpujob_real,
    "teardown": teardown,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubeflow-tpu-e2e")
    ap.add_argument("command", choices=sorted(COMMANDS))
    ap.add_argument("--namespace", default="kubeflow-test")
    ap.add_argument("--artifacts-dir", default="/tmp/artifacts")
    args = ap.parse_args(argv)

    suite = JUnitSuite(args.command)
    suite.run(args.command, lambda: COMMANDS[args.command](args.namespace))
    path = suite.write(args.artifacts_dir)
    print(f"junit: {path}", file=sys.stderr)
    return 0 if suite.ok else 1


if __name__ == "__main__":
    sys.exit(main())
