"""E2E test drivers — heir of testing/test_deploy.py's argparse
subcommands (deploy_model :160-190, deploy_pytorchjob :219-235,
teardown :520-626), each wrapped into JUnit artifacts.

Two backends: against a real cluster these drive kubectl-applied
manifests; hermetically they drive the FakeKube + reconciler, which is
how CI exercises the full TPUJob lifecycle without hardware (the
improvement SURVEY.md §4 calls for over the reference's rented-VM
strategy).
"""

from __future__ import annotations

import argparse
import sys
import time

from kubeflow_tpu.testing.junit import JUnitSuite


def tpujob_smoke(namespace: str = "kubeflow-test") -> None:
    """Submit a tiny TPUJob to the in-process control plane and drive it
    to completion — the simple_tfjob equivalent
    (testing/workflows/components/workflows.libsonnet:398-411)."""
    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler
    from kubeflow_tpu.operator.kube import RUNNING, SUCCEEDED, FakeKube
    from kubeflow_tpu.operator.reconciler import (
        JOB_RUNNING,
        JOB_SUCCEEDED,
        TPUJobController,
    )

    kube = FakeKube()
    controller = TPUJobController(kube, GangScheduler({"v5e-8": 1}))
    job = crd.TPUJobSpec(name="smoke", namespace=namespace,
                         slice_type="v5e-8")
    kube.create_custom(job.to_custom_resource())
    cr = kube.list_custom()[0]
    controller.reconcile_once(cr)
    for pod in kube.list_pods(namespace):
        kube.set_pod_phase(namespace, pod["metadata"]["name"], RUNNING)
    assert controller.reconcile_once(cr) == JOB_RUNNING
    for pod in kube.list_pods(namespace):
        kube.set_pod_phase(namespace, pod["metadata"]["name"], SUCCEEDED)
    assert controller.reconcile_once(cr) == JOB_SUCCEEDED


def serving_smoke(namespace: str = "kubeflow-test") -> None:
    """Export a tiny model, serve it over HTTP, assert a live predict —
    the inception-golden equivalent (testing/test_tf_serving.py)."""
    import json
    import tempfile
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.resnet import ResNet18
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.model_server import ModelServer

    with tempfile.TemporaryDirectory() as tmp:
        model = ResNet18(num_classes=4, num_filters=8)
        variables = model.init(
            jax.random.key(0), np.zeros((1, 32, 32, 3), np.float32),
            train=False)
        export(f"{tmp}/m", 1, variables,
               loader="kubeflow_tpu.serving.loaders:classifier",
               config={"family": "resnet18", "num_classes": 4,
                       "num_filters": 8},
               signature={"inputs": ["image"]})
        server = ModelServer()
        server.add_model("m", f"{tmp}/m")
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            body = json.dumps({"instances": [
                {"image": np.zeros((32, 32, 3), np.float32).tolist()}
            ]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/model/m:predict", data=body)
            with urllib.request.urlopen(req, timeout=60) as resp:
                out = json.loads(resp.read())
            assert len(out["predictions"]) == 1
            scores = out["predictions"][0]["scores"]
            assert abs(sum(scores) - 1.0) < 1e-3
        finally:
            httpd.shutdown()


def engine_smoke(namespace: str = "kubeflow-test") -> None:
    """Admit mixed-length LM requests through the HTTP surface against
    the in-process continuous-batching DecodeEngine: all must complete
    (in-flight admission + slot reuse, 3 requests through 2 slots) and
    the engine must report zero occupancy and an empty queue after.
    Then a shared-prefix burst (concurrent clients, one common system
    prompt) must register prefix-cache hits in
    ``kft_engine_prefix_hits_total`` and keep the max inter-token gap
    of in-flight slots under the chunk-budget bound (no full-prefill
    stall spike).  Then a speculative burst (--speculative_tokens
    rebuild, repetitive prompts the n-gram drafter can predict) must
    register accepted drafts in ``kft_engine_spec_accepted_total``,
    report all three compiled programs over :stats (chunked prefill,
    step, verify — prefix reuse is zero-copy block aliasing, no copy
    program exists), and produce token-IDENTICAL output to a spec-OFF
    control rebuild.  Finally a block-exhaustion burst against a
    deliberately tiny ``kv_pool_blocks`` pool: admission must shed
    typed Overloaded (HTTP 429) while the pool is exhausted,
    retirement must free blocks and restore admission (the queued
    request completes), and the
    ``kft_engine_kv_block_evictions_total`` /
    ``kft_engine_kv_shed_no_blocks_total`` counters must move as
    deltas over /metrics.  Finally a fused-decode burst
    (``--decode_rounds 8`` rebuild): the engine must dispatch fused
    while_loop rounds (``kft_engine_fused_rounds_total`` delta > 0),
    report the ``decode_rounds`` program over :stats, and produce
    token-IDENTICAL output to a ``decode_rounds=1`` control rebuild
    that compiles no fused program."""
    import json
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 16
    cfg = _model_config(overrides)
    model = Transformer(cfg)
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    with tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        server = ModelServer()
        server.add_model("lm", f"{tmp}/lm")
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=16, prefill_chunk_tokens=8,
            kv_block_tokens=4))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        try:
            port = httpd.server_address[1]
            rng = np.random.RandomState(0)
            prompts = [rng.randint(1, 128, size=(n,)).tolist()
                       for n in (3, 9, 16)]
            outs: dict = {}

            def client(i, prompt):
                body = json.dumps(
                    {"instances": [{"tokens": prompt}]}).encode()
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/model/lm:predict",
                    data=body)
                with urllib.request.urlopen(req, timeout=120) as resp:
                    outs[i] = json.loads(resp.read())

            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, prompt in enumerate(prompts):
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            # Occupancy must return to zero once the work drains (the
            # :stats route reads the engine's locked snapshot).
            deadline = time.time() + 30
            while True:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/model/lm:stats",
                        timeout=30) as resp:
                    stats = json.loads(resp.read())["batcher"]
                if (stats["active_slots"] == 0
                        and stats["queue_depth"] == 0
                        and stats["in_flight_requests"] == 0):
                    break
                assert time.time() < deadline, (
                    f"engine never drained: {stats}")
                time.sleep(0.05)
            assert stats["requests"] == len(prompts)

            # --- shared-prefix burst: 4 concurrent clients, one
            # common 8-token system prompt + unique suffixes.  The
            # first admission captures the prefix into the donor pool;
            # later ones resume from it.
            shared = rng.randint(1, 128, size=(8,)).tolist()
            burst = [shared + rng.randint(1, 128, size=(4,)).tolist()
                     for _ in range(4)]
            outs.clear()
            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(burst)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, prompt in enumerate(burst):
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/model/lm:stats",
                    timeout=30) as resp:
                stats = json.loads(resp.read())["batcher"]
            assert stats["prefix_hits"] > 0, (
                f"shared-prefix burst produced no cache hits: {stats}")
            assert stats["cached_token_ratio"] > 0
            # Concurrent admission must not have stalled in-flight
            # decode beyond the chunk budget: the worst observed
            # inter-token gap stays within a (generous, CI-noise-proof)
            # multiple of one scheduling turn — one chunk call plus one
            # step — where an unchunked full-prefill storm would spike
            # it by the whole admission wave's prompt length.
            turn_ms = (stats["token_latency_p95_ms"]
                       + stats["prefill_chunk_p95_ms"])
            bound_ms = 500.0 + 25.0 * max(turn_ms, 1.0)
            assert stats["inter_token_gap_max_ms"] <= bound_ms, (
                f"inter-token gap {stats['inter_token_gap_max_ms']} ms "
                f"exceeded the chunk-budget bound {bound_ms:.0f} ms")
            # The prefix-cache counters are on /metrics for operators.
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                metrics = resp.read().decode()
            from kubeflow_tpu.runtime.prom import (
                parse_metrics,
                sample_value,
            )
            parsed = parse_metrics(metrics)
            hits = sample_value(
                parsed, "kft_engine_prefix_hits_total") or 0
            assert hits > 0, "kft_engine_prefix_hits_total not exported"
            assert sample_value(
                parsed, "kft_serving_cached_token_ratio") is not None

            # --- speculative burst: rebuild the batching plane with
            # speculation on (fresh engine, third AOT program) and
            # drive repetitive prompts — tiled patterns whose greedy
            # continuations collapse into runs the n-gram drafter
            # predicts.  Speculation must ACCEPT drafts (counted in
            # kft_engine_spec_accepted_total) while staying token-
            # identical to a spec-OFF control rebuild.
            def rebuild(spec_tokens, **extra):
                server.enable_batching("lm", batcher_factory(
                    micro_batch_size=0, batch_timeout_s=0.005,
                    lm_engine=True, lm_engine_slots=2,
                    lm_engine_prefill_len=16, prefill_chunk_tokens=8,
                    kv_block_tokens=4,
                    speculative_tokens=spec_tokens, **extra))

            rebuild(4)
            # Pick burst prompts the DRAFTER itself would succeed on,
            # by simulating it host-side against the reference greedy
            # continuations (the same selection bench.py's
            # speculation probe uses): the spec_accepted assert below
            # must hold by construction, independent of the measured-
            # throughput gate's scheduling-sensitive timing on a
            # loaded box.
            from kubeflow_tpu.models.generate import (
                DecodeConfig,
                generate,
            )
            from kubeflow_tpu.serving.engine import _ngram_propose

            cand = [np.asarray(
                (rng.randint(1, 128, size=(4,)).tolist() * 3)[:12],
                np.int32) for _ in range(8)]
            refs = np.asarray(generate(
                cfg, variables["params"], np.stack(cand),
                DecodeConfig(max_new_tokens=max_new,
                             temperature=0.0))[0])

            def sim_accepts(prompt, cont):
                hist = list(prompt) + [cont[0]]
                accepted, i = 0, 1
                while i < len(cont):
                    room = len(cont) - i - 1
                    prop = (_ngram_propose(
                        np.asarray(hist, np.int32), min(4, room))
                        if room > 0 else np.empty((0,), np.int32))
                    a = 0
                    for j, p in enumerate(prop.tolist()):
                        if p == cont[i + j]:
                            a += 1
                        else:
                            break
                    accepted += a
                    hist.extend(cont[i:i + a + 1])
                    i += a + 1
                return accepted

            scores = [sim_accepts(cand[i].tolist(),
                                  refs[i, 12:].tolist())
                      for i in range(len(cand))]
            ranked = sorted(range(len(cand)),
                            key=lambda i: scores[i], reverse=True)
            assert scores[ranked[0]] > 0, (
                "no candidate prompt is draftable under the n-gram "
                "drafter; widen the candidate pool")
            spec_prompts = [cand[i].tolist() for i in ranked[:4]]
            outs.clear()
            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(spec_prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            spec_out = {}
            for i, prompt in enumerate(spec_prompts):
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
                spec_out[i] = tokens
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/model/lm:stats",
                    timeout=30) as resp:
                stats = json.loads(resp.read())["batcher"]
            assert stats["spec_drafted"] > 0, (
                f"speculative burst proposed no drafts: {stats}")
            assert stats["spec_accepted"] > 0, (
                f"speculative burst accepted no drafts: {stats}")
            assert 0 < stats["spec_acceptance_rate"] <= 1
            # The three-program guarantee, end to end over :stats —
            # verify exists exactly once; a purely-drafted burst may
            # never need the plain step program, so it is 0 or 1.
            # There is no copy_prefix key: prefix reuse is host-side
            # block-table aliasing, not a device program.
            programs = stats["compiled_programs"]
            assert set(programs) == {"chunked_prefill", "step",
                                     "verify"}, programs
            assert programs["verify"] == 1, programs
            assert programs["chunked_prefill"] == 1, programs
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                parsed = parse_metrics(resp.read().decode())
            # Pinned to THIS server's engine label: the prom registry
            # is process-global, and an unpinned read falls back to
            # the first series of each family — which other suites'
            # engines may own (and own DIFFERENTLY per family).
            accepted = sample_value(
                parsed, "kft_engine_spec_accepted_total",
                engine="lm-v1") or 0
            drafted = sample_value(
                parsed, "kft_engine_spec_drafted_total",
                engine="lm-v1") or 0
            assert accepted > 0, (
                "kft_engine_spec_accepted_total not exported/zero")
            assert drafted >= accepted
            # Spec-OFF control: identical tokens on a fresh engine.
            rebuild(0)
            outs.clear()
            for i, prompt in enumerate(spec_prompts):
                client(i, prompt)
                assert outs[i]["predictions"][0]["tokens"] \
                    == spec_out[i], (
                    f"speculation changed tokens for prompt {i}")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/model/lm:stats",
                    timeout=30) as resp:
                stats = json.loads(resp.read())["batcher"]
            assert stats["spec_drafted"] == 0
            assert stats["compiled_programs"]["verify"] == 0
            assert set(stats["compiled_programs"]) \
                == {"chunked_prefill", "step", "verify"}

            # --- block-exhaustion burst: a deliberately tiny pool (8
            # pages of 4 tokens against 12-token prompts + 16-token
            # budgets = 7 reserved pages per request, so exactly ONE
            # request fits) and a queue cap of 1.  8 simultaneous
            # clients: one admits, one queues, the rest MUST shed 429
            # Overloaded while the pool is exhausted — and every
            # accepted request must still complete, because
            # retirement frees its pages and re-opens admission for
            # the queued one (tokens-resident admission never
            # deadlocks a mid-flight slot).
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                parsed = parse_metrics(resp.read().decode())
            shed_before = sample_value(
                parsed, "kft_engine_kv_shed_no_blocks_total",
                engine="lm-v1") or 0
            evict_before = sample_value(
                parsed, "kft_engine_kv_block_evictions_total",
                engine="lm-v1") or 0
            rebuild(0, kv_pool_blocks=8, max_queue_depth=1)
            burst = [rng.randint(1, 128, size=(12,)).tolist()
                     for _ in range(8)]
            outs.clear()
            codes: dict = {}

            def burst_client(i, prompt):
                try:
                    client(i, prompt)
                    codes[i] = 200
                except urllib.error.HTTPError as err:
                    codes[i] = err.code
                    err.read()

            threads = [threading.Thread(target=burst_client,
                                        args=(i, p))
                       for i, p in enumerate(burst)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            ok = [i for i, c in codes.items() if c == 200]
            shed = [i for i, c in codes.items() if c == 429]
            assert codes and set(codes.values()) <= {200, 429}, codes
            assert ok, f"exhaustion burst completed nothing: {codes}"
            assert shed, (
                f"pool exhaustion shed nothing (want 429s): {codes}")
            for i in ok:
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(burst[i])] == burst[i]
                assert len(tokens) == len(burst[i]) + max_new
            # Admission restored after the burst drains: a fresh
            # request must be served, not shed.
            client("post", burst[0])
            assert len(outs["post"]["predictions"][0]["tokens"]) \
                == len(burst[0]) + max_new
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/model/lm:stats",
                    timeout=30) as resp:
                stats = json.loads(resp.read())["batcher"]
            # Every 429 is a typed shed; the pool-typed counter is
            # racy by design (a thread scheduled after the first
            # request retires can shed queue-full while the freed
            # pages sit unclaimed), so assert it MOVED rather than
            # that it covers every shed.
            assert stats["shed"] >= len(shed), stats
            assert stats["kv_shed_no_blocks"] >= 1, stats
            assert stats["kv_blocks"] == 8
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                parsed = parse_metrics(resp.read().decode())
            shed_after = sample_value(
                parsed, "kft_engine_kv_shed_no_blocks_total",
                engine="lm-v1") or 0
            evict_after = sample_value(
                parsed, "kft_engine_kv_block_evictions_total",
                engine="lm-v1") or 0
            # The pool gauges are live: capacity == the rebuilt
            # engine's 8 pages, and the published prefix pages of the
            # drained burst are still resident (scrape-visible — the
            # loop refreshes the used gauge, not just close()).
            assert sample_value(parsed, "kft_engine_kv_blocks",
                                engine="lm-v1") == 8
            assert (sample_value(parsed, "kft_engine_kv_blocks_used",
                                 engine="lm-v1") or 0) > 0
            assert shed_after - shed_before >= 1, (
                shed_before, shed_after, codes)
            # Successive distinct prompts through an 8-page pool force
            # LRU eviction of published prefix pages — the eviction
            # counter must move.
            assert evict_after > evict_before, (
                evict_before, evict_after)

            # --- fused-decode burst: rebuild with decode_rounds=8 —
            # the fused while_loop program replaces the per-step
            # dispatch loop (docs §5.2e) — and drive mixed-length
            # concurrent prompts.  The engine must dispatch fused
            # rounds (kft_engine_fused_rounds_total delta > 0), report
            # the fused program in compiled_programs, and produce
            # token-IDENTICAL output to a decode_rounds=1 control
            # rebuild (the k=1 path compiles no fused program).
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                parsed = parse_metrics(resp.read().decode())
            fused_before = sample_value(
                parsed, "kft_engine_fused_rounds_total",
                engine="lm-v1") or 0
            rebuild(0, decode_rounds=8)
            fused_prompts = [rng.randint(1, 128, size=(n,)).tolist()
                             for n in (3, 9, 16)]
            outs.clear()
            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(fused_prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            fused_out = {}
            for i, prompt in enumerate(fused_prompts):
                tokens = outs[i]["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
                fused_out[i] = tokens
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/model/lm:stats",
                    timeout=30) as resp:
                stats = json.loads(resp.read())["batcher"]
            assert stats["decode_rounds"] == 8, stats
            assert stats["fused_rounds"] > 0, (
                f"fused burst dispatched no fused rounds: {stats}")
            assert stats["steps_per_round_p50"] >= 1, stats
            programs = stats["compiled_programs"]
            # The fused program joins the guarantee exactly once; the
            # per-step program is never needed on this path (0), and
            # verify stays 0 (spec off).
            assert programs.get("decode_rounds") == 1, programs
            assert programs["chunked_prefill"] == 1, programs
            assert programs["verify"] == 0, programs
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=30) as resp:
                parsed = parse_metrics(resp.read().decode())
            fused_after = sample_value(
                parsed, "kft_engine_fused_rounds_total",
                engine="lm-v1") or 0
            assert fused_after - fused_before > 0, (
                fused_before, fused_after)
            # k=1 control rebuild: identical tokens, no fused program.
            # Same concurrent shape as the fused burst — greedy decode
            # is order-independent per slot, and the threads halve the
            # control's wall time.
            rebuild(0)
            outs.clear()
            threads = [threading.Thread(target=client, args=(i, p))
                       for i, p in enumerate(fused_prompts)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            for i, prompt in enumerate(fused_prompts):
                assert outs[i]["predictions"][0]["tokens"] \
                    == fused_out[i], (
                    f"fused decode changed tokens for prompt {i}")
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/model/lm:stats",
                    timeout=30) as resp:
                stats = json.loads(resp.read())["batcher"]
            assert stats["fused_rounds"] == 0, stats
            assert "decode_rounds" not in stats["compiled_programs"], \
                stats["compiled_programs"]
        finally:
            httpd.shutdown()
            server.stop()


def fault_injection_smoke(namespace: str = "kubeflow-test") -> None:
    """Seeded chaos scenario against the whole serving fault layer,
    driven by the KFT_FAULTS harness (kubeflow_tpu/testing/faults.py):

      1. overload shed — slots full + queue full => HTTP 429 with a
         Retry-After header, while accepted requests still complete;
      2. deadline expiry MID-GENERATION (slow steps injected) => HTTP
         504, and the freed slot serves a follow-up request;
      3. loader circuit-break — a corrupt model version trips the
         reload breaker (no loader hot-loop) while the last-good
         version keeps serving; a fixed version recovers;
      4. graceful drain — /readyz flips 503 with a request in flight,
         /healthz stays 200, and the accepted request completes;
      5. every shed/expired/reload-failure is visible in kft_* metrics.

    Override the scenario by exporting KFT_FAULTS (same grammar).
    """
    import json
    import os
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory, wait_for_drain
    from kubeflow_tpu.serving.model_server import ModelServer
    from kubeflow_tpu.testing import faults

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 16
    scenario = os.environ.get(faults.ENV) or \
        "seed=20260803;engine.step:sleep=0.03"
    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))

    def predict_req(port, body):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/lm:predict",
            data=json.dumps(body).encode())
        try:
            with urllib.request.urlopen(req, timeout=180) as resp:
                return resp.status, dict(resp.headers), \
                    json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, dict(e.headers), json.loads(e.read())

    def engine_stats(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/model/lm:stats",
                timeout=30) as resp:
            return json.loads(resp.read())["batcher"]

    prompt = list(range(1, 9))
    body_full = {"instances": [{"tokens": prompt}]}
    with faults.injected(scenario) as inj, \
            tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        server = ModelServer(reload_backoff_s=0.5)
        server.add_model("lm", f"{tmp}/lm")
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=1,
            lm_engine_prefill_len=16, max_queue_depth=1))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        port = httpd.server_address[1]
        try:
            # -- 1. overload shed ---------------------------------------
            results: dict = {}

            def client(i, body):
                results[i] = predict_req(port, body)

            t0 = threading.Thread(target=client, args=(0, body_full))
            t0.start()
            deadline = time.time() + 120
            while engine_stats(port)["in_flight_requests"] < 1:
                assert time.time() < deadline, "first request never ran"
                time.sleep(0.01)
            # Slot busy (slow steps injected): 4 more arrivals — the
            # single queue seat takes one, the rest shed as 429.
            burst = [threading.Thread(target=client, args=(i, body_full))
                     for i in range(1, 5)]
            for t in burst:
                t.start()
            for t in [t0] + burst:
                t.join(timeout=180)
            codes = sorted(results[i][0] for i in range(5))
            assert codes.count(429) >= 1, codes
            assert codes.count(200) >= 2, codes  # slot + queue seat
            shed_headers = [results[i][1] for i in range(5)
                            if results[i][0] == 429]
            assert all(h.get("Retry-After") for h in shed_headers), (
                "429 responses must carry Retry-After")
            ok = [results[i][2] for i in range(5)
                  if results[i][0] == 200]
            for out in ok:
                tokens = out["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            # -- 2. deadline expiry mid-generation ----------------------
            code, _, payload = predict_req(
                port, {**body_full, "deadline_ms": 120})
            assert code == 504, (code, payload)
            assert "deadline" in payload["error"].lower()
            # The expired request's slot is reclaimed: a follow-up
            # full-budget request completes on the same single slot.
            code, _, payload = predict_req(port, body_full)
            assert code == 200, (code, payload)
            stats = engine_stats(port)
            assert stats["deadline_expired"] >= 1, stats
            assert stats["shed"] >= 1, stats
            # -- 3. loader circuit-break --------------------------------
            os.makedirs(f"{tmp}/lm/2")
            with open(f"{tmp}/lm/2/model.json", "w") as f:
                f.write("{corrupt json")
            raised = False
            try:
                server.reload("lm")
            except Exception:
                raised = True
            assert raised, "corrupt version must raise"
            attempts = inj.fired("loader.load")
            # Breaker open: repeated polls (the watcher loop) skip the
            # loader entirely — no hot-loop on the corrupt artifact.
            for _ in range(5):
                assert server.reload("lm") is False
            assert inj.fired("loader.load") == attempts
            # Last-good version keeps serving through the open breaker.
            code, _, _ = predict_req(port, body_full)
            assert code == 200
            assert server.get("lm").version == 1
            # Half-open after backoff (policy clock skipped forward):
            # the trial load runs, still corrupt, breaker re-opens.
            inj.advance_clock(30)
            raised = False
            try:
                server.reload("lm")
            except Exception:
                raised = True
            assert raised, "still-corrupt version must raise"
            assert inj.fired("loader.load") == attempts + 1
            # A NEW good version resets the breaker and loads at once.
            export(f"{tmp}/lm", 3, variables,
                   loader="kubeflow_tpu.serving.loaders:lm_generate",
                   config={"model": overrides,
                           "max_new_tokens": max_new,
                           "temperature": 0.0})
            assert server.reload("lm") is True
            assert server.get("lm").version == 3
            code, _, _ = predict_req(port, body_full)
            assert code == 200
            # -- 4. graceful drain --------------------------------------
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=30) as r:
                assert r.status == 200
            holder: dict = {}
            t = threading.Thread(
                target=lambda: holder.update(
                    {"resp": predict_req(port, body_full)}))
            t.start()
            deadline = time.time() + 120
            while server.inflight() < 1:
                assert time.time() < deadline, "drain request never ran"
                time.sleep(0.01)
            server.begin_drain()
            try:
                urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/readyz", timeout=30)
                raise AssertionError("/readyz must be 503 while draining")
            except urllib.error.HTTPError as e:
                assert e.code == 503
                assert json.loads(e.read())["status"] == "draining"
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz", timeout=30) as r:
                assert r.status == 200  # alive, just not ready
            t.join(timeout=180)
            assert holder["resp"][0] == 200, (
                "request accepted before drain was lost")
            assert wait_for_drain(server, deadline_s=30)
            # -- 5. shed/expired/breaker visible in kft_* metrics -------
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/metrics", timeout=30) as r:
                metrics = r.read().decode()
            for needle in ('kft_serving_shed_total{batcher="lm-v1"}',
                           'kft_serving_deadline_expired_total'
                           '{batcher="lm-v1"}',
                           'kft_serving_reload_failures_total'
                           '{model="lm"}'):
                line = [ln for ln in metrics.splitlines()
                        if ln.startswith(needle)]
                assert line and float(line[0].rsplit(" ", 1)[1]) >= 1, (
                    f"expected a nonzero {needle} series")
        finally:
            httpd.shutdown()
            server.stop()


def fleet_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic fleet control-plane scenario: a load-aware router in
    front of THREE in-process serving replicas (each a real ModelServer
    + DecodeEngine + HTTP listener), discovered as label-selected pods
    through testing/fake_apiserver.py over real sockets.

      1. discovery + routing — kube-discovered endpoints, concurrent
         mixed traffic through the router, spread across replicas;
      2. scale-out under open-loop load — the autoscaler reads scraped
         kft_serving_* load off the registry and patches the serving
         Deployment's replicas through the SAME fake apiserver;
      3. replica kill mid-generation -> ejection within one probe
         interval; every request issued after the kill is retried onto
         survivors (failed-before-send policy) and succeeds; clock-
         skewed backoff expiry + restart -> half-open probe recovery;
      4. drain-aware rolling restart under continuous traffic — the
         draining replica gets no NEW work, finishes its in-flight,
         restarts, and ZERO accepted requests are lost end to end;
      5. distributed tracing end to end — a request proxied through
         the router yields ONE trace whose span tree walks
         router.request -> router.forward -> server.predict ->
         engine.admission -> engine.prefill_chunk -> engine.decode
         with a consistent trace_id (W3C traceparent propagation),
         retrievable from /debug/traces on the router AND the
         replica; with the healthy-sample rate at ZERO, a
         deadline-expired request is still always retained (tail
         sampling) while ok traffic is not;
      6. router/autoscaler/trace outcomes visible in kft_router_* /
         kft_autoscaler_* / kft_trace_* metrics.

    All replicas share one process (and thus one prom registry and one
    fault injector): per-endpoint /metrics scrapes stay correct because
    each replica's scrape refreshes its own server's gauges at render
    time.  Override the chaos scenario via KFT_FAULTS (the default
    slows engine steps so in-flight load is observable).
    """
    import json
    import os
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.fleet.autoscaler import Autoscaler
    from kubeflow_tpu.fleet.endpoints import (
        EndpointRegistry,
        KubeEndpoints,
    )
    from kubeflow_tpu.fleet.router import FleetRouter, make_router_server
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.operator.kube_http import HttpKube
    from kubeflow_tpu.runtime import tracing
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory, wait_for_drain
    from kubeflow_tpu.serving.model_server import ModelServer
    from kubeflow_tpu.testing import faults
    from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 8
    scenario = os.environ.get(faults.ENV) or \
        "seed=20260803;engine.step:sleep=0.02"
    prompt = list(range(1, 9))

    def make_replica(base, port=0):
        server = ModelServer()
        server.add_model("lm", base)
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=16, max_queue_depth=8))
        httpd, _ = make_http_server(server, port=port,
                                    host="127.0.0.1")
        return server, httpd

    def predict_via(port, body, timeout=180):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/lm:predict",
            data=json.dumps(body).encode())
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def get_traces(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/debug/traces",
                timeout=30) as resp:
            return json.loads(resp.read())

    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    replicas = []
    apiserver = router_httpd = None
    registry = None
    # Tracing ON for the whole scenario: every hop below stamps spans
    # into one shared in-process store (router + replicas share the
    # process here, which is exactly what makes the cross-"process"
    # trace_id consistency assertable end to end).
    tracing.enable(sample_rate=1.0, capacity=256)
    with faults.injected(scenario) as inj, \
            tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        try:
            # -- fleet assembly -------------------------------------------
            replicas = [list(make_replica(f"{tmp}/lm"))
                        for _ in range(3)]
            apiserver, _, store = make_fake_apiserver()
            api_port = apiserver.server_address[1]
            kube = HttpKube(base_url=f"http://127.0.0.1:{api_port}")
            store.create_deployment({
                "metadata": {"namespace": namespace,
                             "name": "tpu-serving"},
                "spec": {"replicas": 1}})
            for i, (_, httpd) in enumerate(replicas):
                store.create_pod({
                    "metadata": {"namespace": namespace,
                                 "name": f"srv-{i}",
                                 "labels": {"app": "tpu-serving"}},
                    "spec": {"containers": [{"ports": [{
                        "name": "http",
                        "containerPort": httpd.server_address[1]}]}]},
                    "status": {"podIP": "127.0.0.1"}})
                store.set_pod_phase(namespace, f"srv-{i}", "Running")
            registry = EndpointRegistry(
                KubeEndpoints(kube, namespace, {"app": "tpu-serving"}),
                probe_interval_s=0.2, eject_threshold=1,
                eject_backoff_s=2.0)
            registry.refresh()
            assert len(registry.routable()) == 3, registry.describe()
            router = FleetRouter(registry, max_tries=3,
                                 try_timeout_s=180.0)
            router_httpd, _ = make_router_server(router, port=0,
                                                 host="127.0.0.1")
            rport = router_httpd.server_address[1]
            body_full = {"instances": [{"tokens": prompt}]}

            # -- 1. routed traffic spreads and completes ------------------
            results: dict = {}

            def client(i, body=body_full):
                results[i] = predict_via(rport, body)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(9)]
            for t in threads:
                t.start()
            # -- 2. scale-out under the open-loop burst -------------------
            autoscaler = Autoscaler(
                kube, namespace, "tpu-serving", registry,
                target_inflight_per_replica=1.0, tolerance=0.1,
                min_replicas=1, max_replicas=3,
                scale_up_cooldown_s=0.0, scale_down_cooldown_s=3600.0)
            deadline = time.time() + 120
            scaled = None
            while time.time() < deadline:
                registry.refresh()
                if registry.total_load() >= 2:
                    scaled = autoscaler.reconcile_once()
                    if scaled["applied"]:
                        break
                time.sleep(0.02)
            assert scaled is not None and scaled["applied"], (
                "autoscaler never saw the burst's load")
            dep = kube.get_deployment(namespace, "tpu-serving")
            assert dep["spec"]["replicas"] >= 2, dep
            for t in threads:
                t.join(timeout=180)
            assert sorted(r[0] for r in results.values()) \
                == [200] * 9, results
            for code, payload in results.values():
                tokens = payload["predictions"][0]["tokens"]
                assert tokens[:len(prompt)] == prompt
                assert len(tokens) == len(prompt) + max_new
            served_by = [i for i, (srv, _) in enumerate(replicas)
                         if (srv.batcher_stats("lm") or {}).get(
                             "requests", 0) > 0]
            assert len(served_by) >= 2, (
                f"load not spread: replicas {served_by} served")

            # -- 5a. trace propagation: router hop -> decode step ---------
            # One routed request must yield ONE trace whose span tree
            # carries the whole path with a consistent trace_id: the
            # router injected its forward span's traceparent, the
            # replica's server span continued it, and the engine
            # stamped admission/prefill/decode children at drain time.
            snap = get_traces(rport)
            assert snap["enabled"], snap
            full = None
            for trace in snap["traces"]:
                names = {s["name"] for s in trace["spans"]}
                if {"router.request", "router.forward",
                        "server.predict", "engine.admission",
                        "engine.prefill_chunk",
                        "engine.decode"} <= names:
                    full = trace
                    break
            assert full is not None, (
                f"no trace with the full router->engine span chain in "
                f"{[sorted({s['name'] for s in t['spans']}) for t in snap['traces']]}")
            tid = full["trace_id"]
            assert all(s["trace_id"] == tid for s in full["spans"])
            by_name = {}
            for s in full["spans"]:
                by_name.setdefault(s["name"], s)
            # Parent chain: server span under the forward span, which
            # is under the router root (the W3C header did its job).
            root = by_name["router.request"]
            assert root["parent_id"] is None
            assert by_name["router.forward"]["parent_id"] \
                == root["span_id"]
            assert by_name["server.predict"]["parent_id"] \
                == by_name["router.forward"]["span_id"]
            assert by_name["engine.decode"]["attrs"]["tokens"] \
                == max_new
            # The router root span's id is retrievable from a REPLICA's
            # /debug/traces too (shared store in the hermetic fleet):
            # the trace one port shows is the trace every port shows.
            replica_port = replicas[0][1].server_address[1]
            replica_snap = get_traces(replica_port)
            assert any(t["trace_id"] == tid
                       for t in replica_snap["traces"]), (
                f"trace {tid} not visible on replica "
                f"{replica_port}")

            # -- 3. kill mid-generation -> eject -> recover ---------------
            victim_srv, victim_httpd = replicas[0]
            victim_port = victim_httpd.server_address[1]
            holder: dict = {}
            t = threading.Thread(target=lambda: holder.update(
                {"resp": predict_via(victim_port, body_full,
                                     timeout=30)}))
            t.start()
            deadline = time.time() + 60
            while victim_srv.inflight() < 1:
                assert time.time() < deadline, \
                    "victim request never started"
                time.sleep(0.01)
            victim_httpd.shutdown()   # the kill, mid-generation
            victim_httpd.server_close()
            t.join(timeout=60)
            # One probe interval: a single refresh ejects it
            # (eject_threshold=1).
            registry.refresh()
            states = {s.name: s for s in registry.all()}
            assert states["srv-0"].breaker.open, registry.describe()
            assert len(registry.routable()) == 2
            # Everything issued AFTER the kill lands on survivors.
            results.clear()
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert sorted(r[0] for r in results.values()) \
                == [200] * 6, results
            # Recovery: backoff expires on the skewed policy clock, the
            # replica returns on the SAME port (warm engine), and the
            # half-open probe readmits it.
            new_httpd = make_http_server(
                victim_srv, port=victim_port, host="127.0.0.1")[0]
            replicas[0][1] = new_httpd
            inj.advance_clock(10)
            registry.refresh()
            assert states["srv-0"].routable(), registry.describe()

            # -- 4. drain-aware rolling restart, zero loss ----------------
            stop_traffic = threading.Event()
            outcomes: list = []

            def traffic():
                while not stop_traffic.is_set():
                    outcomes.append(predict_via(rport, body_full)[0])

            traffic_threads = [threading.Thread(target=traffic)
                               for _ in range(3)]
            for t in traffic_threads:
                t.start()
            try:
                roll_srv, roll_httpd = replicas[1]
                roll_port = roll_httpd.server_address[1]
                roll_srv.begin_drain()
                registry.refresh()
                states = {s.name: s for s in registry.all()}
                assert not states["srv-1"].routable()
                assert states["srv-1"].state_label() == "draining"
                assert wait_for_drain(roll_srv, deadline_s=120), \
                    "draining replica never quiesced"
                roll_httpd.shutdown()
                roll_httpd.server_close()
                roll_srv.stop()
                # Restarted process: fresh ModelServer, same address.
                new_srv, new_httpd = make_replica(f"{tmp}/lm",
                                                  port=roll_port)
                replicas[1] = [new_srv, new_httpd]
                registry.refresh()
                states = {s.name: s for s in registry.all()}
                deadline = time.time() + 60
                while not states["srv-1"].routable():
                    assert time.time() < deadline, registry.describe()
                    time.sleep(0.05)
                    registry.refresh()
            finally:
                stop_traffic.set()
                for t in traffic_threads:
                    t.join(timeout=180)
            assert outcomes, "traffic generator produced nothing"
            bad = [c for c in outcomes if c != 200]
            assert not bad, (
                f"rolling restart lost {len(bad)}/{len(outcomes)} "
                f"accepted requests: {bad[:5]}")

            # -- 5b. tail sampling: errored requests ALWAYS retained ------
            # Fresh store with the healthy-sample rate at ZERO: ok
            # traffic keeps nothing, a deadline-expired request is
            # still captured (the always-keep tier).
            tracing.enable(sample_rate=0.0, capacity=64)
            assert predict_via(rport, body_full)[0] == 200
            code, payload = predict_via(
                rport, {**body_full, "deadline_ms": 0.001})
            assert code == 504, (code, payload)
            snap = get_traces(rport)
            statuses = [t["status"] for t in snap["traces"]]
            assert "deadline_exceeded" in statuses, snap["traces"]
            kept = [t for t in snap["traces"]
                    if t["status"] == "deadline_exceeded"]
            assert all(t["retained"] == "error" for t in kept)
            assert not any(t["status"] == "ok"
                           for t in snap["traces"]), (
                f"ok traffic retained at sample rate 0: {statuses}")

            # -- 6. control-plane outcomes in kft_* metrics ---------------
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rport}/metrics",
                    timeout=30) as resp:
                metrics = resp.read().decode()
            from kubeflow_tpu.runtime.prom import (
                parse_metrics,
                sample_value,
            )

            parsed = parse_metrics(metrics)
            assert (sample_value(parsed, "kft_router_ejections_total",
                                 endpoint="srv-0") or 0) >= 1
            ok = sum(v for labels, v in
                     parsed.get("kft_router_requests_total", ())
                     if labels.get("outcome") == "ok")
            assert ok >= 15, parsed.get("kft_router_requests_total")
            assert (sample_value(
                parsed, "kft_autoscaler_desired_replicas") or 0) >= 2
            assert sample_value(parsed, "kft_router_endpoints",
                                state="routable") == 3, parsed.get(
                                    "kft_router_endpoints")
            # Trace-store health on the same scrape: spans recorded,
            # the errored trace retained, occupancy visible.
            assert (sample_value(parsed, "kft_trace_spans_total")
                    or 0) > 0
            assert (sample_value(parsed, "kft_trace_retained_total",
                                 reason="error") or 0) >= 1
            assert sample_value(
                parsed, "kft_trace_store_traces") is not None
        finally:
            tracing.disable()
            if router_httpd is not None:
                router_httpd.shutdown()
            if apiserver is not None:
                apiserver.shutdown()
                apiserver.server_close()
            for srv, httpd in replicas:
                try:
                    httpd.shutdown()
                    httpd.server_close()
                except Exception:
                    pass
                srv.stop()


def survivable_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic survivable-inference scenario: a router in front of
    THREE engine replicas under a seeded chaos schedule that kills a
    replica MID-GENERATION and restarts it mid-burst.

      1. control — an uninterrupted streaming :generate run records
         the greedy token sequence (all replicas share one export, so
         greedy is replica-independent);
      2. chaos burst — concurrent streaming clients through the
         router while a deterministic kill schedule fires: the moment
         a client has received its 3rd token, the replica serving it
         is killed (its live sockets severed — the in-process
         equivalent of SIGKILL's socket signature).  EVERY accepted
         greedy request must complete with a token stream
         BIT-IDENTICAL to the control — zero duplicated, missing, or
         reordered tokens, zero 502s — because the router replays
         prompt + delivered tokens as a resume payload on a survivor
         and splices the streams (the engine admits the resume as one
         chunked prefill);
      3. the dead replica is force-ejected immediately (no probe-
         interval wait), then RESTARTED on the same port and readmits
         via the half-open probe on the skewed policy clock, serving
         post-restart traffic;
      4. dedup — a double-submitted :predict with one idempotency key
         executes ONCE and both submissions get the identical
         payload;
      5. kft_router_replays_total{outcome="ok"} > 0,
         kft_router_resume_tokens observations, and
         kft_serving_dedup_hits_total > 0 asserted as /metrics
         deltas, plus the router.replay / engine.resume hook-site
         encounters on the installed injector.
    """
    import json
    import os
    import socket
    import tempfile
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    import jax
    import numpy as np

    from kubeflow_tpu.fleet.endpoints import (
        Endpoint,
        EndpointRegistry,
        StaticEndpoints,
    )
    from kubeflow_tpu.fleet.router import FleetRouter, make_router_server
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.runtime.prom import parse_metrics, sample_value
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer
    from kubeflow_tpu.testing import faults

    class KillableServer(ThreadingHTTPServer):
        """ThreadingHTTPServer that can sever its LIVE connections:
        shutdown() only stops accepting, while a crashed process also
        resets every established socket — kill() reproduces that
        signature so a mid-generation stream actually breaks."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._live = set()
            self._live_lock = threading.Lock()

        def process_request(self, request, client_address):
            with self._live_lock:
                self._live.add(request)
            super().process_request(request, client_address)

        def shutdown_request(self, request):
            with self._live_lock:
                self._live.discard(request)
            super().shutdown_request(request)

        def handle_error(self, request, client_address):
            # The severed handler threads die on BrokenPipe by
            # design; their tracebacks are not scenario output.
            pass

        def kill(self):
            # Sever FIRST: shutdown() blocks up to serve_forever's
            # 0.5 s poll, and a kill that waits that long lands after
            # a short generation already finished.
            with self._live_lock:
                live = list(self._live)
                self._live.clear()
            for sock in live:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                sock.close()
            self.shutdown()
            self.server_close()

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 12
    prompt = list(range(1, 9))
    # Seeded schedule: the step sleep paces generation so the 3rd-token
    # kill trigger always lands mid-generation, deterministically.
    scenario = os.environ.get(faults.ENV) or \
        "seed=20260804;engine.step:sleep=0.02"

    def make_replica(base, port=0):
        server = ModelServer()
        server.add_model("lm", base)
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=32, max_queue_depth=16))
        httpd, _ = make_http_server(server, port=port, host="127.0.0.1",
                                    server_cls=KillableServer)
        return server, httpd

    def stream_via(port, body, on_tokens=None, timeout=180):
        """POST :generate, read the NDJSON stream; returns
        (meta, tokens, terminal_msg)."""
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        conn.request("POST", "/model/lm:generate",
                     json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200, (resp.status, resp.read())
        meta = terminal = None
        tokens = []
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            if "meta" in msg:
                meta = msg["meta"]
            elif "tokens" in msg:
                tokens.extend(msg["tokens"])
                if on_tokens is not None:
                    on_tokens(tokens)
            if "done" in msg or "error" in msg:
                terminal = msg
                break
        conn.close()
        return meta, tokens, terminal

    def predict_via(port, body, headers=None, timeout=180):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/lm:predict",
            data=json.dumps(body).encode(),
            headers=headers or {})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()

    def scrape(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            return parse_metrics(resp.read().decode())

    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    replicas = []
    router_httpd = None
    with faults.injected(scenario) as inj, \
            tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        try:
            replicas = [list(make_replica(f"{tmp}/lm"))
                        for _ in range(3)]
            eps = [Endpoint(name=f"srv-{i}",
                            url=f"http://127.0.0.1:"
                                f"{h.server_address[1]}")
                   for i, (_, h) in enumerate(replicas)]
            registry = EndpointRegistry(
                StaticEndpoints(eps), probe_interval_s=0.2,
                eject_threshold=3, eject_backoff_s=2.0)
            registry.refresh()
            assert len(registry.routable()) == 3, registry.describe()
            router = FleetRouter(registry, max_tries=3, max_replays=2,
                                 try_timeout_s=180.0)
            router_httpd, _ = make_router_server(router, port=0,
                                                 host="127.0.0.1")
            rport = router_httpd.server_address[1]
            body = {"tokens": prompt, "max_new_tokens": max_new}

            # -- 1. uninterrupted control run -------------------------
            meta, control, terminal = stream_via(
                replicas[0][1].server_address[1], body)
            assert meta["resumable"] is True, meta
            assert terminal.get("done") and len(control) == max_new, \
                (control, terminal)

            before = scrape(rport)

            # -- 2. chaos burst: kill the serving replica at token 3 --
            killed: dict = {}
            kill_lock = threading.Lock()

            def maybe_kill(tokens):
                if len(tokens) < 3:
                    return
                with kill_lock:
                    if killed:
                        return
                    for i, (srv, httpd) in enumerate(replicas):
                        stats = srv.batcher_stats("lm") or {}
                        if stats.get("in_flight_requests", 0) > 0:
                            killed["index"] = i
                            killed["port"] = httpd.server_address[1]
                            httpd.kill()
                            return

            results: dict = {}

            def client(i, on_tokens=None):
                results[i] = stream_via(rport, body,
                                        on_tokens=on_tokens)

            threads = [threading.Thread(
                target=client, args=(i, maybe_kill if i == 0 else None))
                for i in range(5)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert killed, "the kill schedule never fired"
            for i, (meta, tokens, terminal) in results.items():
                assert terminal is not None and terminal.get("done"), (
                    f"client {i} stream did not complete: {terminal}")
                assert tokens == control, (
                    f"client {i} stream drifted from the uninterrupted "
                    f"control: {tokens} != {control}")
            assert inj.fired("router.replay") >= 1
            assert inj.fired("engine.resume") >= 1

            # -- 3. immediate ejection, then restart + readmission ----
            victim = {s.name: s for s in registry.all()}[
                f"srv-{killed['index']}"]
            assert victim.breaker.open, registry.describe()
            assert victim.breaker.state() in ("open", "half_open")
            srv = replicas[killed["index"]][0]
            new_httpd = make_http_server(
                srv, port=killed["port"], host="127.0.0.1",
                server_cls=KillableServer)[0]
            replicas[killed["index"]][1] = new_httpd
            inj.advance_clock(30)
            registry.refresh()
            assert victim.routable(), registry.describe()
            _, tokens, terminal = stream_via(rport, body)
            assert terminal.get("done") and tokens == control

            # -- 4. dedup: double submit executes once ----------------
            target_srv, target_httpd = replicas[(killed["index"] + 1)
                                                % 3]
            tport = target_httpd.server_address[1]
            stats0 = target_srv.batcher_stats("lm") or {}
            pbody = {"instances": [{"tokens": prompt}]}
            hdrs = {"x-kft-idempotency-key": "survivable-e2e-1"}
            s1, payload1 = predict_via(tport, pbody, hdrs)
            s2, payload2 = predict_via(tport, pbody, hdrs)
            assert (s1, s2) == (200, 200)
            assert payload1 == payload2, "dedup hit changed the payload"
            stats1 = target_srv.batcher_stats("lm") or {}
            assert stats1.get("requests", 0) \
                == stats0.get("requests", 0) + 1, (
                "double submit executed twice", stats0, stats1)

            # -- 5. /metrics deltas (shared in-process registry) ------
            after = scrape(rport)

            def delta(name, **labels):
                return (sample_value(after, name, **labels) or 0) \
                    - (sample_value(before, name, **labels) or 0)

            assert delta("kft_router_replays_total", outcome="ok") \
                >= 1, after.get("kft_router_replays_total")
            assert delta("kft_serving_dedup_hits_total", model="lm") \
                >= 1, after.get("kft_serving_dedup_hits_total")
            assert delta("kft_router_resume_tokens_count") >= 1, \
                after.get("kft_router_resume_tokens_count")
            # Zero 502/504 THIS scenario (delta — an earlier in-process
            # scenario may have driven deliberate failures).
            prior = {tuple(sorted(labels.items())): v for labels, v in
                     before.get("kft_router_requests_total", ())}
            bad = {tuple(sorted(labels.items())): v for labels, v in
                   after.get("kft_router_requests_total", ())
                   if labels.get("code") in ("502", "504")
                   and v > prior.get(
                       tuple(sorted(labels.items())), 0)}
            assert not bad, bad
        finally:
            if router_httpd is not None:
                router_httpd.shutdown()
            for srv, httpd in replicas:
                try:
                    httpd.shutdown()
                    httpd.server_close()
                except Exception:
                    pass
                srv.stop()


def kv_spill_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic hierarchical-KV scenario (§5.10): three engine
    replicas with a TIGHT device pool (12 pages) and a host spill
    tier behind the fleet router.

      1. control — uninterrupted turn-1 and turn-2 greedy streams
         recorded on one replica;
      2. spill under pressure — multi-turn sessions park their KV
         (``park_kv``) on a replica until the parked mass exceeds the
         device pool; the overflow spills to host RAM with ZERO
         sheds and ZERO destructive evictions
         (kft_engine_kv_spill_total{direction="out"} and the host-
         tier gauge move, kv_shed stays flat);
      3. re-import — the first parked session's turn 2 re-imports its
         spilled pages through kv_import (spill_total{direction="in"}
         delta) and streams BIT-IDENTICAL to the uninterrupted
         control;
      4. resume-by-FETCH failover — a session parked on BOTH
         surviving replicas is killed mid-generation on whichever
         replica serves its turn 2; the router's replay fetches the
         session's pages from a surviving peer (:fetch_kv,
         kft_router_kv_fetch_total{outcome="ok"} delta, engine.fetch
         hook-site encounter) and the spliced stream equals the
         control.
    """
    import json
    import os
    import socket
    import tempfile
    import threading
    import urllib.request
    from http.server import ThreadingHTTPServer

    import jax
    import numpy as np

    from kubeflow_tpu.fleet.endpoints import (
        Endpoint,
        EndpointRegistry,
        StaticEndpoints,
    )
    from kubeflow_tpu.fleet.router import FleetRouter, make_router_server
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.runtime.prom import parse_metrics, sample_value
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer
    from kubeflow_tpu.testing import faults

    class KillableServer(ThreadingHTTPServer):
        """See survivable_smoke: severs live sockets on kill()."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._live = set()
            self._live_lock = threading.Lock()

        def process_request(self, request, client_address):
            with self._live_lock:
                self._live.add(request)
            super().process_request(request, client_address)

        def shutdown_request(self, request):
            with self._live_lock:
                self._live.discard(request)
            super().shutdown_request(request)

        def handle_error(self, request, client_address):
            pass

        def kill(self):
            with self._live_lock:
                live = list(self._live)
                self._live.clear()
            for sock in live:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                sock.close()
            self.shutdown()
            self.server_close()

    overrides = {
        "vocab_size": 128, "d_model": 32, "n_layers": 2, "n_heads": 4,
        "n_kv_heads": 2, "d_ff": 64, "head_dim": 8, "max_seq_len": 64,
        "dtype": "float32",
    }
    max_new = 12
    rng = np.random.RandomState(20260807)
    prompts = [rng.randint(1, 120, size=(9 + i,)).tolist()
               for i in range(5)]
    scenario = os.environ.get(faults.ENV) or \
        "seed=20260807;engine.step:sleep=0.02"

    def make_replica(base, port=0):
        server = ModelServer()
        server.add_model("lm", base)
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=32, max_queue_depth=16,
            kv_block_tokens=4, kv_pool_blocks=12,
            host_spill_blocks=60))
        httpd, _ = make_http_server(server, port=port, host="127.0.0.1",
                                    server_cls=KillableServer)
        return server, httpd

    def stream_via(port, body, on_tokens=None, timeout=180):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        conn.request("POST", "/model/lm:generate",
                     json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200, (resp.status, resp.read())
        meta = terminal = None
        tokens = []
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            if "meta" in msg:
                meta = msg["meta"]
            elif "tokens" in msg:
                tokens.extend(msg["tokens"])
                if on_tokens is not None:
                    on_tokens(tokens)
            if "done" in msg or "error" in msg:
                terminal = msg
                break
        conn.close()
        return meta, tokens, terminal

    def scrape(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=30) as resp:
            return parse_metrics(resp.read().decode())

    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0), np.zeros((1, 4), np.int32))
    replicas = []
    router_httpd = None
    with faults.injected(scenario) as inj, \
            tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        try:
            replicas = [list(make_replica(f"{tmp}/lm"))
                        for _ in range(3)]
            ports = [h.server_address[1] for _, h in replicas]
            eps = [Endpoint(name=f"srv-{i}",
                            url=f"http://127.0.0.1:{p}")
                   for i, p in enumerate(ports)]
            registry = EndpointRegistry(
                StaticEndpoints(eps), probe_interval_s=0.2,
                eject_threshold=3, eject_backoff_s=2.0)
            registry.refresh()
            assert len(registry.routable()) == 3, registry.describe()
            router = FleetRouter(registry, max_tries=3, max_replays=2,
                                 try_timeout_s=180.0)
            router_httpd, _ = make_router_server(router, port=0,
                                                 host="127.0.0.1")
            rport = router_httpd.server_address[1]

            # -- 1. uninterrupted controls on replica 0 ---------------
            def turn1_body(i, park=False):
                b = {"tokens": prompts[i], "max_new_tokens": max_new}
                if park:
                    b["park_kv"] = True
                return b

            controls = {}
            for i in range(len(prompts)):
                _, toks, term = stream_via(ports[0], turn1_body(i))
                assert term.get("done") and len(toks) == max_new
                controls[i] = toks
            # Turn 2 extends turn 1's full context with 3 user tokens.
            extra = rng.randint(1, 120, size=(3,)).tolist()

            def turn2_body(i):
                return {"tokens": prompts[i] + controls[i] + extra,
                        "max_new_tokens": max_new}

            control2 = {}
            for i in (0, 1):
                _, toks, term = stream_via(ports[0], turn2_body(i))
                assert term.get("done"), term
                control2[i] = toks

            before = scrape(rport)
            spills_before = inj.fired("engine.spill")

            def delta(name, **labels):
                # Deltas, not absolutes: the registry is process-wide
                # and an earlier in-process scenario may have moved
                # the same counters.  Engine-labeled reads must pin
                # engine="lm-v1" (batcher_factory names engines
                # {model}-v{version}): sample_value returns the FIRST
                # matching series, and an earlier test file's engines
                # (default name "engine") register theirs first.
                return (sample_value(scrape(rport), name, **labels)
                        or 0) - (sample_value(before, name, **labels)
                                 or 0)

            # -- 2. parked sessions overflow the pool into host RAM --
            # Replica 1 parks every session (5 contexts x ~5 pages in
            # a 12-page pool => the cold ones MUST spill); replica 2
            # parks session 1 too — the fetch-failover scenario needs
            # the session host-resident on BOTH survivors.
            for i in range(len(prompts)):
                _, toks, term = stream_via(
                    ports[1], turn1_body(i, park=True))
                assert term.get("done") and toks == controls[i], (
                    f"parked session {i} diverged", toks)
            _, toks, _ = stream_via(ports[2], turn1_body(1, park=True))
            assert toks == controls[1]
            assert inj.fired("engine.spill") > spills_before
            assert delta("kft_engine_kv_spill_total",
                         engine="lm-v1", direction="out") > 0
            assert (sample_value(scrape(rport),
                                 "kft_engine_host_tier_blocks",
                                 engine="lm-v1")
                    or 0) > 0
            assert delta("kft_engine_kv_shed_no_blocks_total",
                         engine="lm-v1") == 0, (
                "pool-exhaustion shed while spillable mass existed")
            st1 = replicas[1][0].batcher_stats("lm") or {}
            assert st1.get("shed", 0) == 0, st1
            assert st1.get("parked_sessions") == len(prompts)
            assert st1.get("tokens_addressable") == (12 + 60) * 4
            assert st1.get("kv_spill_ratio", 0) > 0

            # -- 3. turn-2 re-import: bit-identical to the control ----
            _, toks, term = stream_via(ports[1], turn2_body(0))
            assert term.get("done") and toks == control2[0], (
                "re-imported resume diverged from control",
                toks, control2[0])
            assert delta("kft_engine_kv_spill_total",
                         engine="lm-v1", direction="in") > 0, \
                "turn 2 did not re-import spilled pages"

            # -- 4. kill mid-generation; resume by FETCH from a peer --
            killed: dict = {}
            kill_lock = threading.Lock()

            def maybe_kill(tokens):
                if len(tokens) < 3:
                    return
                with kill_lock:
                    if killed:
                        return
                    for i, (srv, httpd) in enumerate(replicas):
                        stats = srv.batcher_stats("lm") or {}
                        if stats.get("in_flight_requests", 0) > 0:
                            killed["index"] = i
                            httpd.kill()
                            return

            meta, toks, term = stream_via(rport, turn2_body(1),
                                          on_tokens=maybe_kill)
            assert killed, "the kill never fired"
            assert term is not None and term.get("done"), term
            assert toks == control2[1], (
                "fetch-resumed stream diverged from control",
                toks, control2[1])
            assert delta("kft_router_kv_fetch_total",
                         outcome="ok") >= 1
            assert delta("kft_router_replays_total",
                         outcome="ok") >= 1
            assert inj.fired("engine.fetch") >= 1
        finally:
            if router_httpd is not None:
                router_httpd.shutdown()
            for srv, httpd in replicas:
                try:
                    httpd.shutdown()
                    httpd.server_close()
                except Exception:
                    pass
                srv.stop()


def multichip_serving_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic multi-chip serving scenario (§5.9) over a forced
    multi-device host platform:

      1. topology — a PREFILL-role replica and a DECODE-role replica
         (its engine tensor-parallel over a 2-device mesh,
         serving/sharding.py) behind the fleet router; the registry
         learns both tiers off /readyz;
      2. tiered :generate — streams through the router pipeline
         prefill-then-decode (the prompt's KV pages cross as a
         block-page handoff payload) and every token stream is
         IDENTICAL to a unified single-tier control replica's;
      3. handoff counters — kft_engine_handoff_pages_total
         {direction="export"} on the prefill replica and
         {direction="import"} on the decode replica move as /metrics
         deltas, as do kft_router_tier_requests_total{tier};
      4. decode-pool death mid-handoff — with the only decode
         replica dead, a tiered :generate sheds typed 429 Overloaded
         (Retry-After set) instead of hanging or 502ing.

    Needs >= 4 local devices; when the current process initialized
    JAX single-device (standalone CI runs), it re-execs itself in a
    subprocess with ``--xla_force_host_platform_device_count=4`` —
    the same trick the test conftest and MULTICHIP dryruns use.
    """
    import os
    import sys

    import jax

    if jax.device_count() < 4:
        import subprocess

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        flags = env.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            env["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
        proc = subprocess.run(
            [sys.executable, "-m", "kubeflow_tpu.testing.e2e",
             "multichip_serving", "--namespace", namespace],
            env=env, timeout=600)
        assert proc.returncode == 0, (
            f"multichip_serving re-exec failed rc={proc.returncode}")
        return

    import json
    import tempfile
    import urllib.request

    import numpy as np

    from kubeflow_tpu.fleet.endpoints import (
        Endpoint,
        EndpointRegistry,
        StaticEndpoints,
    )
    from kubeflow_tpu.fleet.router import FleetRouter, make_router_server
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.runtime.prom import parse_metrics, sample_value
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer

    overrides = {"vocab_size": 96, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "n_kv_heads": 4, "d_ff": 64,
                 "head_dim": 8, "max_seq_len": 64, "dtype": "float32"}
    max_new = 10
    rng = np.random.RandomState(20260804)
    prompts = [rng.randint(1, 96, size=(n,)).tolist()
               for n in (9, 12, 16)]

    import socket
    import threading
    from http.server import ThreadingHTTPServer

    class KillableServer(ThreadingHTTPServer):
        """shutdown() only stops accepting; a dead pod also resets
        every ESTABLISHED socket (including the router's pooled
        keep-alive upstreams) — kill() reproduces that signature."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._live = set()
            self._live_lock = threading.Lock()

        def process_request(self, request, client_address):
            with self._live_lock:
                self._live.add(request)
            super().process_request(request, client_address)

        def shutdown_request(self, request):
            with self._live_lock:
                self._live.discard(request)
            super().shutdown_request(request)

        def kill(self):
            self.shutdown()
            self.server_close()
            with self._live_lock:
                live = list(self._live)
            for sock in live:
                try:
                    sock.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    sock.close()
                except OSError:
                    pass

    def make_replica(base, role, mesh=""):
        server = ModelServer(role=role)
        server.add_model("lm", base)
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=2,
            lm_engine_prefill_len=32, kv_block_tokens=4,
            max_queue_depth=16, mesh=mesh))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1",
                                    server_cls=KillableServer)
        return server, httpd

    def stream_via(port, body, path="/model/lm:generate",
                   timeout=180):
        import http.client

        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        conn.request("POST", path, json.dumps(body).encode(),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if resp.status != 200:
            payload = resp.read()
            conn.close()
            return resp.status, dict(resp.headers.items()), payload
        tokens = []
        terminal = None
        while True:
            line = resp.readline()
            if not line:
                break
            line = line.strip()
            if not line:
                continue
            msg = json.loads(line)
            if "tokens" in msg:
                tokens.extend(msg["tokens"])
            if "done" in msg or "error" in msg:
                terminal = msg
                break
        conn.close()
        return 200, tokens, terminal

    def scrape(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=30) as resp:
            return parse_metrics(resp.read().decode())

    model = Transformer(_model_config(overrides))
    variables = model.init(jax.random.key(0),
                           np.zeros((1, 4), np.int32))
    servers = []
    router_httpd = None
    with tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        try:
            pre_srv, pre_httpd = make_replica(f"{tmp}/lm", "prefill")
            dec_srv, dec_httpd = make_replica(f"{tmp}/lm", "decode",
                                              mesh="tensor=2")
            uni_srv, uni_httpd = make_replica(f"{tmp}/lm", "unified")
            servers = [(pre_srv, pre_httpd), (dec_srv, dec_httpd),
                       (uni_srv, uni_httpd)]
            pre_port = pre_httpd.server_address[1]
            dec_port = dec_httpd.server_address[1]
            uni_port = uni_httpd.server_address[1]
            # The fleet is the two TIERS; the unified replica stays
            # outside as the single-tier control.
            registry = EndpointRegistry(StaticEndpoints([
                Endpoint(name="pre-0",
                         url=f"http://127.0.0.1:{pre_port}"),
                Endpoint(name="dec-0",
                         url=f"http://127.0.0.1:{dec_port}"),
            ]), probe_interval_s=0.2, eject_threshold=2)
            registry.refresh()
            tiers = {s.name: s.tier for s in registry.all()}
            assert tiers == {"pre-0": "prefill", "dec-0": "decode"}, (
                f"registry failed to learn tiers: {tiers}")
            router = FleetRouter(registry, max_tries=3,
                                 try_timeout_s=60.0)
            router_httpd, _ = make_router_server(router, port=0,
                                                 host="127.0.0.1")
            rport = router_httpd.server_address[1]

            pre0 = scrape(pre_port)
            dec0 = scrape(dec_port)
            r0 = scrape(rport)

            # --- tiered streams match the unified control exactly ---
            for prompt in prompts:
                body = {"tokens": prompt}
                st, want, wterm = stream_via(uni_port, body)
                assert st == 200 and wterm.get("done"), (st, wterm)
                st, got, gterm = stream_via(rport, body)
                assert st == 200, (st, got)
                assert gterm.get("done"), gterm
                assert got == want, (
                    f"tiered stream diverged from unified control "
                    f"for len {len(prompt)}: {got} != {want}")

            # --- handoff + tier counters moved as deltas ------------
            def delta(before, after, name, **labels):
                b = sample_value(before, name, **labels) or 0
                a = sample_value(after, name, **labels) or 0
                return a - b

            pre1, dec1, r1 = (scrape(pre_port), scrape(dec_port),
                              scrape(rport))
            exported = delta(pre0, pre1,
                             "kft_engine_handoff_pages_total",
                             engine="lm-v1", direction="export")
            imported = delta(dec0, dec1,
                             "kft_engine_handoff_pages_total",
                             engine="lm-v1", direction="import")
            assert exported > 0, "no pages exported by prefill tier"
            assert imported > 0, "no pages imported by decode tier"
            assert delta(r0, r1, "kft_router_tier_requests_total",
                         tier="prefill") == len(prompts)
            assert delta(r0, r1, "kft_router_tier_requests_total",
                         tier="decode") == len(prompts)
            # Per-replica (the three in-process replicas share one
            # prom registry, so the engine-labeled gauge aliases —
            # the :stats route is per-server truth).
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{dec_port}/model/lm:stats",
                    timeout=30) as resp:
                dec_stats = json.loads(resp.read())["batcher"]
            assert dec_stats["mesh_devices"] == 2, dec_stats
            assert dec_stats["handoff_pages_in"] > 0
            assert dec_stats["compiled_programs"]["kv_import"] == 1

            # --- decode-pool death mid-handoff: typed Overloaded ----
            dec_httpd.kill()
            # The registry still lists the decode tier as routable
            # (no probe ran since the kill), so the router commits to
            # the tiered path, the prefill leg succeeds, and the dead
            # decode pool must shed typed 429 — never hang or 502.
            st, headers, payload = stream_via(rport,
                                              {"tokens": prompts[0]})
            assert st == 429, (st, payload)
            assert "Retry-After" in headers, headers
            r2 = scrape(rport)
            assert delta(r1, r2, "kft_router_requests_total",
                         outcome="shed", code="429") >= 1
        finally:
            if router_httpd is not None:
                router_httpd.shutdown()
            for srv, httpd in servers:
                try:
                    httpd.shutdown()
                except Exception:
                    pass
                srv.stop()


def adapter_serving_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic adapter-array multi-model serving scenario (§5.11):
    THREE per-tenant adapters over a TWO-replica engine fleet behind
    the router, every variant riding the base model's one compiled
    program set.

      1. hot-load under live traffic — while concurrent base-model
         clients stream through the router, the first requests naming
         ``lm@alpha`` / ``lm@beta`` hot-load their artifacts from the
         adapter directory mid-burst; every request (base and variant)
         returns 200 with tokens IDENTICAL to a sequential per-adapter
         control server's;
      2. co-batched mixed burst — base/alpha/beta concurrently through
         the router: all complete, all token-identical to their
         sequential controls, and each engine still reports only the
         base program set over :stats (no per-adapter executable);
      3. evict-under-pressure — with 2 registry slots per replica, a
         gamma request against a replica holding an IN-FLIGHT alpha
         generation must evict the idle beta, never the pinned alpha:
         the live request completes bit-identical, beta hot-reloads on
         its next request, and kft_engine_adapter_evictions_total
         moves as a /metrics delta;
      4. advertisement + affinity — /readyz advertises loaded adapter
         digests, the registry learns them at the next probe, and
         routed ``lm@alpha`` traffic prefers warm replicas
         (kft_router_adapter_affinity_total{outcome="hit"} delta);
         an unknown adapter sheds typed 404 through the whole stack.

    kft_engine_adapter_loads_total / _requests_total / _evictions_total
    and the router affinity counter are all asserted as /metrics
    deltas.  Override the chaos scenario via KFT_FAULTS (the default
    slows engine steps so the in-flight pin in step 3 is observable).
    """
    import json
    import os
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import jax
    import numpy as np

    from kubeflow_tpu.fleet.endpoints import (
        Endpoint,
        EndpointRegistry,
        StaticEndpoints,
    )
    from kubeflow_tpu.fleet.router import FleetRouter, make_router_server
    from kubeflow_tpu.models.transformer import Transformer
    from kubeflow_tpu.runtime.prom import parse_metrics, sample_value
    from kubeflow_tpu.serving.adapters import (
        random_adapter_factors,
        save_adapter,
    )
    from kubeflow_tpu.serving.export import export
    from kubeflow_tpu.serving.http import make_http_server
    from kubeflow_tpu.serving.loaders import _model_config
    from kubeflow_tpu.serving.main import batcher_factory
    from kubeflow_tpu.serving.model_server import ModelServer
    from kubeflow_tpu.testing import faults

    overrides = {"vocab_size": 96, "d_model": 32, "n_layers": 2,
                 "n_heads": 4, "n_kv_heads": 2, "d_ff": 64,
                 "head_dim": 8, "max_seq_len": 64, "dtype": "float32"}
    cfg = _model_config(overrides)
    max_new, rank = 8, 4
    scenario = os.environ.get(faults.ENV) or \
        "seed=20260807;engine.step:sleep=0.01"
    rng = np.random.RandomState(20260807)
    prompts = [rng.randint(1, 96, size=(n,)).tolist()
               for n in (8, 5, 11, 9)]
    tenants = ("alpha", "beta", "gamma")

    def make_replica(base, adir):
        server = ModelServer()
        server.add_model("lm", base)
        server.enable_batching("lm", batcher_factory(
            micro_batch_size=0, batch_timeout_s=0.005,
            lm_engine=True, lm_engine_slots=3,
            lm_engine_prefill_len=16, kv_block_tokens=4,
            max_queue_depth=16, adapters_dir=adir,
            adapter_slots=2, adapter_rank=rank))
        httpd, _ = make_http_server(server, port=0, host="127.0.0.1")
        return server, httpd

    def predict_via(port, name, prompt, timeout=180):
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/model/{name}:predict",
            data=json.dumps(
                {"instances": [{"tokens": prompt}]}).encode())
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    def scrape(port):
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics",
                timeout=30) as resp:
            return parse_metrics(resp.read().decode())

    def delta(before, after, name, **labels):
        return (sample_value(after, name, **labels) or 0.0) \
            - (sample_value(before, name, **labels) or 0.0)

    model = Transformer(cfg)
    variables = model.init(jax.random.key(0),
                           np.zeros((1, 4), np.int32))
    replicas = []
    router_httpd = None
    with faults.injected(scenario), \
            tempfile.TemporaryDirectory() as tmp:
        export(f"{tmp}/lm", 1, variables,
               loader="kubeflow_tpu.serving.loaders:lm_generate",
               config={"model": overrides, "max_new_tokens": max_new,
                       "temperature": 0.0})
        adir = f"{tmp}/adapters"
        os.makedirs(adir)
        for i, name in enumerate(tenants):
            save_adapter(f"{adir}/{name}.npz", random_adapter_factors(
                cfg, rank, seed=100 + i, scale=0.5))
        control = None
        try:
            # -- sequential per-adapter controls (one request in
            # flight at a time, dedicated server: the co-batched
            # fleet must be bit-identical to THIS).
            control = ModelServer()
            control.add_model("lm", f"{tmp}/lm")
            control.enable_batching("lm", batcher_factory(
                micro_batch_size=0, batch_timeout_s=0.005,
                lm_engine=True, lm_engine_slots=1,
                lm_engine_prefill_len=16, adapters_dir=adir,
                adapter_slots=3, adapter_rank=rank))
            want = {}
            for name in ("lm", "lm@alpha", "lm@beta", "lm@gamma"):
                for p in prompts:
                    out = control.predict(
                        name, {"tokens": np.asarray(p, np.int32)[None]})
                    want[(name, tuple(p))] = \
                        np.asarray(out["tokens"])[0].tolist()
            assert want[("lm@alpha", tuple(prompts[0]))] != \
                want[("lm", tuple(prompts[0]))], (
                "adapter delta too small to move greedy decode — the "
                "identity assertions below would be vacuous")

            # -- fleet assembly --------------------------------------
            replicas = [make_replica(f"{tmp}/lm", adir)
                        for _ in range(2)]
            ports = [h.server_address[1] for _, h in replicas]
            registry = EndpointRegistry(StaticEndpoints([
                Endpoint(name=f"srv-{i}",
                         url=f"http://127.0.0.1:{p}")
                for i, p in enumerate(ports)]),
                probe_interval_s=0.2, eject_threshold=2)
            registry.refresh()
            assert len(registry.routable()) == 2, registry.describe()
            router = FleetRouter(registry, max_tries=3,
                                 try_timeout_s=180.0)
            router_httpd, _ = make_router_server(router, port=0,
                                                 host="127.0.0.1")
            rport = router_httpd.server_address[1]
            m0 = scrape(ports[0])

            # -- 1. hot-load under live base traffic -----------------
            results: dict = {}

            def client(i, name, prompt):
                results[i] = (name, prompt,
                              predict_via(rport, name, prompt))

            base_threads = [
                threading.Thread(target=client,
                                 args=(i, "lm", prompts[i % 2]))
                for i in range(4)]
            for t in base_threads:
                t.start()
            # Mid-burst: the FIRST requests naming the variants land
            # while base traffic is in flight — cold artifact loads
            # under live load.
            hot_threads = [
                threading.Thread(
                    target=client,
                    args=(4 + j, f"lm@{name}", prompts[2 + j % 2]))
                for j, name in enumerate(("alpha", "beta"))]
            for t in hot_threads:
                t.start()
            for t in base_threads + hot_threads:
                t.join(timeout=180)
            assert len(results) == 6
            for name, prompt, (code, payload) in results.values():
                assert code == 200, (name, code, payload)
                got = payload["predictions"][0]["tokens"]
                assert got == want[(name, tuple(prompt))], (
                    f"{name} diverged from its sequential control "
                    f"under the hot-load burst")

            # -- 2. co-batched mixed burst ---------------------------
            results = {}
            mixed = [("lm", prompts[0]), ("lm@alpha", prompts[1]),
                     ("lm@beta", prompts[2]), ("lm@alpha", prompts[3]),
                     ("lm", prompts[2]), ("lm@beta", prompts[0]),
                     ("lm@alpha", prompts[2]), ("lm", prompts[1]),
                     ("lm@beta", prompts[3])]
            threads = [threading.Thread(target=client,
                                        args=(i, name, prompt))
                       for i, (name, prompt) in enumerate(mixed)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert sorted(r[2][0] for r in results.values()) \
                == [200] * len(mixed), results
            for name, prompt, (_, payload) in results.values():
                got = payload["predictions"][0]["tokens"]
                assert got == want[(name, tuple(prompt))], (
                    f"{name} diverged from its sequential control "
                    f"in the co-batched burst")
            # One program set per engine — no per-adapter executable.
            for i, (srv, _) in enumerate(replicas):
                stats = srv.batcher_stats("lm") or {}
                programs = stats.get("compiled_programs") or {}
                assert set(k for k, v in programs.items() if v) <= \
                    {"chunked_prefill", "step"}, (
                    f"replica {i} grew extra programs under mixed "
                    f"adapter traffic: {programs}")

            # -- 3. evict-under-pressure with a live pin -------------
            # Direct to replica 0: make alpha + beta resident, hold an
            # alpha generation IN FLIGHT, then demand gamma — its load
            # must evict idle beta, never the pinned alpha.
            srv0, port0 = replicas[0][0], ports[0]
            for name in ("lm@alpha", "lm@beta"):
                code, payload = predict_via(port0, name, prompts[0])
                assert code == 200, (name, code, payload)
            m_before = scrape(port0)
            inflight0 = srv0.inflight()
            holder: dict = {}
            t = threading.Thread(target=lambda: holder.update(
                {"resp": predict_via(port0, "lm@alpha", prompts[3])}))
            t.start()
            deadline = time.time() + 60
            while srv0.inflight() <= inflight0:
                assert time.time() < deadline, (
                    "pinned alpha request never started")
                time.sleep(0.005)
            code, payload = predict_via(port0, "lm@gamma", prompts[1])
            assert code == 200, (code, payload)
            assert payload["predictions"][0]["tokens"] \
                == want[("lm@gamma", tuple(prompts[1]))]
            t.join(timeout=180)
            code, payload = holder["resp"]
            assert code == 200, (
                "the in-flight alpha request was dropped by the "
                "eviction", code, payload)
            assert payload["predictions"][0]["tokens"] \
                == want[("lm@alpha", tuple(prompts[3]))], (
                "the pinned alpha generation was corrupted by the "
                "gamma load")
            resident = {a["name"]
                        for a in srv0.adapter_info().get("lm", ())}
            assert "alpha" in resident and "gamma" in resident, resident
            assert "beta" not in resident, (
                "eviction took the wrong victim", resident)
            m_after = scrape(port0)
            assert delta(m_before, m_after,
                         "kft_engine_adapter_evictions_total",
                         engine="lm-v1") >= 1
            # Evicted beta hot-reloads on demand, identically.
            code, payload = predict_via(port0, "lm@beta", prompts[0])
            assert code == 200
            assert payload["predictions"][0]["tokens"] \
                == want[("lm@beta", tuple(prompts[0]))]

            # -- 4. advertisement + affinity + typed sheds -----------
            # Touch alpha on replica 0 first: the beta reload above may
            # have taken alpha as its LRU victim, and the affinity
            # assertion below needs at least one warm alpha replica.
            code, _ = predict_via(port0, "lm@alpha", prompts[0])
            assert code == 200
            resident = {a["name"]
                        for a in srv0.adapter_info().get("lm", ())}
            assert "alpha" in resident, resident
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port0}/readyz",
                    timeout=30) as resp:
                ready = json.loads(resp.read())
            advertised = {a["name"]: a["digest"]
                          for a in ready.get("adapters", {}).get(
                              "lm", ())}
            assert set(advertised) == resident, ready
            assert all(len(d) == 64 for d in advertised.values())
            registry.refresh()   # the probe learns the advertisement
            r_before = scrape(rport)
            for _ in range(4):
                code, payload = predict_via(rport, "lm@alpha",
                                            prompts[0])
                assert code == 200
                assert payload["predictions"][0]["tokens"] \
                    == want[("lm@alpha", tuple(prompts[0]))]
            r_after = scrape(rport)
            assert delta(r_before, r_after,
                         "kft_router_adapter_affinity_total",
                         outcome="hit") >= 4, (
                "routed lm@alpha traffic never hit the warm subset")
            code, payload = predict_via(rport, "lm@ghost", prompts[0])
            assert code == 404, (
                "unknown adapter must shed typed 404 through the "
                "router", code, payload)

            # -- engine adapter counters moved as /metrics deltas ----
            m1 = scrape(ports[0])
            assert delta(m0, m1, "kft_engine_adapter_loads_total",
                         engine="lm-v1", adapter="alpha") >= 1
            assert delta(m0, m1, "kft_engine_adapter_requests_total",
                         engine="lm-v1", adapter="alpha") >= 1
            total_loads = sum(
                delta(m0, m1, "kft_engine_adapter_loads_total",
                      engine="lm-v1", adapter=name)
                for name in tenants)
            assert total_loads >= 4, (
                "expected initial loads + the beta reload", total_loads)
        finally:
            if router_httpd is not None:
                router_httpd.shutdown()
            if control is not None:
                control.stop()
            for srv, httpd in replicas:
                try:
                    httpd.shutdown()
                except Exception:
                    pass
                srv.stop()


def scheduler_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic multi-tenant scheduler scenario: two tenants' TPUJobs
    through the fake apiserver (real sockets, HttpKube) against the
    policy layer (kubeflow_tpu/scheduler/) + gang + reconciler:

      1. quota — a greedy tenant's third job holds at QuotaExceeded
         while a politer tenant admitted later runs;
      2. backfill — a small low-priority job provably jumps a blocked
         large high-priority job (disjoint slice pools) and the large
         job's admission is not delayed;
      3. preemption with resume — a high-priority arrival evicts the
         lowest-priority gang through the Preempting grace window
         (clock-skewed, no wall sleeping); the victim re-queues
         ``resumable`` and, after the preemptor finishes, restarts and
         resumes from its latest CheckpointManager step (> 0, no
         step-0 retraining);
      4. every outcome is scrapeable in kft_scheduler_* metrics.
    """
    import numpy as np

    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler
    from kubeflow_tpu.operator.kube_http import HttpKube
    from kubeflow_tpu.operator.reconciler import (
        JOB_PREEMPTING,
        JOB_SUCCEEDED,
        QUEUED,
        STARTING,
        TPUJobController,
    )
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    from kubeflow_tpu.runtime.prom import (
        REGISTRY,
        parse_metrics,
        sample_value,
    )
    from kubeflow_tpu.scheduler import (
        LABEL_PRIORITY,
        LABEL_TENANT,
        ClusterScheduler,
        PreemptionConfig,
        SchedulerConfig,
    )
    from kubeflow_tpu.testing import faults
    from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver

    def make_cr(name, tenant, priority, slice_type="v5e-8", n=1):
        job = crd.TPUJobSpec(name=name, namespace=namespace,
                             slice_type=slice_type, num_slices=n)
        cr = job.to_custom_resource()
        cr["metadata"]["labels"] = {LABEL_TENANT: tenant,
                                    LABEL_PRIORITY: priority}
        return cr

    import tempfile

    apiserver = None
    with faults.injected("seed=20260804") as inj, \
            tempfile.TemporaryDirectory() as tmp:
        try:
            apiserver, _, store = make_fake_apiserver()
            kube = HttpKube(
                base_url=f"http://127.0.0.1:"
                         f"{apiserver.server_address[1]}")
            gang = GangScheduler({"v5e-8": 4, "v5p-32": 1})
            cluster = ClusterScheduler(gang, SchedulerConfig(
                quotas={"greedy": {"v5e-8": 16}},
                preemption=PreemptionConfig(grace_period_s=30.0)))
            ctl = TPUJobController(kube, gang, cluster)

            def statuses():
                return {c["metadata"]["name"]: (c.get("status") or {})
                        for c in kube.list_custom(namespace)}

            def run_pods(job_name):
                for p in kube.list_pods(
                        namespace,
                        labels={"kubeflow-tpu.org/job-name": job_name}):
                    store.set_pod_phase(namespace,
                                        p["metadata"]["name"],
                                        "Running")

            # -- 1. quota-capped greedy tenant ------------------------
            for i in range(3):
                kube.create_custom(
                    make_cr(f"greedy-{i}", "greedy", "normal"))
            kube.create_custom(make_cr("polite", "polite", "normal"))
            ctl.reconcile_all()
            st = statuses()
            admitted = sorted(n for n in st
                              if st[n].get("phase") == STARTING)
            assert admitted == ["greedy-0", "greedy-1", "polite"], st
            assert st["greedy-2"]["phase"] == QUEUED
            assert st["greedy-2"]["reason"] == "QuotaExceeded", st

            # -- 2. backfill past a blocked large job -----------------
            kube.create_custom(
                make_cr("vp-run", "research", "high",
                        slice_type="v5p-32"))
            ctl.reconcile_all()
            kube.create_custom(
                make_cr("vp-blocked", "research", "high",
                        slice_type="v5p-32"))
            kube.create_custom(make_cr("small-low", "batch", "low"))
            ctl.reconcile_all()
            st = statuses()
            assert st["vp-blocked"]["reason"] == "WaitingForSlices", st
            assert st["small-low"]["phase"] == STARTING, st
            assert cluster.status()["counters"]["backfilled"] >= 1
            # ETA unchanged: vp-run ends, vp-blocked starts at once
            # with the backfilled job still holding its v5e slice.
            run_pods("vp-run")
            ctl.reconcile_all()
            for p in kube.list_pods(
                    namespace,
                    labels={"kubeflow-tpu.org/job-name": "vp-run"}):
                store.set_pod_phase(namespace, p["metadata"]["name"],
                                    "Succeeded")
            ctl.reconcile_all()
            ctl.reconcile_all()
            st = statuses()
            assert st["vp-run"]["phase"] == JOB_SUCCEEDED
            assert st["vp-blocked"]["phase"] == STARTING, st
            assert st["small-low"]["phase"] == STARTING, st

            # -- 3. preemption -> checkpoint grace -> resume ----------
            # The victim gang's trainer has checkpointed through step
            # 4 (what restore_or_init will find on re-admission).
            base = np.arange(8, dtype=np.float32)
            with CheckpointManager(f"{tmp}/victim-ckpt",
                                   save_interval_steps=1) as mgr:
                for step in range(5):
                    mgr.save(step,
                             {"step": np.full((), step, np.int32),
                              "w": base + step})
            kube.create_custom(make_cr("vip", "prod", "high"))
            ctl.reconcile_all()
            st = statuses()
            # v5e-8 was full; the lowest-priority gang is evicted.
            assert st["small-low"]["phase"] == JOB_PREEMPTING, st
            assert st["small-low"]["resumable"] is True
            assert kube.list_pods(
                namespace,
                labels={"kubeflow-tpu.org/job-name": "small-low"}), \
                "pods must survive the checkpoint grace window"
            ctl.reconcile_all()
            assert statuses()["small-low"]["phase"] == JOB_PREEMPTING
            inj.advance_clock(31)   # grace elapses, zero wall waiting
            ctl.reconcile_all()
            st = statuses()
            assert st["small-low"]["phase"] == QUEUED
            assert st["small-low"]["reason"] == "PreemptedRequeued", st
            ctl.reconcile_all()
            st = statuses()
            assert st["vip"]["phase"] == STARTING, st
            run_pods("vip")
            ctl.reconcile_all()
            for p in kube.list_pods(
                    namespace,
                    labels={"kubeflow-tpu.org/job-name": "vip"}):
                store.set_pod_phase(namespace, p["metadata"]["name"],
                                    "Succeeded")
            ctl.reconcile_all()
            ctl.reconcile_all()
            st = statuses()
            assert st["vip"]["phase"] == JOB_SUCCEEDED
            assert st["small-low"]["phase"] == STARTING, st
            # resumable was consumed by the resume admission; the
            # preemption count survives as history.
            assert st["small-low"]["resumable"] is False
            assert int(st["small-low"]["preemptions"]) == 1
            assert int(st["small-low"].get("restarts", 0)) == 0, \
                "preemption must not consume the restart budget"
            # Trainer side of the resume contract: the re-admitted
            # gang restores step 4 and continues at 5 — never step 0.
            fresh = {"step": np.zeros((), np.int32),
                     "w": np.zeros(8, np.float32)}
            with CheckpointManager(f"{tmp}/victim-ckpt") as mgr2:
                restored, start = mgr2.restore_or_init(fresh)
            assert start == 5, f"resume restarted at {start}"
            np.testing.assert_allclose(restored["w"], base + 4)

            # -- 4. outcomes in kft_scheduler_* metrics ---------------
            parsed = parse_metrics(REGISTRY.render())
            assert (sample_value(parsed,
                                 "kft_scheduler_preemptions_total",
                                 tenant="batch") or 0) >= 1, parsed.get(
                "kft_scheduler_preemptions_total")
            assert (sample_value(parsed,
                                 "kft_scheduler_backfills_total",
                                 tenant="batch") or 0) >= 1
            assert (sample_value(parsed,
                                 "kft_scheduler_resumes_total",
                                 tenant="batch") or 0) >= 1
            assert sample_value(parsed, "kft_scheduler_quota_chips",
                                tenant="greedy",
                                slice_type="v5e-8") == 16
            assert sample_value(parsed, "kft_scheduler_queue_depth",
                                tenant="greedy",
                                priority="normal") is not None
            assert "kft_scheduler_queue_wait_seconds" in parsed or \
                "kft_scheduler_queue_wait_seconds_count" in parsed
        finally:
            if apiserver is not None:
                apiserver.shutdown()
                apiserver.server_close()


def train_resilience_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic crash-safe training scenario — the whole PR-10 stack:

      1. supervised resume — a tiny LM trains under the
         TrainSupervisor with an injected ``train.step`` raise; the
         supervisor restarts in process, resumes from a VERIFIED
         checkpoint (never step 0), the global step stays monotone,
         and the final params are IDENTICAL to an uninterrupted
         control run of the same seed (loss-identity);
      2. walk-back restore — the latest checkpoint is corrupted on
         disk (truncated leaf file); ``restore_or_init`` skips it and
         resumes from the newest verified predecessor;
      3. bad-node quarantine — a TPUJob over the fake apiserver flaps
         repeatedly on one node; the operator quarantines the node
         (NodeQuarantined event), excludes it from the re-placed
         gang's pods via node anti-affinity, and exports
         ``kft_operator_quarantined_nodes``;
      4. every outcome lands in kft_train_* / kft_checkpoint_*
         metrics (asserted as deltas — the registry is shared).
    """
    import tempfile
    from pathlib import Path

    import jax
    import numpy as np
    import optax

    from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler, NodeQuarantine
    from kubeflow_tpu.operator.kube import FAILED, RUNNING
    from kubeflow_tpu.operator.kube_http import HttpKube
    from kubeflow_tpu.operator.reconciler import TPUJobController
    from kubeflow_tpu.parallel import MeshSpec
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    from kubeflow_tpu.runtime.metrics import MetricsLogger
    from kubeflow_tpu.runtime.prom import (
        REGISTRY,
        parse_metrics,
        sample_value,
    )
    from kubeflow_tpu.runtime.supervisor import TrainSupervisor
    from kubeflow_tpu.runtime.train import Trainer
    from kubeflow_tpu.testing import faults
    from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver

    def metric(parsed, name, **labels):
        return sample_value(parsed, name, **labels) or 0.0

    before = parse_metrics(REGISTRY.render())
    mesh = MeshSpec(data=-1).build()
    cfg = TransformerConfig(
        vocab_size=64, d_model=16, n_layers=1, n_heads=2, n_kv_heads=2,
        d_ff=32, head_dim=8, max_seq_len=16, dtype="float32")
    init_fn, loss_fn = lm_task(cfg, mesh=mesh)
    batch = 2 * jax.device_count()
    steps = 8

    def data_factory():
        rng = np.random.RandomState(0)
        while True:
            yield {"tokens": rng.randint(
                0, cfg.vocab_size, size=(batch, 16)).astype(np.int32)}

    def make_trainer(ckpt_dir):
        return Trainer(
            init_fn=init_fn, loss_fn=loss_fn, tx=optax.adamw(1e-3),
            mesh=mesh,
            checkpoints=CheckpointManager(ckpt_dir, max_to_keep=3),
            checkpoint_every=2,
            metrics=MetricsLogger(stream=open("/dev/null", "w")))

    def leaves(state):
        return [np.asarray(x) for x in
                jax.tree_util.tree_leaves(state.params)]

    with faults.injected("seed=20260804") as inj, \
            tempfile.TemporaryDirectory() as tmp:
        # -- control: one uninterrupted run ---------------------------
        control = make_trainer(f"{tmp}/control")
        control_state = control.run_state = TrainSupervisor(
            control, max_restarts=0).run(
                data_factory, steps, examples_per_step=batch,
                log_every=0)
        control.checkpoints.close()

        # -- 1. supervised resume from a verified step ----------------
        trainer = make_trainer(f"{tmp}/victim")
        sup = TrainSupervisor(trainer, max_restarts=2, backoff_s=5.0)
        sup.run(data_factory, 4, examples_per_step=batch, log_every=0)
        assert trainer.checkpoints.latest_verified_step() == 3
        # Fault the FIRST step of the continuation; the skew entry
        # expires the restart backoff instantly (no wall sleeping).
        inj2 = faults.parse("train.step:raise*1;train.step:skew=60")
        faults.install(inj2)
        try:
            final = sup.run(data_factory, steps,
                            examples_per_step=batch, log_every=0)
        finally:
            faults.install(inj)
        assert sup.restarts == 1, sup.stats()
        # Monotone global step: every call boundary after the restart
        # continues PAST the verified step — never back to 0.
        boundaries = sup.steps_seen
        assert boundaries == sorted(boundaries), boundaries
        assert boundaries[-1] == steps
        assert min(b for b in boundaries if b > 4) == 5, boundaries
        # Loss identity: the supervised run's params equal the
        # uninterrupted control's (same seed, replayed stream).
        for got, want in zip(leaves(final), leaves(control_state)):
            np.testing.assert_allclose(got, want, rtol=0, atol=0)

        # -- 2. corrupt latest -> walk-back restore -------------------
        trainer.checkpoints.wait()
        ckpt_dir = Path(f"{tmp}/victim")
        all_steps = trainer.checkpoints.all_steps()
        latest = all_steps[-1]
        victim_file = max(
            (p for p in (ckpt_dir / str(latest)).rglob("*")
             if p.is_file()), key=lambda p: p.stat().st_size)
        victim_file.write_bytes(victim_file.read_bytes()[:16])
        fresh = trainer.create_state()
        restored, start = trainer.checkpoints.restore_or_init(fresh)
        prev_verified = max(s for s in all_steps if s != latest)
        assert start == prev_verified + 1, (
            f"walk-back resumed at {start}, want {prev_verified + 1}")
        trainer.checkpoints.close()

        # -- 3. node flap -> quarantine + gang re-place ---------------
        apiserver = None
        try:
            apiserver, _, store = make_fake_apiserver()
            kube = HttpKube(base_url=f"http://127.0.0.1:"
                                     f"{apiserver.server_address[1]}")
            ctl = TPUJobController(
                kube, GangScheduler({"v5e-8": 1}),
                quarantine=NodeQuarantine(threshold=3, window_s=600,
                                          cooldown_s=1800))
            kube.create_custom(crd.TPUJobSpec(
                name="flappy", namespace=namespace,
                slice_type="v5e-8").to_custom_resource())
            for _ in range(3):  # three worker failures on one node
                ctl.reconcile_all()
                for p in kube.list_pods(namespace):
                    store.set_pod_node(namespace,
                                       p["metadata"]["name"],
                                       "node-flap")
                    store.set_pod_phase(namespace,
                                        p["metadata"]["name"], RUNNING)
                ctl.reconcile_all()
                pod = kube.list_pods(namespace)[0]
                store.set_pod_phase(namespace, pod["metadata"]["name"],
                                    FAILED)
                ctl.reconcile_all()
            assert ctl.quarantine.quarantined() == ["node-flap"]
            events = [e for e in store.events
                      if e["reason"] == "NodeQuarantined"]
            assert len(events) == 1, events
            # The re-placed gang's pods must EXCLUDE the bad node.
            ctl.reconcile_all()
            pods = kube.list_pods(namespace)
            assert pods, "gang was not re-placed after quarantine"
            for p in pods:
                terms = (p["spec"]["affinity"]["nodeAffinity"]
                         ["requiredDuringSchedulingIgnoredDuring"
                          "Execution"]["nodeSelectorTerms"])
                expr = terms[0]["matchExpressions"][0]
                assert expr["operator"] == "NotIn"
                assert "node-flap" in expr["values"]
        finally:
            if apiserver is not None:
                apiserver.shutdown()
                apiserver.server_close()

        # -- 4. outcomes in kft_* metrics (deltas) --------------------
        parsed = parse_metrics(REGISTRY.render())
        assert metric(parsed, "kft_train_restarts_total",
                      reason="step") \
            - metric(before, "kft_train_restarts_total",
                     reason="step") >= 1
        assert metric(parsed, "kft_checkpoint_saves_total") \
            - metric(before, "kft_checkpoint_saves_total") >= 4
        assert metric(parsed, "kft_checkpoint_verify_failures_total") \
            - metric(before,
                     "kft_checkpoint_verify_failures_total") >= 1
        assert sample_value(
            parsed, "kft_operator_quarantined_nodes") == 1
        assert sample_value(
            parsed, "kft_train_heartbeat_age_seconds") is not None


def train_smoke(namespace: str = "kubeflow-test") -> None:
    """A few real SPMD train steps on whatever devices exist."""
    import subprocess

    proc = subprocess.run(
        [sys.executable, "-m", "kubeflow_tpu.tools.train_cnn",
         "--model", "resnet18", "--steps", "2",
         "--batch-size-per-device", "2", "--image-size", "32",
         "--num-classes", "4"],
        capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def hfta_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic horizontally-fused-training-array scenario — the whole
    HFTA tier, scheduler fold (scheduler/fuse.py) through fused
    runtime (runtime/hfta.py):

      1. fold at admission — two tenants submit four fusable
         singleton TPUJobs (same family/slice/priority) over the fake
         apiserver; they admit as ONE fused gang claim on one slice,
         each member stamped with its gang and billed its fair SHARE
         of the slice chips (2 of 8), so both tenants fit a 4-chip
         quota that could not admit even one 8-chip singleton;
      2. preemption with per-member resume — a high-priority arrival
         evicts the fused gang through the clock-skewed grace window;
         every member requeues ``resumable`` with its gang stamp
         cleared, and once the preemptor finishes the fold re-forms
         and resumes ALL members (resume counter == member count);
      3. member-level completion — the shared pod gang succeeding
         completes every member CR individually (one
         FusedMemberCompleted event per member);
      4. runtime bit-identity across the same lifecycle — a width-4
         FusedTrainer (two tenants, one member early-stopping masked
         mid-run) is killed after 3 steps and resumed from its
         per-member verified-manifest checkpoints: per-member steps
         stay monotone across the boundary and final params are
         bit-identical to an uninterrupted control run, the
         early-stopped member included;
      5. outcomes are scrapeable: kft_scheduler_fused_gangs/_members
         while the gang runs, kft_train_member_steps_total /
         kft_train_members_active from the fused fit.
    """
    import tempfile

    import jax
    import numpy as np

    from kubeflow_tpu.models.transformer import TransformerConfig, lm_task
    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler
    from kubeflow_tpu.operator.kube_http import HttpKube
    from kubeflow_tpu.operator.reconciler import (
        JOB_PREEMPTING,
        JOB_SUCCEEDED,
        QUEUED,
        STARTING,
        TPUJobController,
    )
    from kubeflow_tpu.parallel import MeshSpec
    from kubeflow_tpu.runtime.hfta import FusedTrainer, MemberSpec
    from kubeflow_tpu.runtime.metrics import MetricsLogger
    from kubeflow_tpu.runtime.prom import (
        REGISTRY,
        parse_metrics,
        sample_value,
    )
    from kubeflow_tpu.scheduler import (
        LABEL_FUSE_FAMILY,
        LABEL_PRIORITY,
        LABEL_TENANT,
        ClusterScheduler,
        PreemptionConfig,
        SchedulerConfig,
    )
    from kubeflow_tpu.testing import faults
    from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver

    def metric(parsed, name, **labels):
        return sample_value(parsed, name, **labels) or 0.0

    def make_cr(name, tenant, priority="low", family="sweep"):
        job = crd.TPUJobSpec(name=name, namespace=namespace,
                             slice_type="v5e-8", num_slices=1)
        cr = job.to_custom_resource()
        cr["metadata"]["labels"] = {LABEL_TENANT: tenant,
                                    LABEL_PRIORITY: priority,
                                    LABEL_FUSE_FAMILY: family}
        return cr

    before = parse_metrics(REGISTRY.render())
    apiserver = None
    with faults.injected("seed=20260807") as inj, \
            tempfile.TemporaryDirectory() as tmp:
        try:
            apiserver, _, store = make_fake_apiserver()
            kube = HttpKube(
                base_url=f"http://127.0.0.1:"
                         f"{apiserver.server_address[1]}")
            gang = GangScheduler({"v5e-8": 1})
            cluster = ClusterScheduler(gang, SchedulerConfig(
                quotas={"tenant-a": {"v5e-8": 4},
                        "tenant-b": {"v5e-8": 4}},
                preemption=PreemptionConfig(grace_period_s=30.0)))
            ctl = TPUJobController(kube, gang, cluster)

            def statuses():
                return {c["metadata"]["name"]: (c.get("status") or {})
                        for c in kube.list_custom(namespace)}

            # -- 1. two tenants' singletons fold into one gang --------
            for i in range(4):
                kube.create_custom(make_cr(
                    f"m{i}", tenant=f"tenant-{'ab'[i % 2]}"))
            ctl.reconcile_all()
            st = statuses()
            gkey = f"fused:{namespace}/sweep"
            for i in range(4):
                assert st[f"m{i}"]["phase"] == STARTING, st
                assert st[f"m{i}"]["fusedGang"] == gkey, st
            assert gang.admitted(gkey)
            assert kube.list_pods(
                namespace,
                labels={"kubeflow-tpu.org/job-name": "fused-sweep"}), \
                "fused gang must run ONE shared pod gang"
            # Fair share: each tenant is billed its members' slice
            # share (2 x 2 chips), inside a quota an 8-chip singleton
            # would blow on its own.
            quotas = {q["tenant"]: q["used_chips"]
                      for q in cluster.status()["quotas"]}
            assert quotas == {"tenant-a": 4.0, "tenant-b": 4.0}, quotas
            rows = {r["job"]: r for r in cluster.status()["jobs"]}
            assert rows[f"{namespace}/m0"]["members"] == 4
            assert rows[f"{namespace}/m0"]["chips"] == 2.0
            parsed = parse_metrics(REGISTRY.render())
            assert sample_value(
                parsed, "kft_scheduler_fused_gangs") == 1.0
            assert sample_value(
                parsed, "kft_scheduler_fused_members") == 4.0

            # -- 2. preempt the gang; every member resumes ------------
            # vip rides an unquoted tenant — the point is priority
            # eviction, not quota.
            kube.create_custom(make_cr("vip", tenant="prod",
                                       priority="high", family=""))
            ctl.reconcile_all()
            st = statuses()
            for i in range(4):
                assert st[f"m{i}"]["phase"] == JOB_PREEMPTING, st
                assert st[f"m{i}"]["resumable"] is True
            inj.advance_clock(31)   # grace elapses, no wall waiting
            ctl.reconcile_all()
            st = statuses()
            for i in range(4):
                assert st[f"m{i}"]["phase"] == QUEUED, st
                assert st[f"m{i}"]["reason"] == "PreemptedRequeued"
                assert not st[f"m{i}"].get("fusedGang"), st
            assert not gang.admitted(gkey)
            ctl.reconcile_all()
            assert statuses()["vip"]["phase"] == STARTING
            for p in kube.list_pods(
                    namespace,
                    labels={"kubeflow-tpu.org/job-name": "vip"}):
                store.set_pod_phase(namespace, p["metadata"]["name"],
                                    "Succeeded")
            ctl.reconcile_all()
            ctl.reconcile_all()
            st = statuses()
            assert st["vip"]["phase"] == JOB_SUCCEEDED
            for i in range(4):
                assert st[f"m{i}"]["phase"] == STARTING, st
                assert int(st[f"m{i}"]["preemptions"]) == 1
            assert gang.admitted(gkey)
            assert cluster.status()["counters"]["resumed"] == 4

            # -- 3. one pod-gang success completes every member -------
            for p in kube.list_pods(
                    namespace,
                    labels={"kubeflow-tpu.org/job-name": "fused-sweep"}):
                store.set_pod_phase(namespace, p["metadata"]["name"],
                                    "Succeeded")
            ctl.reconcile_all()
            st = statuses()
            for i in range(4):
                assert st[f"m{i}"]["phase"] == JOB_SUCCEEDED, st
            assert not gang.admitted(gkey)
            completed = [e for e in store.events
                         if e["reason"] == "FusedMemberCompleted"]
            assert len(completed) == 4, store.events

            # -- 4. the members' TRAINING side of that lifecycle ------
            mesh = MeshSpec(data=-1).build()
            cfg = TransformerConfig(
                vocab_size=64, d_model=16, n_layers=1, n_heads=2,
                n_kv_heads=2, d_ff=32, head_dim=8, max_seq_len=16,
                dtype="float32")
            init_fn, loss_fn = lm_task(cfg, mesh=mesh)
            batch = 2 * jax.device_count()
            members = [MemberSpec(name=f"m{i}", seed=i,
                                  lr=1e-3 * (i + 1),
                                  tenant=f"tenant-{'ab'[i % 2]}",
                                  stop_step=(2 if i == 1 else None))
                       for i in range(4)]

            def data_factory():
                rng = np.random.RandomState(0)
                while True:
                    yield {"tokens": rng.randint(
                        0, cfg.vocab_size,
                        size=(batch, 16)).astype(np.int32)}

            def fused_trainer(ckpt=None):
                return FusedTrainer(
                    init_fn=init_fn, loss_fn=loss_fn, members=members,
                    mesh=mesh, checkpoint_dir=ckpt, checkpoint_every=1,
                    metrics=MetricsLogger(stream=open("/dev/null",
                                                      "w")))

            def member_leaves(ft, state, i):
                return [np.asarray(x) for x in
                        jax.tree_util.tree_leaves(
                            ft.member_state(state, i).params)]

            control = fused_trainer()
            s_control = control.fit(data_factory(), 6, log_every=0)
            # Kill after 3 steps; m1 froze at its stop_step before the
            # kill, so the resume must re-enter it MASKED.
            victim = fused_trainer(ckpt=f"{tmp}/fused")
            s_victim = victim.fit(data_factory(), 3, log_every=0)
            cut = [int(victim.member_state(s_victim, i).step)
                   for i in range(4)]
            assert cut == [3, 2, 3, 3], cut
            resumed = fused_trainer(ckpt=f"{tmp}/fused")
            s_resumed = resumed.fit(data_factory(), 6, log_every=0)
            steps = [int(resumed.member_state(s_resumed, i).step)
                     for i in range(4)]
            assert steps == [6, 2, 6, 6], steps
            assert all(a >= b for a, b in zip(steps, cut)), (steps, cut)
            for i in range(4):
                got = member_leaves(resumed, s_resumed, i)
                want = member_leaves(control, s_control, i)
                assert len(got) == len(want)
                for g, w in zip(got, want):
                    assert np.array_equal(g, w), \
                        f"member {i} diverged across preempt/resume"

            # -- 5. fused-fit observability ---------------------------
            parsed = parse_metrics(REGISTRY.render())
            assert metric(parsed, "kft_train_member_steps_total",
                          member="m0") \
                - metric(before, "kft_train_member_steps_total",
                         member="m0") >= 6
            # Every member either completed num_steps or early-stopped
            # — the active gauge must read 0 after the final fit.
            assert sample_value(
                parsed, "kft_train_members_active") == 0.0
        finally:
            if apiserver is not None:
                apiserver.shutdown()
                apiserver.server_close()


def colocation_smoke(namespace: str = "kubeflow-test") -> None:
    """Hermetic train/serve colocation scenario (§5.13): ONE chip pool
    under the shared arbiter, driven through the fake apiserver (real
    sockets, HttpKube) by the REAL fleet Autoscaler in claims mode:

      1. trough — zero serving load with min_replicas=0 makes no
         claim; training owns the whole pool;
      2. burst — scraped load spikes, the autoscaler writes a
         2-replica claim CR (never spec.replicas), the arbiter evicts
         the low-priority training gang on the SHORT serving grace
         while prepull pods pin to the victim's exact nodes, and the
         reconciler patches the Deployment only on grant;
      3. the victim checkpoints inside the grace window and — after
         the evening trough releases the claim (CR deleted,
         Deployment zeroed, stale sweep frees the gang claim) — is
         backfilled and resumes bit-identical from its latest
         verified step, restart budget untouched;
      4. the combined-pool snapshot rides the claim status back to
         the ServingClaimClient (the fleet-status footer's data) and
         every transition lands in kft_* metric deltas.
    """
    import tempfile

    import numpy as np

    from kubeflow_tpu.fleet.autoscaler import Autoscaler
    from kubeflow_tpu.operator import crd
    from kubeflow_tpu.operator.gang import GangScheduler
    from kubeflow_tpu.operator.kube_http import HttpKube
    from kubeflow_tpu.operator.reconciler import (
        JOB_PREEMPTING,
        JOB_RUNNING,
        QUEUED,
        STARTING,
        TPUJobController,
    )
    from kubeflow_tpu.runtime.checkpoint import CheckpointManager
    from kubeflow_tpu.runtime.prom import (
        REGISTRY,
        parse_metrics,
        sample_value,
    )
    from kubeflow_tpu.scheduler import (
        LABEL_PRIORITY,
        LABEL_TENANT,
        ClusterScheduler,
        PreemptionConfig,
        SchedulerConfig,
        colocate,
    )
    from kubeflow_tpu.testing import faults
    from kubeflow_tpu.testing.fake_apiserver import make_fake_apiserver

    class ScrapedLoad:
        """Registry stand-in: the diurnal curve the test scripts."""

        def __init__(self):
            self.load = 0.0

        def total_load(self):
            return self.load

        def ready_count(self):
            return 1

    def make_train_cr(name, priority, n=1):
        job = crd.TPUJobSpec(name=name, namespace=namespace,
                             num_slices=n)
        cr = job.to_custom_resource()
        cr["metadata"]["labels"] = {LABEL_TENANT: "research",
                                    LABEL_PRIORITY: priority}
        return cr

    apiserver = None
    with faults.injected("seed=20260807") as inj, \
            tempfile.TemporaryDirectory() as tmp:
        try:
            apiserver, _, store = make_fake_apiserver()
            kube = HttpKube(
                base_url=f"http://127.0.0.1:"
                         f"{apiserver.server_address[1]}")
            gang = GangScheduler({"v5e-8": 4})
            cluster = ClusterScheduler(gang, SchedulerConfig(
                preemption=PreemptionConfig(
                    grace_period_s=30.0,
                    serving_grace_period_s=5.0)))
            ctl = TPUJobController(kube, gang, cluster)
            store.create_deployment({
                "metadata": {"name": "lm", "namespace": namespace},
                "spec": {"replicas": 0}})
            load = ScrapedLoad()
            claims = colocate.ServingClaimClient(kube, namespace, "lm")
            scaler = Autoscaler(
                kube, namespace, "lm", load,
                target_inflight_per_replica=4.0,
                min_replicas=0, max_replicas=4,
                scale_up_cooldown_s=10.0,
                scale_down_cooldown_s=60.0,
                claims=claims)

            def statuses():
                return {c["metadata"]["name"]: (c.get("status") or {})
                        for c in kube.list_custom(namespace)}

            # -- 1. overnight trough: training owns the pool ----------
            out = scaler.reconcile_once()
            assert out["desired"] == 0
            assert out["claim"]["state"] == "released"
            kube.create_custom(make_train_cr("night-batch", "low", n=2))
            kube.create_custom(make_train_cr("steady", "normal", n=2))
            ctl.reconcile_all()
            st = statuses()
            assert st["night-batch"]["phase"] == STARTING, st
            assert st["steady"]["phase"] == STARTING, st
            pool = cluster.pool_status()
            assert pool["free_chips"] == 0
            assert pool["training_chips"] == pool["capacity_chips"]
            # The victim's trainer checkpoints through step 4.
            base = np.arange(8, dtype=np.float32)
            with CheckpointManager(f"{tmp}/night-ckpt",
                                   save_interval_steps=1) as mgr:
                for step in range(5):
                    mgr.save(step,
                             {"step": np.full((), step, np.int32),
                              "w": base + step})
            for i, p in enumerate(kube.list_pods(
                    namespace,
                    labels={"kubeflow-tpu.org/job-name":
                            "night-batch"})):
                store.set_pod_node(namespace, p["metadata"]["name"],
                                   f"node-{i}")

            # -- 2. morning burst: claim steals chips -----------------
            load.load = 8.0   # ceil(8/4) = 2 replicas wanted
            out = scaler.reconcile_once()
            assert out["applied"] and out["desired"] == 2
            assert out["claim"]["state"] == "pending"
            # Desire rode the claim CR; replicas are still 0.
            assert kube.get_deployment(
                namespace, "lm")["spec"]["replicas"] == 0
            ctl.reconcile_all()
            st = statuses()
            # Lowest-priority 2-slice gang drains; high-priority claim
            # outranks it on the shared pool.
            assert st["night-batch"]["phase"] == JOB_PREEMPTING, st
            assert st["night-batch"]["resumable"] is True
            assert st["steady"]["phase"] == STARTING, st
            # Speculative placement: prepull pods pin the EXACT nodes
            # the plan predicts will free, during the drain.
            prepulls = kube.list_pods(
                namespace,
                labels={colocate.LABEL_WORKLOAD:
                        colocate.WORKLOAD_PREPULL})
            assert sorted(
                p["spec"]["nodeName"] for p in prepulls) == \
                ["node-0", "node-1"], prepulls
            # SHORT serving grace: 6 s ends the drain (the 30 s
            # training grace would still be holding it).
            inj.advance_clock(6)
            ctl.reconcile_all()
            st = statuses()
            assert st["night-batch"]["phase"] == QUEUED
            assert st["night-batch"]["reason"] == "PreemptedRequeued"
            ctl.reconcile_all()
            ctl.reconcile_all()
            st = statuses()
            assert st["serving-lm"]["phase"] == JOB_RUNNING, st
            assert st["serving-lm"]["grantedReplicas"] == 2
            # The RECONCILER patched replicas on grant.
            assert kube.get_deployment(
                namespace, "lm")["spec"]["replicas"] == 2
            inj.advance_clock(11)
            out = scaler.reconcile_once()
            assert out["claim"]["state"] == "granted"
            # Combined-pool snapshot rode the claim status back to the
            # client (the `fleet status` footer's data source).
            pool = claims.pool()
            assert pool is not None
            assert pool["serving_chips"] == 16
            assert pool["used_chips"] == pool["capacity_chips"]
            # Prepull warmers retire once the claim is fully granted.
            ctl.reconcile_all()
            assert kube.list_pods(
                namespace,
                labels={colocate.LABEL_WORKLOAD:
                        colocate.WORKLOAD_PREPULL}) == []

            # -- 3. evening trough: release, backfill, resume ---------
            load.load = 0.0
            inj.advance_clock(120)   # past the scale-down cooldown
            out = scaler.reconcile_once()
            assert out["desired"] == 0
            assert out["claim"]["state"] == "released"
            assert kube.get_deployment(
                namespace, "lm")["spec"]["replicas"] == 0
            ctl.reconcile_all()   # stale sweep frees the gang claim
            ctl.reconcile_all()   # backfill re-admits the victim
            st = statuses()
            assert "serving-lm" not in st
            assert st["night-batch"]["phase"] == STARTING, st
            assert st["night-batch"]["resumable"] is False
            assert int(st["night-batch"]["preemptions"]) == 1
            assert int(st["night-batch"].get("restarts", 0)) == 0, \
                "eviction must not consume the restart budget"
            # Bit-identical resume from the verified checkpoint.
            fresh = {"step": np.zeros((), np.int32),
                     "w": np.zeros(8, np.float32)}
            with CheckpointManager(f"{tmp}/night-ckpt") as mgr2:
                restored, start = mgr2.restore_or_init(fresh)
            assert start == 5, f"resume restarted at {start}"
            np.testing.assert_allclose(restored["w"], base + 4)

            # -- 4. every transition is scrapeable --------------------
            parsed = parse_metrics(REGISTRY.render())
            assert (sample_value(
                parsed,
                "kft_scheduler_colocation_preemptions_total") or 0) \
                >= 1
            assert (sample_value(
                parsed, "kft_autoscaler_claim_granted_total",
                deployment="lm") or 0) >= 1
            assert (sample_value(
                parsed, "kft_scheduler_resumes_total",
                tenant="research") or 0) >= 1
            claims.close()
            parsed = parse_metrics(REGISTRY.render())
            assert not any(
                v for _, v in parsed.get(
                    "kft_scheduler_serving_claim_chips", [])), \
                "claim gauge must read 0 after close()"
        finally:
            if apiserver is not None:
                apiserver.shutdown()
                apiserver.server_close()


def _kubectl(args, *, input_text: str = None, timeout: int = 300) -> str:
    import subprocess

    proc = subprocess.run(
        ["kubectl"] + args, input=input_text, text=True,
        capture_output=True, timeout=timeout,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"kubectl {' '.join(args)} failed: {proc.stderr[-2000:]}")
    return proc.stdout


def deploy_real(namespace: str = "kubeflow-test") -> None:
    """Deploy the platform to the CURRENT kubectl context and verify it
    comes up — the reference's center-of-gravity E2E
    (testing/test_deploy.py:160-190 deploy-then-verify; cluster may be
    kind/minikube/GKE, exactly as prow_config.yaml parameterised it).

    Renders the platform through the same registry path a user drives,
    applies it, then waits for every Deployment to roll out within the
    reference's 10-minute readiness budget (test_deploy.py:188-189).
    KFT_E2E_DEPLOY selects the prototypes (comma-separated; default the
    full kubeflow-core — clusters that can only pull a subset of images,
    e.g. kind with locally built ones, set e.g. `tpujob-operator`).
    """
    import os

    import kubeflow_tpu.manifests  # noqa: F401 — registers prototypes
    from kubeflow_tpu.config.registry import App
    from kubeflow_tpu.manifests.base import to_yaml

    app = App()
    prototypes = os.environ.get("KFT_E2E_DEPLOY", "kubeflow-core")
    for i, proto in enumerate(p.strip() for p in prototypes.split(",")):
        app.add(proto, f"c{i}-{proto}", namespace=namespace)
    objects = app.render()
    _kubectl(["create", "namespace", namespace,
              "--dry-run=client", "-o", "yaml"])  # validates kubectl works
    try:
        _kubectl(["create", "namespace", namespace])
    except RuntimeError:
        pass  # already exists
    _kubectl(["apply", "-n", namespace, "-f", "-"],
             input_text=to_yaml(objects))
    deployments = [o["metadata"]["name"] for o in objects
                   if o["kind"] == "Deployment"]
    for name in deployments:
        _kubectl(["rollout", "status", f"deployment/{name}",
                  "-n", namespace, "--timeout=600s"], timeout=650)


def deploy_crds(namespace: str = "kubeflow-test") -> None:
    """Apply only the CRDs (+ namespace) to the current context.

    The control-plane-only footing for clusters that cannot pull the
    platform images (ephemeral kind, ci/run_e2e_kind.sh): the operator
    then runs as a host process against the cluster, so exactly one
    reconciler owns the CRs."""
    import kubeflow_tpu.manifests  # noqa: F401
    from kubeflow_tpu.config.registry import default_registry
    from kubeflow_tpu.manifests.base import to_yaml

    objs = default_registry.generate("tpujob-operator", "op",
                                     namespace=namespace)
    crds = [o for o in objs if o["kind"] == "CustomResourceDefinition"]
    try:
        _kubectl(["create", "namespace", namespace])
    except RuntimeError:
        pass  # already exists
    _kubectl(["apply", "-f", "-"], input_text=to_yaml(crds))


def tpujob_real(namespace: str = "kubeflow-test") -> None:
    """Submit the tpu-job-simple example to the real cluster and poll the
    CR until the operator reports a terminal phase (the simple_tfjob
    check, workflows.libsonnet:398-411, against a live control plane)."""
    import json
    import os

    import kubeflow_tpu.manifests  # noqa: F401
    from kubeflow_tpu.config.registry import default_registry
    from kubeflow_tpu.manifests.base import to_yaml

    objs = default_registry.generate(
        "tpu-job-simple", "e2e-smoke", namespace=namespace,
        slice_type=os.environ.get("KFT_E2E_SLICE", "v5e-1"))
    _kubectl(["apply", "-n", namespace, "-f", "-"],
             input_text=to_yaml(objs))
    deadline = time.time() + 600
    phase = ""
    while time.time() < deadline:
        out = _kubectl(["get", "tpujobs.kubeflow-tpu.org", "e2e-smoke",
                        "-n", namespace, "-o", "json"])
        phase = json.loads(out).get("status", {}).get("phase", "")
        if phase in ("Succeeded", "Failed"):
            break
        time.sleep(5)
    assert phase == "Succeeded", f"TPUJob ended in phase {phase!r}"


def teardown(namespace: str = "kubeflow-test") -> None:
    """Hermetic backend has nothing persistent; real clusters delete the
    test namespace (the reference's teardown subcommand,
    test_deploy.py:520-626)."""
    try:
        _kubectl(["delete", "namespace", namespace, "--ignore-not-found"],
                 timeout=600)
    except (RuntimeError, FileNotFoundError):
        pass  # no cluster in hermetic runs — nothing to tear down


COMMANDS = {
    "tpujob": tpujob_smoke,
    "serving": serving_smoke,
    "engine": engine_smoke,
    "faults": fault_injection_smoke,
    "fleet": fleet_smoke,
    "survivable": survivable_smoke,
    "kv_spill": kv_spill_smoke,
    "multichip_serving": multichip_serving_smoke,
    "adapter_serving": adapter_serving_smoke,
    "scheduler": scheduler_smoke,
    "train": train_smoke,
    "train_resilience": train_resilience_smoke,
    "hfta": hfta_smoke,
    "colocation": colocation_smoke,
    "deploy": deploy_real,
    "deploy-crds": deploy_crds,
    "tpujob-real": tpujob_real,
    "teardown": teardown,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="kubeflow-tpu-e2e")
    ap.add_argument("command", choices=sorted(COMMANDS))
    ap.add_argument("--namespace", default="kubeflow-test")
    ap.add_argument("--artifacts-dir", default="/tmp/artifacts")
    args = ap.parse_args(argv)

    suite = JUnitSuite(args.command)
    suite.run(args.command, lambda: COMMANDS[args.command](args.namespace))
    path = suite.write(args.artifacts_dir)
    print(f"junit: {path}", file=sys.stderr)
    return 0 if suite.ok else 1


if __name__ == "__main__":
    sys.exit(main())
