"""E2E workflow DAG builder — heir of the reference's Argo test pipeline
(testing/workflows/components/workflows.libsonnet:174-310, SURVEY.md §3.6).

Generates an Argo Workflow with the same structural ideas: a checkout
step, platform deploy, a fan-out of test steps, an onExit teardown
handler that copies JUnit artifacts — targeting the argo component the
addons package deploys (manifests/addons.py).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class Step:
    name: str
    command: List[str]
    image: str = "ghcr.io/kubeflow-tpu/worker:latest"
    deps: List[str] = dataclasses.field(default_factory=list)
    env: Dict[str, str] = dataclasses.field(default_factory=dict)


class E2EWorkflow:
    """Build an Argo Workflow CR for a platform E2E run.

    The canonical DAG (mirroring §3.6's shape, minus the minikube fork —
    the fake-slice backend replaced rented clusters for correctness
    tests; this DAG is the real-cluster smoke path):

        checkout -> deploy-kubeflow -> {tpujob-test, serving-test,
        notebook-test} -> (onExit) teardown + copy-artifacts
    """

    def __init__(self, name: str, namespace: str = "kubeflow-test",
                 artifacts_gcs: str = ""):
        self.name = name
        self.namespace = namespace
        self.artifacts_gcs = artifacts_gcs
        self.steps: List[Step] = []
        self.exit_steps: List[Step] = []

    def add_step(self, step: Step) -> "E2EWorkflow":
        self.steps.append(step)
        return self

    def add_exit_step(self, step: Step) -> "E2EWorkflow":
        self.exit_steps.append(step)
        return self

    def _template(self, step: Step) -> dict:
        container = {
            "image": step.image,
            "command": step.command,
        }
        if step.env:
            container["env"] = [
                {"name": k, "value": v} for k, v in sorted(step.env.items())
            ]
        return {"name": step.name, "container": container}

    def to_custom_resource(self) -> dict:
        dag_tasks = [
            {
                "name": s.name,
                "template": s.name,
                **({"dependencies": s.deps} if s.deps else {}),
            }
            for s in self.steps
        ]
        templates = [
            {"name": "main", "dag": {"tasks": dag_tasks}},
            *[self._template(s) for s in self.steps],
        ]
        spec = {
            "entrypoint": "main",
            "templates": templates,
        }
        if self.exit_steps:
            spec["onExit"] = "exit-handler"
            templates.append({
                "name": "exit-handler",
                "steps": [[{"name": s.name, "template": s.name}]
                          for s in self.exit_steps],
            })
            templates.extend(self._template(s) for s in self.exit_steps)
        return {
            "apiVersion": "argoproj.io/v1alpha1",
            "kind": "Workflow",
            "metadata": {"generateName": f"{self.name}-",
                         "namespace": self.namespace},
            "spec": spec,
        }


def default_e2e(name: str = "e2e", namespace: str = "kubeflow-test",
                image: str = "ghcr.io/kubeflow-tpu/worker:latest",
                repo: str = "https://github.com/kubeflow-tpu/kubeflow-tpu",
                artifacts_gcs: str = "") -> E2EWorkflow:
    """The stock platform E2E DAG."""
    wf = E2EWorkflow(name, namespace, artifacts_gcs)
    wf.add_step(Step(
        "checkout", ["git", "clone", repo, "/src"], image=image))
    wf.add_step(Step(
        "deploy-kubeflow",
        ["python", "-m", "kubeflow_tpu.testing.e2e", "deploy",
         "--namespace", namespace],
        image=image, deps=["checkout"]))
    wf.add_step(Step(
        "tpujob-test",
        ["python", "-m", "kubeflow_tpu.testing.e2e", "tpujob",
         "--namespace", namespace],
        image=image, deps=["deploy-kubeflow"]))
    wf.add_step(Step(
        "serving-test",
        ["python", "-m", "kubeflow_tpu.testing.e2e", "serving",
         "--namespace", namespace],
        image=image, deps=["deploy-kubeflow"]))
    wf.add_exit_step(Step(
        "teardown",
        ["python", "-m", "kubeflow_tpu.testing.e2e", "teardown",
         "--namespace", namespace],
        image=image))
    if artifacts_gcs:
        wf.add_exit_step(Step(
            "copy-artifacts",
            ["gsutil", "-m", "cp", "-r", "/artifacts",
             artifacts_gcs], image=image))
    return wf


# Per-platform default step lists (ci/e2e_config.yaml's `steps:` values
# resolve to kubeflow_tpu.testing.e2e subcommands).
PLATFORM_STEPS = {
    "hermetic": ["tpujob", "scheduler", "serving", "engine", "faults",
                 "fleet", "survivable", "kv_spill", "multichip_serving",
                 "adapter_serving", "train", "train_resilience",
                 "hfta", "colocation"],
    "kind": ["deploy-crds", "tpujob-real"],
    "gke": ["deploy", "tpujob-real"],
}


def platform_e2e(platform: str, steps: Optional[List[str]] = None,
                 name: str = "", namespace: str = "kubeflow-test",
                 image: str = "ghcr.io/kubeflow-tpu/worker:latest",
                 artifacts_gcs: str = "") -> E2EWorkflow:
    """Render the DAG for one ci/e2e_config.yaml entry (the heir of the
    reference's per-platform workflow params, prow_config.yaml:3-15)."""
    if platform not in PLATFORM_STEPS:
        raise ValueError(
            f"unknown platform {platform!r}; known: {sorted(PLATFORM_STEPS)}")
    steps = steps or PLATFORM_STEPS[platform]
    wf = E2EWorkflow(name or f"e2e-{platform}", namespace, artifacts_gcs)
    wf.add_step(Step("checkout", ["git", "clone",
                                 "https://github.com/kubeflow-tpu/"
                                 "kubeflow-tpu", "/src"], image=image))
    prev = "checkout"
    for step_name in steps:
        wf.add_step(Step(
            step_name,
            ["python", "-m", "kubeflow_tpu.testing.e2e", step_name,
             "--namespace", namespace],
            image=image, deps=[prev]))
        prev = step_name
    wf.add_exit_step(Step(
        "teardown",
        ["python", "-m", "kubeflow_tpu.testing.e2e", "teardown",
         "--namespace", namespace], image=image))
    if artifacts_gcs:
        wf.add_exit_step(Step(
            "copy-artifacts",
            ["gsutil", "-m", "cp", "-r", "/artifacts", artifacts_gcs],
            image=image))
    return wf


def main(argv=None) -> int:
    """`python -m kubeflow_tpu.testing.workflow --platform=gke` prints the
    Argo Workflow JSON for a CI trigger to submit."""
    import argparse
    import json
    import sys

    ap = argparse.ArgumentParser(prog="kubeflow-tpu-workflow")
    ap.add_argument("--platform", default="hermetic",
                    choices=sorted(PLATFORM_STEPS))
    ap.add_argument("--steps", default="",
                    help="comma-separated e2e subcommands (default: the "
                         "platform's list)")
    ap.add_argument("--name", default="")
    ap.add_argument("--namespace", default="kubeflow-test")
    ap.add_argument("--artifacts-gcs", default="")
    args = ap.parse_args(argv)
    steps = [s.strip() for s in args.steps.split(",") if s.strip()] or None
    wf = platform_e2e(args.platform, steps, name=args.name,
                      namespace=args.namespace,
                      artifacts_gcs=args.artifacts_gcs)
    json.dump(wf.to_custom_resource(), sys.stdout, indent=2)
    print()
    return 0


if __name__ == "__main__":
    import sys

    sys.exit(main())
